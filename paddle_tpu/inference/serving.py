"""Continuous-batching LLM serving engine over the paged KV cache.

The serving role PaddleNLP's ``llm/predict/predictor.py`` + a request
scheduler play over AnalysisPredictor, rebuilt TPU-first for the
compiler's static-shape world (arxiv 2603.09555) with the block-table
paged KV layout of *Ragged Paged Attention* (arxiv 2604.15464):

- **Fixed slots, one compiled decode step.** The engine owns
  ``num_slots`` serving slots. Every decode step runs ALL slots through
  one batched model call — token ids [S, 1], block tables [S, MB],
  per-slot lengths [S] — whose shapes never change, so the step is
  AOT-compiled exactly once and steady state runs ZERO recompiles
  (assert via the ``serving_decode_compiles`` / ``serving_decode_steps``
  monitor counters). Raggedness lives in the table/length VALUES.
- **Paged KV.** All slots share one block pool per layer
  (``ops/paged_cache.py``); the host-side ``BlockAllocator`` hands
  blocks to admitted requests and reclaims them at retirement, so HBM
  scales with live tokens, not ``slots x max_len``.
- **Continuous batching.** ``step()`` admits queued requests into freed
  slots, decodes one token for every active slot, streams tokens out,
  and retires slots on EOS/max-len — freed blocks and slots are reused
  by the next admission without ever draining the batch.
- **Chunked prefill — ONE executable.** Admission prefills the prompt
  in fixed-size chunks (``ServingConfig.prefill_chunk``, default 128)
  through the SAME multi-query paged path the speculative verify step
  rides (``paged_verify_attention`` with ``T = chunk``): each chunk
  writes its K/V into the slot's blocks and attends to every
  previously cached block plus its own in-chunk causal prefix. The
  chunk step is AOT-compiled ONCE per engine — ``ceil(n / C)`` chunk
  calls replace the old per-power-of-two-bucket prefill zoo, so
  ``serving_prefill_compiles`` collapses from O(#buckets) (x draft
  copies) to O(1) and no prompt pays bucket padding. Optionally the
  scheduler interleaves prefill chunks between decode steps
  (``max_prefill_chunks_per_step > 0``) to bound head-of-line latency
  for running requests. Kill switch ``PADDLE_TPU_CHUNKED_PREFILL=0``
  restores the bucketed dense prefill.
- **Prefix caching (content-addressed blocks).** The ``BlockAllocator``
  keeps per-block refcounts and a content-hash index (rolling hash
  chains over token ids, seeded by a model/config fingerprint —
  ``ops/paged_cache.chain_hashes``). Retirement publishes the retired
  sequence's FULL blocks into the index instead of dropping them; they
  park in an LRU list until memory pressure evicts them. Admission
  hashes the prompt's full blocks, maps the longest cached prefix
  straight into the slot's block table (refcount++) and chunk-prefills
  only the suffix — shared system prompts, few-shot headers and
  multi-turn history prefill once per cache lifetime, not per request.
  A shared block the suffix must write into (full-prompt hit) is
  copy-on-write duplicated first (one device block copy). Greedy
  outputs are token-exact vs the cold path. Kill switch:
  ``PADDLE_TPU_PREFIX_CACHE=0``. See docs/OPS.md "Prefix caching &
  chunked prefill".
- **Ragged decode attention** reads the pool through the Pallas kernel
  on TPU (``ops/pallas/paged_attention.py``) and the gather fallback on
  CPU, behind the models' ordinary cached-attention path — the same
  code ``generate(cache_impl="paged")`` rides.
- **Speculative decoding** (``num_speculative_tokens = gamma > 0``): a
  drafter (model-free n-gram prompt lookup, or a smaller draft model
  sharing the block tables) proposes gamma tokens per slot and ONE
  fixed-shape multi-token verify forward (the multi-query paged
  kernel) accepts 1..gamma+1 of them — still exactly one compiled
  executable in steady state, because accept/reject lives in the
  LENGTH values: rejected tokens roll back by decrementing
  ``cache_lens`` and returning overhang blocks to the allocator (no
  data movement). The scheduler reserves ``prompt + max_new + gamma``
  blocks worst-case (the speculated window may overhang the final
  token), retires EOS found anywhere inside the window, and streams
  every accepted token through the ordinary callback. Kill switch:
  ``PADDLE_TPU_SPECULATIVE=0``; capacity-routed MoE is excluded (the
  window tokens would compete for expert capacity — same reasoning as
  prompt bucketing). See docs/OPS.md "Speculative decoding".

- **Ragged mixed-batch serving — ONE executable per engine.** By
  default every engine tick runs ONE AOT-compiled ragged step
  (``_compile_ragged_step``) that consumes ALL active work as a single
  packed row buffer: decoding slots contribute 1 query row, speculative
  verify windows ``gamma + 1`` rows, and pending prefill chunks up to
  ``prefill_chunk`` rows — partitioned by per-slot ``q_lens`` and
  cumulative ``row_starts`` (*Ragged Paged Attention*, with the
  surrounding write/sample fused into the same launch per the MPK
  mega-kernelization direction). The per-width decode/verify/chunk
  executables (and the interleave scheduler that juggled them)
  collapse: steady-state executables per engine is 1 (2 with a draft
  model — its proposal scan + prefill priming fuse into one draft
  ragged step), every tick is one dispatch round-trip, and admission
  prefill overlaps running decodes for free (prefill rows ride the
  same launch — no head-of-line interleave budget needed, no NULL-row
  table dance: a pending slot simply contributes 0 decode rows).
  Greedy outputs are token-exact vs the per-width zoo (the ragged XLA
  fallback is bitwise the per-width fallback per row). Kill switch
  ``PADDLE_TPU_RAGGED_BATCH=0`` (or ``ServingConfig(
  ragged_batch=False)``) restores the per-width executables
  bit-for-bit. See docs/OPS.md "Ragged mixed-batch serving".

- **Mega-kernelized decode tick** (``ServingConfig(fused_decode=
  True)``, the default): inside every serving executable the decoder
  layers' norm -> QKV, attention-epilogue -> O-projection (+
  residual), norm -> gate/up and swiglu -> down (+ residual)
  boundaries run as fused Pallas kernels
  (``ops/pallas/decode_fused.py``) — per-layer activations stay in
  VMEM across the old kernel boundaries on TPU. Off TPU the fallback
  is bitwise the unfused graph, so fused ON==OFF is token-exact by
  construction; GSPMD TP traces keep the unfused projections. The
  sampling head's temperature/top-k/top-p ride as a per-SLOT device
  tensor (``submit()`` accepts per-request overrides), so a new
  sampling config never recompiles anything. The per-executable
  kernel census (``monitor.kernel_census`` —
  ``stats()["kernels_per_tick"]``, ``serving_kernels_per_tick``
  gauge) measures the collapse. Kill switch
  ``PADDLE_TPU_FUSED_DECODE=0``; ``=interpret`` runs the kernels
  under the Pallas interpreter on any backend. See docs/OPS.md
  "Decode-tick fusion & the in-executable sampling head".

- **Quantized KV cache** (``ServingConfig(kv_cache_dtype="int8")`` /
  env twin ``PADDLE_TPU_KV_INT8``): the block pool stores int8 K/V
  plus per-(block, position, head) absmax scales
  (``ops/paged_cache.QuantKV``) — every write path quantizes on store
  through one shared scatter, the Pallas kernels dequantize tiles in
  VMEM after the block load, and the XLA fallbacks mirror the same
  math through ``gather_dense``. Steady-state decode is HBM-bound on
  KV reads, so bytes/step halve (~0.53x pool bytes vs bf16) and
  ~2x the slots fit a fixed pool budget. Prefix caching, COW,
  speculative rollback, chunked prefill, the ragged engine and TP all
  compose (stored bytes are a pure function of the tokens; the scale
  pool shards on the same kv_head cut). Default (None) keeps the fp
  pool bit-for-bit; ``PADDLE_TPU_KV_INT8=0`` is the kill switch. See
  docs/OPS.md "KV cache quantization".

- **Tensor-parallel serving** (``ServingConfig(tp_degree=N)``): every
  serving executable — batched decode, fixed-gamma verify, fixed-chunk
  prefill, the draft loop and the ``copy_blocks`` COW — is sharded
  over a ``Mesh(devices[:N], ("mp",))`` axis (GSPMD, arxiv 2105.04663).
  The KV block pool splits on its kv_heads dim (each shard owns a
  contiguous kv_head slice of EVERY block, so the paged-attention
  grid runs unmodified on its local slice inside ``shard_map`` —
  ``ops/pallas/paged_attention.sharded_paged_attention_step``); model
  params shard column/row-wise through the models' existing ``mp``
  PartitionSpecs; block tables, ``cache_lens``, token ids and the
  sampling PRNG key are replicated. The only EXPLICIT cross-shard
  collective is one logits ``all_gather`` before sampling
  (``_gather_logits`` — census-asserted; the per-layer reduces of the
  row-parallel linears are GSPMD-inserted and proxied by the
  ``sharding_constraint`` census row), so sampling consumes the same
  replicated logits/key on every shard. Host state is untouched: ONE
  ``BlockAllocator``, one scheduler, one prefix-cache index — block
  ids are global and every shard's pool slice is indexed by the same
  tables, so prefix caching, COW, speculative rollback and chunked
  prefill all compose with TP for free. Kill switch
  ``PADDLE_TPU_SERVE_TP=0`` restores the single-device path
  bit-for-bit. See docs/OPS.md "Tensor-parallel serving".

- **Disaggregated prefill -> decode** (``ServingConfig(role=
  "prefill" | "decode" | "both")``): a role="prefill" engine runs
  admission + chunked prefill only — each completed prompt streams its
  first token, then parks for ``pop_prefilled()``, which exports the
  slot's KV blocks as a self-contained payload (ONE fixed-width
  ``ops/paged_cache.export_blocks`` executable; int8 blocks carry
  data + per-row scales) and publishes the prompt's blocks into the
  prefix index before freeing them. ``admit_prefilled()`` on any
  engine of the same model/layout imports the payload (ONE fixed-width
  scatter) and seats a decoding slot at exactly the colocated
  post-prefill state, so greedy continuation is token-exact. The
  ``EngineCluster`` (``inference/cluster.py``) orchestrates N replicas
  behind a session-affine router on top of this. See docs/OPS.md
  "Engine replication & disaggregated prefill".

Admission is worst-case reserved: a request is admitted only when the
pool can cover ``prompt + max_new`` blocks for it PLUS the outstanding
reservations of every active slot, so mid-decode pool exhaustion is
impossible by construction (no preemption path needed; a
role="prefill" engine reserves only the prompt's blocks — its decode
horizon lives on the importing replica).

Telemetry (monitor registry, exported in the JSONL dump):
``serving_slot_occupancy`` gauge, ``serving_batch_utilization`` /
``serving_queue_wait_ms`` histograms (the latter labeled by terminal
outcome: admitted | cancelled | rejected | shutdown, so pre-admission
exits leave a record too), ``serving_tokens_total`` /
``serving_decode_steps`` / ``serving_decode_compiles`` /
``serving_prefill_compiles`` / ``serving_requests_completed`` /
``serving_prefix_blocks_reused`` / ``serving_prefix_tokens_reused`` /
``serving_cow_copies`` / ``serving_cache_evictions`` counters and the
``serving_prefix_hit_rate`` gauge.

Request-lifecycle tracing + SLO digests (docs/OPS.md "Request tracing
& SLO goodput"): every engine owns a span tracer
(``monitor/tracing.py`` — one trace-viewer pid per engine, tid 0 the
engine tick timeline, tid 1+i slot i, last tid the admission queue)
recording ``submit -> queued -> admit (prefix-hit annotated) ->
prefill chunk[i] -> decode/verify tick (rows, accepted_len, exec id)
-> retired`` plus per-tick engine spans (occupancy, kernel-fallback
count) on all three step paths; ``engine.dump_trace(path)`` writes
Perfetto-loadable Chrome trace JSON. Kill switch ``PADDLE_TPU_TRACE=0``
(bit-for-bit inert: tracing is host-side only). Independent of that
switch, four always-on P² latency digests power ``stats()``'s
``ttft_ms`` / ``itl_ms`` / ``queue_wait_ms`` / ``e2e_ms`` summaries and
the ``serving_ttft_ms`` / ``serving_itl_ms`` /
``serving_queue_wait_quantile_ms`` / ``serving_e2e_ms`` p50/p95/p99
gauges.
"""
from __future__ import annotations

import contextlib
import itertools
import os
import time
import warnings
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import monitor
from ..distributed import moe as _moe
from ..monitor import health as _health
from ..monitor import tracing as _tracing
from ..monitor.digest import LatencyDigest
from ..ops import lora as _lora
from ..ops import paged_cache as _pc
from ..ops.pallas import paged_attention as _pa

__all__ = ["ServingConfig", "ServingRequest", "ServingEngine",
           "PrefilledRequest", "MigratedSession", "QueueShedError"]


class QueueShedError(RuntimeError):
    """Raised by ``submit()`` when queue-depth load shedding is armed
    (``ServingConfig.shed_queue_depth``) and the admission queue is
    already at the threshold: the request is REFUSED at the front
    door (a ``serving_queue_wait_ms{outcome="shed"}`` observation is
    the only trace it leaves) so queued work keeps its latency budget
    instead of everyone timing out together under overload."""

# trace-viewer pid per engine (and the stats() engine_id)
_ENGINE_IDS = itertools.count()


@contextlib.contextmanager
def _quiet_donation():
    """Pool donation is a TPU-side optimization (decode/prefill reuse
    the pool's HBM in place); CPU ignores donation with a warning that
    would fire every engine tick. Scoped here so other code's genuinely
    broken donations still surface."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


@dataclass
class ServingConfig:
    num_slots: int = 8                  # fixed decode batch width
    block_size: int = 16                # tokens per KV block
    max_model_len: int = 1024           # prompt + generated cap per seq
    # pool size; default covers every slot at max_model_len (admission
    # then never queues on blocks, only on slots) — shrink to trade HBM
    # for queueing
    num_blocks: Optional[int] = None
    max_new_tokens: int = 128           # per-request default
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    decode_strategy: str = "greedy_search"   # or "sampling"
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    min_prefill_bucket: int = 16        # smallest prompt bucket (legacy
    #                                     bucketed prefill only)
    # speculative decoding: draft gamma tokens per slot per step and
    # verify them in one multi-token forward (0 = off)
    num_speculative_tokens: int = 0
    drafter: str = "ngram"              # ngram | model (pass draft_model)
    #                                     | heads (tree draft heads)
    spec_ngram_max: int = 3             # longest prompt-lookup n-gram
    # tree-structured speculation (docs/OPS.md "Tree speculation"):
    # the speculated window becomes a token TREE instead of a linear
    # chain. The tuple gives each non-root node's parent index —
    # node k+1's parent is spec_tree[k], 0 = the root (this tick's
    # committed token); len(spec_tree) must equal
    # num_speculative_tokens, so the verify node budget t_q = gamma+1
    # is unchanged and the ONE ragged executable keeps zero
    # steady-state recompiles. Topology is static per engine; a chain
    # tree (0, 1, 2, ...) is bit-for-bit the linear path. Drafting:
    # drafter="ngram" fills the tree's root-to-leaf chains with the
    # top-k prompt-lookup continuations (zero extra weights);
    # drafter="heads" adds Medusa-style draft-head projections over
    # the target's final hidden state (weights ride WITH the target
    # params, so head-drafted trees serve on disaggregated clusters
    # where separate draft models cannot). Kill switch
    # PADDLE_TPU_SPEC_TREE=0 restores the linear speculative engine
    # bit-for-bit (heads engines fall back to the linear ngram
    # drafter). None = linear speculation, exactly as before.
    spec_tree: Optional[tuple] = None
    # chunked prefill: ONE fixed-chunk AOT executable processes the
    # prompt suffix in ceil(n / prefill_chunk) steps (multi-query paged
    # attention, T = chunk). False (or PADDLE_TPU_CHUNKED_PREFILL=0)
    # restores the per-bucket dense prefill.
    chunked_prefill: bool = True
    prefill_chunk: int = 128            # tokens per prefill chunk step
    # content-addressed prefix reuse over the block pool (requires
    # chunked prefill). False (or PADDLE_TPU_PREFIX_CACHE=0) disables
    # hashing/publishing — blocks free eagerly as before.
    enable_prefix_cache: bool = True
    # > 0: admission leaves prefill pending and each engine tick
    # advances at most this many chunk steps (across all pending slots)
    # before decoding — bounds head-of-line latency for running
    # requests at the cost of later first tokens. 0 = prefill whole
    # prompts at admission.
    max_prefill_chunks_per_step: int = 0
    # False: retirement drops each request's token buffer instead of
    # holding it for run() — REQUIRED for long-lived streaming
    # deployments that consume tokens via stream_callback and drive
    # step() themselves (otherwise finished results accumulate
    # unboundedly; run() then returns {}).
    retain_results: bool = True
    # ragged mixed-batch serving: ONE executable per engine consumes
    # decode rows + verify windows + prefill chunk rows as a single
    # packed ragged batch each tick. False (or
    # PADDLE_TPU_RAGGED_BATCH=0) restores the per-width
    # decode/verify/chunk executable zoo bit-for-bit.
    ragged_batch: bool = True
    # per-tick prefill row budget of the ragged step (the executable's
    # packed width is num_slots * (gamma+1) + this). None = one
    # prefill_chunk's worth; shrink to trade time-to-first-token for
    # smaller per-tick padding when slots mostly decode.
    ragged_prefill_rows: Optional[int] = None
    # tensor-parallel degree: shard every serving executable over a
    # Mesh(devices[:tp_degree], ("mp",)) axis — the KV pool splits on
    # kv_heads, params column/row-wise, tables/lengths/keys replicate,
    # one explicit logits all_gather per step. Must divide the model's
    # num_kv_heads / num_attention_heads / vocab_size (validated at
    # engine construction). Kill switch: PADDLE_TPU_SERVE_TP=0.
    tp_degree: int = 1
    # KV-pool quantization: None/'auto' = pool in the model dtype
    # (bit-for-bit the pre-quantization layout); 'int8' = quantized
    # block pool (int8 data + per-(block, position, head) f32 absmax
    # scales — ~0.53x the bf16 pool bytes, half the KV HBM stream per
    # decode step, ~2x admissible slots at a fixed pool byte budget).
    # Composes with prefix caching/COW (quantize-on-store makes cached
    # bytes a pure function of the tokens), speculative verify/
    # rollback, chunked prefill, the ragged engine and TP (the scale
    # pool shards on the same kv_head cut). Env twin
    # PADDLE_TPU_KV_INT8: 0 = kill switch (fp pool, bit-for-bit), 1 =
    # int8 when this field is left None. On TPU use block_size=32 (the
    # int8 sublane tile) to keep the Pallas kernel eligible.
    kv_cache_dtype: Optional[str] = None
    # MoE routing telemetry (serving_moe_expert_load /
    # serving_moe_routing_entropy): each sparse layer's dispatch
    # embeds one tiny host callback per executed tick. False (or
    # PADDLE_TPU_MOE_TELEMETRY=0) traces the executables without the
    # tap — zero callback cost, stats() moe_routing_entropy stays 0.0.
    moe_telemetry: bool = True
    # disaggregated-cluster role: "both" (default) serves requests end
    # to end; "prefill" runs admission + chunked prefill ONLY — a slot
    # whose prompt completes parks for ``pop_prefilled()`` handoff
    # (its first token is still streamed; its KV blocks export via
    # ``ops/paged_cache.export_blocks``) and the engine reserves only
    # the PROMPT's blocks per request (the decode horizon lives on the
    # importing replica); "decode" marks a replica that additionally
    # receives ``admit_prefilled()`` imports (any role accepts them —
    # the flag documents cluster intent and shows up in stats()).
    role: str = "both"
    # -- SLO-aware preemptive scheduling + host-DRAM KV tier ----------
    # (docs/OPS.md "Preemption & hierarchical KV offload"). True (the
    # default) arms: priority classes on submit(priority=) — highest
    # class admits first, FIFO within a class; a WATERMARK admission
    # policy that may overcommit the block pool (admit on
    # immediately-needed blocks + headroom instead of the worst-case
    # prompt+max_new reservation — the 1.88x int8 slot win becomes
    # usable); and preemption under slot/block pressure: the
    # lowest-priority victim slot is spilled (full blocks published
    # into the prefix index, live bytes exported to the host-DRAM
    # tier), freed, and re-enqueued at the front of its class — on
    # re-admission it either swap-restores the spilled bytes or
    # re-prefills from the published blocks (recompute-vs-swap cost
    # model), continuing token-exact vs never-preempted. False (or the
    # PADDLE_TPU_PREEMPT=0 kill switch, which beats an explicit True)
    # restores the worst-case-reservation FIFO scheduler bit-for-bit:
    # priorities are ignored, nothing spills, no host tier exists.
    # Preemption needs the chunked-prefill path (the recompute resume
    # IS a chunk prefill) and never runs on a role="prefill" engine
    # (its slots only park for handoff).
    enable_preemption: bool = True
    # watermark admission headroom in blocks: a request is admitted
    # when the worst-case reservation fits (the old policy, unchanged
    # when the pool is ample) OR when free blocks cover its immediate
    # allocation plus this headroom (overcommit — growth past it is
    # reclaimed by preemption). None = num_slots (one growth block per
    # slot of headroom).
    admission_watermark_blocks: Optional[int] = None
    # host-DRAM KV tier capacity (bytes) for spilled blocks: preempted
    # victims' live bytes and LRU-evicted published blocks park here
    # (ops/paged_cache.HostKVTier) and restore through the fixed-width
    # import executable. 0 disables the tier — victims always resume
    # by recompute, evicted cached blocks just die (pre-tier
    # behavior).
    host_kv_tier_bytes: int = 64 << 20
    # resume path for preempted victims: "auto" picks per victim from
    # the measured recompute-vs-swap cost model (chunk-prefill tok/s
    # vs host-transfer bytes/s), "swap"/"recompute" force one path
    # (tests, tuning).
    preempt_resume: str = "auto"
    # queue-depth load shedding: submit() raises QueueShedError (and
    # lands a serving_queue_wait_ms{outcome="shed"} observation) when
    # the admission queue already holds this many requests. None = off.
    shed_queue_depth: Optional[int] = None
    # default per-request queue-wait budget: a request still queued
    # after this many ms exits with outcome="timeout" (empty result,
    # stream never starts). None = unbounded; submit(max_queue_wait_ms=)
    # overrides per request.
    max_queue_wait_ms: Optional[float] = None
    # mega-kernelized decode tick (ops/pallas/decode_fused.py): fuse
    # RMSNorm/LayerNorm into the QKV projection prologue, the
    # attention epilogue into the O-projection + residual add, and the
    # MLP's norm/swiglu boundaries, inside every serving executable —
    # per-layer activations stay in VMEM across the kernel boundaries
    # on TPU. Off-TPU the fallback is bitwise the unfused graph, so
    # this flag is numerics-free on CPU. Kill switch
    # PADDLE_TPU_FUSED_DECODE=0 (beats an explicit True);
    # PADDLE_TPU_FUSED_DECODE=interpret runs the fused kernels under
    # the Pallas interpreter on any backend (tests/bench). GSPMD TP
    # engines keep the unfused projections (an opaque pallas_call
    # cannot be partitioned).
    fused_decode: bool = True
    # fleet health engine (monitor/health.py): SLO burn-rate monitors,
    # anomaly detectors, a stuck-tick watchdog, and incident
    # auto-capture over signals the engine already produces. Pure host
    # code: under PADDLE_TPU_HEALTH=0 (beats an explicit True) the
    # monitor is never constructed and tokens + executables_compiled
    # stay bit-for-bit identical.
    health: bool = True
    # per-request SLO for burn-rate attainment (ms); a request misses
    # its SLO when TTFT exceeds health_slo_ttft_ms or any inter-token
    # latency exceeds health_slo_itl_ms.
    health_slo_ttft_ms: float = 2000.0
    health_slo_itl_ms: float = 500.0
    # SLO target (error budget = 1 - target) and SRE fast/slow burn
    # windows: the fast alert pages only when BOTH windows burn faster
    # than health_burn_threshold x budget and the fast window holds at
    # least health_burn_min_requests retirements.
    health_slo_target: float = 0.99
    health_burn_fast_s: float = 5.0
    health_burn_slow_s: float = 60.0
    health_burn_threshold: float = 2.0
    health_burn_min_requests: int = 8
    # stuck-tick watchdog deadline: max(floor, mult x step-time EMA).
    health_watchdog_mult: float = 50.0
    health_watchdog_floor_s: float = 5.0
    # arm a ProfilerWindow for this many ticks when an alert fires
    # (0 = off; needs PADDLE_TPU_PROFILE_DIR or an explicit path to
    # land anywhere).
    health_profile_ticks: int = 0
    # -- batched multi-LoRA serving (docs/OPS.md "Multi-LoRA
    # serving"): lora_rank > 0 arms the adapter machinery —
    # engine.load_adapter() registers per-tenant A/B delta weights in
    # a host-DRAM registry, submit(adapter_id=) tags requests, and
    # every decode tick applies the per-slot deltas as ONE
    # mixed-adapter ragged grouped matmul inside the single existing
    # tick executable (adapter churn swaps stack VALUES at a fixed
    # shape — zero steady-state recompiles). Requires the ragged
    # engine. Kill switch PADDLE_TPU_LORA=0 restores the base engine
    # bit-for-bit (no extra operand, no tagged module, no extra
    # per-slot row).
    lora_rank: int = 0
    # device-resident adapter budget: this many adapters stay loaded
    # in the stacked device image at once (plus the always-present
    # null adapter); the rest of the registry spills to host DRAM and
    # LRU-swaps in on demand (refcounts pin adapters serving in-flight
    # requests, so eviction mid-request is impossible).
    max_adapters: int = 8
    # LoRA scale numerator: delta = (x @ A @ B) * lora_alpha /
    # lora_rank. None = lora_rank (scale 1.0).
    lora_alpha: Optional[float] = None
    # which projections carry deltas: "attn" = q/k/v/o (qkv/out on
    # GPT), "all" adds the MLP projections (gate/up/down, linear1/2)
    lora_targets: str = "attn"
    # int8-quantize the resident adapter stacks (per-matrix absmax
    # scales, dequantized in-trace — the PR 10 KV-pool recipe applied
    # to the delta weights; ~4x adapters per resident byte)
    lora_quant: bool = False
    # -- async tick pipeline (docs/OPS.md "Async tick pipeline") ------
    # async_depth=1 arms depth-1 dispatch-ahead on the ragged engine:
    # the tick executable additionally returns next-tick inputs
    # (per-slot sampled token, advanced lengths, a budget/EOS ``done``
    # mask) as DEVICE arrays, and on pure steady-state decode ticks
    # the engine dispatches tick N+1 from that device-resident carry
    # while tick N's tokens copy to host asynchronously — commit
    # (emit/retire/stats/tracing) lags one tick. Any slot-composition
    # event (admission, retirement, preemption, migration, handoff,
    # cancel) flushes the pipeline, so async ON == OFF stays greedy
    # token-exact. Requires the ragged engine. Env twin
    # PADDLE_TPU_ASYNC_TICK: 0 = kill switch (beats an explicit depth
    # — today's dispatch-then-block loop returns bit-for-bit, same
    # executables), 1 = depth-1 when this field is left None. Only
    # depth 1 is implemented.
    async_depth: Optional[int] = None

    def __post_init__(self):
        # reject broken degrees HERE, with a message, instead of as a
        # shape crash deep inside shard_map tracing
        tp = self.tp_degree
        if not isinstance(tp, int) or isinstance(tp, bool) or tp < 1:
            raise ValueError(
                f"tp_degree must be a positive int, got {tp!r}")
        if self.role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be both|prefill|decode, got {self.role!r}")
        if self.preempt_resume not in ("auto", "swap", "recompute"):
            raise ValueError(
                f"preempt_resume must be auto|swap|recompute, got "
                f"{self.preempt_resume!r}")
        if self.host_kv_tier_bytes < 0:
            raise ValueError(
                f"host_kv_tier_bytes must be >= 0, got "
                f"{self.host_kv_tier_bytes!r}")
        if self.shed_queue_depth is not None \
                and int(self.shed_queue_depth) < 1:
            raise ValueError(
                f"shed_queue_depth must be >= 1 (or None), got "
                f"{self.shed_queue_depth!r}")
        if not 0.0 < self.health_slo_target < 1.0:
            raise ValueError(
                f"health_slo_target must be in (0, 1), got "
                f"{self.health_slo_target!r}")
        if not 0.0 < self.health_burn_fast_s < self.health_burn_slow_s:
            raise ValueError(
                f"need 0 < health_burn_fast_s < health_burn_slow_s, got "
                f"{self.health_burn_fast_s!r}, {self.health_burn_slow_s!r}")
        if self.health_watchdog_floor_s <= 0:
            raise ValueError(
                f"health_watchdog_floor_s must be > 0, got "
                f"{self.health_watchdog_floor_s!r}")
        if self.health_watchdog_mult < 1.0:
            raise ValueError(
                f"health_watchdog_mult must be >= 1, got "
                f"{self.health_watchdog_mult!r}")
        ad = self.async_depth
        if ad is not None and (not isinstance(ad, int)
                               or isinstance(ad, bool)
                               or ad < 0 or ad > 1):
            raise ValueError(
                f"async_depth must be 0, 1 or None, got {ad!r}")
        lr = self.lora_rank
        if not isinstance(lr, int) or isinstance(lr, bool) or lr < 0:
            raise ValueError(
                f"lora_rank must be an int >= 0, got {lr!r}")
        if lr > 0:
            if int(self.max_adapters) < 1:
                raise ValueError(
                    f"max_adapters must be >= 1, got "
                    f"{self.max_adapters!r}")
            if self.lora_targets not in ("attn", "all"):
                raise ValueError(
                    f"lora_targets must be 'attn' or 'all', got "
                    f"{self.lora_targets!r}")
            if self.lora_alpha is not None \
                    and float(self.lora_alpha) <= 0.0:
                raise ValueError(
                    f"lora_alpha must be > 0 (or None), got "
                    f"{self.lora_alpha!r}")


def _num_experts(cfg) -> int:
    """Routed-expert count of a model config (0 = dense): the ONE
    probe behind the MoE admission gate, the engine's ``_moe`` flag
    and the TP divisibility check — a third MoE config field name
    lands in exactly one place."""
    return int(getattr(cfg, "num_experts", 0)
               or getattr(cfg, "n_routed_experts", 0) or 0)


@dataclass
class ServingRequest:
    request_id: int
    prompt: np.ndarray                  # [L] int32
    max_new_tokens: int
    submit_time: float = field(default_factory=time.monotonic)
    # per-request sampling overrides (None = the engine's
    # ServingConfig values); land in the engine's per-SLOT sampling
    # tensors at admission — device DATA, so a request with its own
    # knobs never recompiles anything
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    # scheduling class: higher admits first under the preemptive
    # scheduler and may preempt strictly-lower-priority victims; FIFO
    # within a class. Ignored (pure FIFO) when preemption is off.
    priority: int = 0
    # queue-wait budget (ms): still queued past it -> outcome="timeout"
    max_queue_wait_ms: Optional[float] = None
    # multi-LoRA tenant: which registered adapter's delta weights this
    # request decodes under (None = base model). Validated at submit;
    # pinned (refcounted) in the AdapterPool while the request holds a
    # slot, carried across preemption spill/resume and disaggregated
    # handoffs.
    adapter_id: Optional[int] = None
    # preemption carry-over (None for fresh requests): the victim's
    # continuation state — {"cache_len", "last_token", "n_emitted",
    # "history", "worst_blocks", "n_blocks", "nbytes", "key"} — plus
    # the resolved per-slot sampling row, so re-admission seats the
    # slot EXACTLY where the preempted one stopped
    resume: Optional[dict] = None


@dataclass
class PrefilledRequest:
    """One finished prefill, packaged for a decode replica: the prompt
    whose KV the payload holds, the first token the prefill engine
    sampled (already streamed to the client), and the exported block
    bytes (``ops/paged_cache.export_blocks`` output — a fixed-width
    ``[mb]`` gather per layer, padded entries carrying null-block
    garbage the importer routes back to its own null block). Produced
    by ``ServingEngine.pop_prefilled()`` on a role="prefill" engine,
    consumed by ``admit_prefilled()`` on any engine of the SAME model
    and serving layout (block_size / max_model_len / kv_cache_dtype)."""
    request_id: int                     # PREFILL-engine-local rid
    prompt: np.ndarray                  # [L] int32
    first_token: int
    max_new_tokens: int
    n_blocks: int                       # real (non-pad) blocks
    payload: list                       # per-layer (k_rows, v_rows)
    # the request's per-slot sampling knobs travel with the handoff
    # (the decode replica seats the slot with the SAME values the
    # prefill engine sampled the first token under)
    temperature: Optional[float] = None
    top_k: Optional[float] = None
    top_p: Optional[float] = None
    # the request's scheduling class rides the handoff so the decode
    # replica's preemptive scheduler sees the same priority the
    # prefill tier admitted under
    priority: int = 0
    # trace flow-link id (monitor/tracing.next_flow_id): the export
    # side records the flow start, the import side the finish, so a
    # merged trace draws the handoff as an arrow between the two
    # replicas' request spans. None when tracing is disabled.
    flow_id: Optional[int] = None
    # multi-LoRA tenant id: the prefill tier computed this payload's
    # KV UNDER the adapter's deltas, so the decode replica MUST seat
    # the slot under the same adapter (load_adapter() is broadcast
    # cluster-wide, so the id resolves on both sides)
    adapter_id: Optional[int] = None


@dataclass
class MigratedSession:
    """One LIVE session packaged for another replica (scale-down
    drain / cluster rebalancing): the full continuation state a
    preemption resume carries — cache position, last sampled token,
    emit count, token history, sampling row, scheduling class,
    adapter pin — PLUS the exported live KV bytes, so the importing
    engine seats a decoding slot exactly where this one stopped and
    the client's stream continues token-exact, never re-submitted.
    ``payload=None`` degrades to the recompute path: the target
    re-prefills ``history[:cache_len]`` through the ordinary chunk
    machinery and restores the continuation (token-exact either way —
    recompute IS the preemption recompute resume). Produced by
    ``ServingEngine.export_session`` / ``drain_sessions``, consumed
    by ``admit_migrated`` on any decode-capable engine of the SAME
    model and serving layout (block_size / max_model_len /
    kv_cache_dtype)."""
    request_id: int                     # SOURCE-engine-local rid
    prompt: np.ndarray                  # [L] int32 original prompt
    history: list                       # prompt + emitted tokens
    cache_len: int                      # valid cache positions
    last_token: int                     # sampled, not yet in cache
    n_emitted: int                      # tokens already streamed
    max_new_tokens: int
    worst_blocks: int                   # admission reserve (carried —
    #                                     replicas share the config,
    #                                     so the target's accounting
    #                                     matches the source's)
    n_blocks: int                       # real (non-pad) payload blocks
    payload: Optional[list] = None      # per-layer host (k, v) rows;
    #                                     None -> recompute on import
    # the resolved per-slot sampling row travels verbatim (the target
    # decodes under the SAME knobs the source sampled with)
    temperature: Optional[float] = None
    top_k: Optional[float] = None
    top_p: Optional[float] = None
    priority: int = 0
    adapter_id: Optional[int] = None
    # trace flow-link id: export records the start, import the finish
    # — the merged fleet trace draws the migration as an arrow
    flow_id: Optional[int] = None
    # export timestamp: the cluster's migration_ms digest observes
    # export -> seated wall time (queueing while pending included —
    # that IS the drain latency a client could feel as a stall)
    export_t: float = field(default_factory=time.monotonic)


class _Slot:
    __slots__ = ("rid", "blocks", "worst_blocks", "cache_len",
                 "last_token", "n_emitted", "max_new", "history",
                 "prompt", "pend_pos", "pend_row", "admit_t",
                 "handoff", "priority", "resume", "adapter_id")

    def __init__(self, rid, blocks, worst_blocks, cache_len, last_token,
                 max_new, history=None, prompt=None, pend_pos=None):
        self.admit_t = time.monotonic()   # request-span start (trace)
        self.handoff = False    # prefill-role slot parked for export
        self.priority = 0       # scheduling class (preemptive sched)
        self.resume = None      # (last_token, n_emitted) to restore
        #                         when a recompute re-prefill completes
        self.adapter_id = None  # pinned LoRA adapter (None = base)
        self.rid = rid
        self.blocks = blocks            # allocated block ids (ordered)
        self.worst_blocks = worst_blocks
        self.cache_len = cache_len      # valid cache positions
        self.last_token = last_token
        self.n_emitted = 1              # prefill emitted the first token
        self.max_new = max_new
        # prompt + emitted tokens: position p of the cache holds
        # history[p] for p < cache_len — the n-gram drafter's lookup
        # corpus AND the token stream retirement hashes full blocks of
        self.history = history
        self.prompt = prompt            # int32 prompt (pending chunks)
        self.pend_pos = pend_pos        # next chunk start; None = done
        self.pend_row = None            # device table row for chunks


class _Pipe:
    """One dispatched-but-uncommitted ragged tick (the async
    pipeline's in-flight record): the executable's output futures plus
    the host-side row layout the commit half needs. ``pure`` marks a
    decode-only tick whose ``carry`` (device-resident next-tick packs)
    may feed a pipelined dispatch."""
    __slots__ = ("outs", "active", "given", "n_pending", "q_lens",
                 "rid_of", "pend_pos0", "t_tick", "t_l0", "pure",
                 "carry")

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, kw.get(k))


class ServingEngine:
    """Continuous-batching serving over a causal-LM with the paged-KV
    protocol (``init_paged_caches`` + ``block_tables``/``cache_lens``
    forward kwargs — Llama/Qwen2/GPT families).

    Usage::

        engine = ServingEngine(model, ServingConfig(num_slots=8))
        rid = engine.submit([1, 2, 3], max_new_tokens=32)
        results = engine.run()          # {rid: np.ndarray of tokens}

    or stream: pass ``stream_callback=lambda rid, tok: ...`` and drive
    ``step()`` yourself.
    """

    def __init__(self, model, config: Optional[ServingConfig] = None,
                 stream_callback: Optional[Callable] = None,
                 draft_model=None, spec_heads=None):
        from ..generation import GenerationMixin, _select_token
        from ..generation import speculative as _spec
        if not isinstance(model, GenerationMixin):
            raise TypeError(
                f"{type(model).__name__} does not support generation "
                "(needs the KV-cache protocol)")
        if not hasattr(model, "init_paged_caches"):
            raise TypeError(
                f"{type(model).__name__} does not implement "
                "init_paged_caches (paged-KV serving)")
        cfg = config or ServingConfig()
        if cfg.decode_strategy not in ("greedy_search", "sampling"):
            raise NotImplementedError(
                f"serving decode_strategy {cfg.decode_strategy!r}; "
                "supported: greedy_search, sampling")
        gamma = int(cfg.num_speculative_tokens or 0)
        if gamma < 0:
            raise ValueError(
                f"num_speculative_tokens must be >= 0, got {gamma}")
        if draft_model is not None and \
                (gamma == 0 or cfg.drafter != "model"):
            # silently drafting via n-gram while the caller handed over
            # a draft model would measure the wrong configuration
            raise ValueError(
                "draft_model requires num_speculative_tokens > 0 and "
                "drafter='model' "
                f"(got gamma={gamma}, drafter={cfg.drafter!r})")
        # -- tree-structured speculation: validate BEFORE the kill
        # switches (misconfiguration must raise regardless of env),
        # resolve AFTER them (a killed tree is exactly the linear
        # engine, heads downgrading to the ngram drafter)
        spec_tree = getattr(cfg, "spec_tree", None)
        if spec_tree is not None:
            spec_tree = tuple(int(p) for p in spec_tree)
            if len(spec_tree) != gamma or gamma == 0:
                raise ValueError(
                    f"spec_tree has {len(spec_tree)} non-root nodes; "
                    "must equal num_speculative_tokens="
                    f"{gamma} (> 0)")
            _pa.tree_ancestor_bits(spec_tree)   # topology/depth check
            if cfg.drafter == "model":
                raise ValueError(
                    "spec_tree drafts via drafter='ngram' (top-k "
                    "prompt lookup) or drafter='heads' (draft-head "
                    "projections); a separate draft_model proposes "
                    "one chain, not a tree")
        if cfg.drafter == "heads" and spec_tree is None:
            raise ValueError(
                "drafter='heads' requires spec_tree (the draft heads "
                "fill a token tree)")
        if not _spec.speculative_enabled():  # PADDLE_TPU_SPECULATIVE=0
            gamma = 0
            draft_model = None
            spec_tree = None
        if spec_tree is not None and not _spec.spec_tree_enabled():
            spec_tree = None                 # PADDLE_TPU_SPEC_TREE=0
        drafter = str(cfg.drafter)
        if drafter == "heads" and spec_tree is None:
            drafter = "ngram"   # killed tree -> the linear ngram path
        self._spec_tree = spec_tree
        self._drafter = drafter
        if spec_tree is not None:
            # static per-engine layout: node depths, the leaf (chain)
            # each node feeds, chain count, max depth (= head count)
            (self._tree_depth, self._tree_leaf_of, self._tree_chains,
             self._tree_max_depth) = _spec.tree_chain_layout(spec_tree)
        self._role = str(getattr(cfg, "role", "both") or "both")
        if self._role == "prefill" and gamma:
            raise NotImplementedError(
                "a prefill-role engine never decodes, so speculative "
                "decoding (num_speculative_tokens > 0) has nothing to "
                "verify there — put the draft on the decode replicas")
        if gamma:
            if cfg.drafter not in ("ngram", "model", "heads"):
                raise ValueError(f"drafter {cfg.drafter!r}; "
                                 "supported: ngram, model, heads")
            if cfg.drafter == "model" and draft_model is None:
                raise ValueError(
                    "drafter='model' requires a draft_model")
            reason = _spec.spec_exclusion_reason(model)
            if reason is not None:
                raise NotImplementedError(
                    f"speculative serving unavailable: {reason}")
            if cfg.drafter == "model":
                reason = _spec.draft_exclusion_reason(model, draft_model)
                if reason is not None:
                    raise NotImplementedError(
                        f"draft model unusable: {reason}")
        # -- MoE admission gate ---------------------------------------
        # Dropless MoE serves: decode-time routing is tiny-batch and
        # per-row, so packed serving rows (other slots' tokens, verify
        # windows, prefill chunks) cannot perturb a row's expert
        # outputs. Capacity routing stays excluded — the batched rows
        # WOULD compete for each expert's capacity slots, making
        # logits depend on batch composition (the bucketing/spec
        # exclusion reasoning of PRs 3-4, applied to the engine
        # itself).
        for mdl, who in ((model, "model"), (draft_model, "draft model")):
            c = getattr(mdl, "config", None) if mdl is not None else None
            if _num_experts(c) and not getattr(c, "dropless", False):
                raise NotImplementedError(
                    f"capacity-routed MoE {who} cannot serve: batched "
                    "slots' tokens would compete for expert capacity, "
                    "so logits would depend on batch composition. Set "
                    "config.dropless=True (grouped dropless routing) "
                    "to serve this model.")
        cfgm = getattr(model, "config", None)
        self._moe = bool(_num_experts(cfgm))
        # stats()['moe_fused_gmm'] reports whether the fused kernel
        # ACTUALLY traced into one of this engine's executables
        # (captured in _aot_compile from the MOE_STATS kernel stamp) —
        # env kill switch, config twin, backend and shape gates all
        # fold in by construction
        self._moe_fused_traced = False
        self._moe_tap_on = bool(getattr(cfg, "moe_telemetry", True)) \
            and os.environ.get("PADDLE_TPU_MOE_TELEMETRY", "1") != "0"
        max_pos = getattr(getattr(model, "config", None),
                          "max_position_embeddings", None)
        if max_pos is not None and cfg.max_model_len + gamma > max_pos:
            raise ValueError(
                f"max_model_len ({cfg.max_model_len})"
                + (f" + speculative window ({gamma})" if gamma else "")
                + f" exceeds the model's max_position_embeddings "
                f"({max_pos})")
        self.model = model
        self.config = cfg
        self._stream = stream_callback
        model.eval()

        # -- tensor parallelism -----------------------------------------
        tp = int(getattr(cfg, "tp_degree", 1) or 1)
        if tp > 1 and os.environ.get("PADDLE_TPU_SERVE_TP", "1") == "0":
            tp = 1          # kill switch: single-device path, bit-for-bit
        self._tp = tp
        self._mesh = self._build_tp_mesh(model, draft_model, tp) \
            if tp > 1 else None
        self._pool_sharding = _pc.pool_sharding(self._mesh) \
            if self._mesh is not None else None
        self._census = {}           # exec name -> jaxpr collective rows
        self._tp_step_bytes = 0     # explicit mp payload of one decode
        self._n_tp_bytes = 0

        from ..jit import _LayerBinder
        binder = _LayerBinder(model)
        self._params = self._shard_params(binder) \
            if self._mesh is not None else binder.param_arrays()
        self._model_step = model._build_model_step(
            binder, binder.buffer_arrays())
        # -- Medusa-style draft heads (drafter="heads") ---------------
        # one [hidden, vocab] projection per tree depth over the
        # target's final hidden state; node k+1 (depth d, sibling rank
        # j under its parent) takes the (j+1)-th top token of head
        # d-1's logits. The head weights ride WITH the target params —
        # never a separate model — which is what lifts the disagg
        # draft-spec exclusion for head-drafted trees.
        self._heads = None
        self._model_step_h = None
        self._slot_props = {}    # slot -> cached next-tick proposal [g]
        if self._spec_tree is not None and self._drafter == "heads":
            import inspect
            if "return_hidden" not in inspect.signature(
                    type(model).forward).parameters:
                raise NotImplementedError(
                    f"{type(model).__name__} does not expose "
                    "forward(return_hidden=...) — draft heads need "
                    "the target's final hidden state")
            hdim = int(cfgm.hidden_size)
            vocab = int(cfgm.vocab_size)
            n_heads = self._tree_max_depth
            sib, cnt = [], {}
            for p in self._spec_tree:
                r = cnt.get(p, 0)
                cnt[p] = r + 1
                sib.append(r)
            self._tree_sib = tuple(sib)
            self._tree_kmax = max(sib) + 1
            if spec_heads is not None:
                ws = [np.asarray(w, np.float32) for w in spec_heads]
                if len(ws) != n_heads or any(
                        w.shape != (hdim, vocab) for w in ws):
                    raise ValueError(
                        f"spec_heads must be {n_heads} arrays of "
                        f"shape ({hdim}, {vocab}) (one per tree "
                        "depth)")
            else:
                # deterministic random calibration: every engine (and
                # every cluster replica) derives the SAME weights from
                # the fixed seed, so head-drafted trees stay
                # token-exact across colocated and disaggregated
                # deployments with zero weight shipping
                ws = [np.random.default_rng(0x5EED + d)
                      .standard_normal((hdim, vocab))
                      .astype(np.float32) * 0.02
                      for d in range(n_heads)]
            self._heads = self._dev(np.stack(ws))
            self._model_step_h = model._build_model_step(
                binder, binder.buffer_arrays(), want_hidden=True)
        elif spec_heads is not None and cfg.drafter != "heads":
            raise ValueError(
                "spec_heads requires drafter='heads' (and spec_tree)")
        do_sample = cfg.decode_strategy == "sampling"
        self._do_sample = do_sample
        self._select_token = _select_token
        # -- in-executable sampling head with per-SLOT knobs ----------
        # (temperature, top_k, top_p) ride as a [num_slots, 3] device
        # tensor every tick instead of Python floats baked into the
        # trace: a new sampling config (engine-wide OR per-request via
        # submit()) is DATA — same executable, zero recompiles. Greedy
        # engines carry the operand untouched (argmax never reads it).
        self._samp_default = np.asarray(
            [float(cfg.temperature), float(cfg.top_k),
             float(cfg.top_p)], np.float32)
        self._slot_samp = np.tile(self._samp_default,
                                  (cfg.num_slots, 1))
        self._samp_dev = None           # device mirror of _slot_samp
        self._samp_row_dev = {}         # slot -> device [3] row (the
        #                                 chunk/bucketed-prefill execs
        #                                 take one slot's row)
        # -- mega-kernelized decode tick ------------------------------
        # resolved ONCE at construction (config flag + the
        # PADDLE_TPU_FUSED_DECODE env twin); GSPMD TP traces keep the
        # unfused projections — an opaque pallas_call cannot be
        # partitioned, the moe_gmm gate applied here
        from ..ops.pallas import decode_fused as _df
        self._df = _df
        self._fused_mode = _df.resolve_fused_mode(
            getattr(cfg, "fused_decode", True))
        if self._mesh is not None:
            # GSPMD TP traces keep the unfused projections (an opaque
            # pallas_call cannot be partitioned — fused_decode_mode
            # would report "off" inside serving_tp_scope anyway);
            # resolving to None HERE keeps stats()['fused_decode']
            # honest on TP engines
            self._fused_mode = None
        self._kcensus = {}          # exec name -> kernel census rows

        self._bs = int(cfg.block_size)
        # +gamma: the speculative verify window may overhang the last
        # emitted token by up to gamma written-then-rolled-back slots
        self._gamma = gamma
        self._ngram_max = int(cfg.spec_ngram_max)
        # chunked prefill + prefix caching switches: prefix reuse NEEDS
        # the chunked path (the bucketed dense prefill recomputes and
        # rewrites the whole prompt, so mapping cached blocks under it
        # would save nothing and the scatter would clobber them)
        self._chunked = bool(cfg.chunked_prefill) and \
            os.environ.get("PADDLE_TPU_CHUNKED_PREFILL", "1") != "0"
        self._prefix_on = self._chunked \
            and bool(cfg.enable_prefix_cache) \
            and os.environ.get("PADDLE_TPU_PREFIX_CACHE", "1") != "0"
        self._chunk = max(1, min(int(cfg.prefill_chunk),
                                 int(cfg.max_model_len)))
        self._chunk_budget = int(cfg.max_prefill_chunks_per_step)
        # KV-pool quantization: resolved ONCE at construction (config
        # + PADDLE_TPU_KV_INT8 env twin) — "int8" or None; raises on
        # an unsupported request before any pool is built
        self._kv_dtype = _pc.resolve_kv_cache_dtype(
            getattr(cfg, "kv_cache_dtype", None))
        # content-hash chain seed: hashes are only comparable within
        # one (model architecture, config, cache layout) world
        self._fp = self._model_fingerprint(model)
        self._mb = _pc.blocks_for(cfg.max_model_len + gamma, self._bs)
        nb = (1 + cfg.num_slots * self._mb) if cfg.num_blocks is None \
            else int(cfg.num_blocks)
        self._alloc = _pc.BlockAllocator(nb)
        # -- ragged mixed-batch layout --------------------------------
        self._ragged = bool(getattr(cfg, "ragged_batch", True)) and \
            os.environ.get("PADDLE_TPU_RAGGED_BATCH", "1") != "0"
        if self._spec_tree is not None and not self._ragged:
            raise NotImplementedError(
                "spec_tree requires the ragged engine (ragged_batch="
                "True without PADDLE_TPU_RAGGED_BATCH=0); to disable "
                "tree speculation itself use PADDLE_TPU_SPEC_TREE=0")
        # -- async tick pipeline (docs/OPS.md "Async tick pipeline") --
        # resolved ONCE at construction: config depth AND the
        # PADDLE_TPU_ASYNC_TICK env twin (0 = kill switch beating an
        # explicit depth — today's dispatch-then-block loop returns
        # bit-for-bit; 1 arms depth-1 when the field is left None)
        _ad = getattr(cfg, "async_depth", None)
        _ae = os.environ.get("PADDLE_TPU_ASYNC_TICK", "")
        if _ae == "0":
            _depth = 0
        elif _ad is None:
            _depth = 1 if _ae == "1" else 0
        else:
            _depth = int(_ad)
        if _depth and not self._ragged:
            if _ad is None:
                _depth = 0      # env-armed: best-effort, legacy engine
            else:
                raise NotImplementedError(
                    "async_depth requires the ragged engine "
                    "(ragged_batch=True without "
                    "PADDLE_TPU_RAGGED_BATCH=0); to disable the "
                    "pipeline itself use PADDLE_TPU_ASYNC_TICK=0")
        self._async_on = _depth >= 1
        self._async_depth = 1 if self._async_on else 0
        self._pipe = None               # in-flight (uncommitted) tick
        self._commit_due = None         # commit half of a split tick
        self._n_pipe_flushes = 0
        self._last_dispatch_t = None    # host-gap digest anchor
        self._split_t0 = 0.0            # cluster phase-split health
        self._split_c0 = 0              # bracket (tick_dispatch)
        if self._chunked:
            want = cfg.ragged_prefill_rows
            self._prefill_rows = max(1, min(
                int(self._chunk if want is None else want),
                int(cfg.max_model_len)))
        else:
            self._prefill_rows = 0      # bucketed prefill at admission
        # static packed width: every active slot's decode/verify rows
        # plus one tick's prefill row budget always fit
        self._rows = cfg.num_slots * (gamma + 1) + self._prefill_rows
        # static per-slot row ceiling (the ragged grid's window dim)
        self._wmax = max(gamma + 1,
                         min(self._chunk, self._prefill_rows)
                         if self._chunked else 1)
        # pad rows park at a position past every table's reach — the
        # write null-routes and the rope/position gathers clamp
        self._overflow = self._mb * self._bs
        self._ragged_exec = None
        self._ragged_draft_exec = None
        self._pools = self._init_caches(model, nb)
        self._draft_model = draft_model \
            if gamma and cfg.drafter == "model" else None
        if self._draft_model is not None:
            self._draft_model.eval()
            dbinder = _LayerBinder(self._draft_model)
            self._dbinder = dbinder
            self._dparams = self._shard_params(dbinder) \
                if self._mesh is not None else dbinder.param_arrays()
            self._draft_step = self._draft_model._build_model_step(
                dbinder, dbinder.buffer_arrays())
            self._dpools = self._init_caches(self._draft_model, nb)
            self._draft_prefill_execs = {}
        self._verify_exec = None
        self._draft_exec = None
        self._tables = np.zeros((cfg.num_slots, self._mb), np.int32)
        self._slots: List[Optional[_Slot]] = [None] * cfg.num_slots
        self._reserved = 0              # blocks promised to active slots
        self._queue: deque = deque()
        self._results: Dict[int, list] = {}
        self._done: Dict[int, np.ndarray] = {}
        self._next_rid = 0
        self._eos = -1 if cfg.eos_token_id is None \
            else int(cfg.eos_token_id)
        self._pad = int(cfg.pad_token_id)
        # the sampling key is EXPLICITLY replicated across shards: every
        # shard consumes the identical key against the identical
        # gathered logits, so TP sampling is the same draw as
        # single-device (never split per-shard — that would silently
        # sample a different token on every shard)
        self._key = self._dev(jax.random.PRNGKey(int(cfg.seed)))
        self._tables_dev = None         # device mirror of _tables
        self._decode_exec = None
        self._prefill_execs = {}        # legacy bucketed prefill
        self._chunk_exec = None         # the ONE chunked-prefill exec
        self._draft_chunk_exec = None
        self._cow_exec = None           # copy-on-write block duplicate
        self._draft_cow_exec = None
        # disaggregated prefill -> decode handoff (role="prefill"
        # parks completed prompts here; export/import are each ONE
        # fixed-width [mb] executable, so steady state stays
        # recompile-free on both sides of the transfer)
        self._handoff_ready: List[int] = []     # slot indices parked
        # transfer width: the payload only ever carries PROMPT blocks,
        # so it is sized by max_model_len alone — NOT _mb, whose +gamma
        # headroom differs between a (spec-free) prefill engine and a
        # speculating decode replica and would shape-mismatch the
        # import executable
        self._mb_xfer = _pc.blocks_for(cfg.max_model_len, self._bs)
        self._export_exec = None
        self._import_exec = None
        self._n_handoffs = 0            # prefills exported (this engine)
        self._n_blocks_exported = 0
        self._n_blocks_imported = 0
        # -- SLO-aware preemptive scheduling + host-DRAM KV tier ------
        # resolved ONCE at construction: config flag AND the
        # PADDLE_TPU_PREEMPT env twin (0 = kill switch beating an
        # explicit True — the worst-case FIFO scheduler returns
        # bit-for-bit); a prefill-role engine never decodes so it has
        # nothing to preempt, and the recompute resume path IS a chunk
        # prefill, so the bucketed-prefill fallback disables it too
        self._preempt_on = bool(getattr(cfg, "enable_preemption",
                                        True)) \
            and os.environ.get("PADDLE_TPU_PREEMPT", "1") != "0" \
            and self._role != "prefill" and self._chunked
        wm = getattr(cfg, "admission_watermark_blocks", None)
        self._watermark = int(cfg.num_slots if wm is None else wm)
        self._resume_policy = str(getattr(cfg, "preempt_resume",
                                          "auto"))
        self._shed_depth = getattr(cfg, "shed_queue_depth", None)
        self._default_qwait = getattr(cfg, "max_queue_wait_ms", None)
        tier_bytes = int(getattr(cfg, "host_kv_tier_bytes", 0) or 0)
        self._host_tier = _pc.HostKVTier(tier_bytes) \
            if self._preempt_on and tier_bytes > 0 else None
        if self._host_tier is not None and self._prefix_on:
            # LRU-evicted published blocks spill their bytes to host
            # instead of dying — a later prefix hit restores them
            # through the fixed-width import scatter
            self._alloc.on_evict = self._spill_evicted
        self._n_preempt = 0             # victim slots preempted
        self._n_spilled = 0             # KV blocks spilled to host
        self._n_restored = 0            # KV blocks restored from host
        self._n_swap_resumes = 0
        self._n_recompute_resumes = 0
        self._n_shed = 0
        self._n_timeout = 0
        self._n_cancelled = 0           # in-flight cancels
        # live-session migration (elastic fleet: scale-down drain /
        # cluster rebalancing — ISSUE 19)
        self._n_migrated_out = 0        # live sessions exported
        self._n_migrated_in = 0         # live sessions imported
        # recompute-vs-swap cost model, measured online: EMA of chunk-
        # prefill row throughput (rows/s — what a recompute resume
        # pays per cached token) and of host-transfer bandwidth
        # (bytes/s over the export/import executables — what a swap
        # pays per payload byte)
        self._prefill_rows_s = 0.0
        self._xfer_bytes_s = 0.0
        # per-engine counts (the monitor counters below are process-
        # global telemetry shared by every engine; stats() must report
        # THIS engine)
        self._n_decode_compiles = 0
        self._n_exec_compiled = 0       # EVERY executable this engine
        #                                 built (decode+verify+chunk+
        #                                 prefill+cow, target AND draft)
        # snapshot of the op-layer's process-wide fallback counter:
        # stats() reports the DELTA, i.e. fallback events observed
        # since this engine was created, not another engine's history
        self._fallbacks0 = sum(_pa.kernel_fallback_counts().values())
        self._n_decode_steps = 0
        self._n_tokens = 0
        self._n_completed = 0
        self._n_prefill_compiles = 0
        self._n_prefill_chunks = 0
        self._n_prefix_blocks = 0       # cached blocks mapped into slots
        self._n_prefix_tokens = 0       # prompt tokens NOT re-prefilled
        self._n_prompt_tokens = 0       # prompt tokens admitted
        self._n_cow = 0
        self._n_evictions_seen = 0
        self._n_spec_proposed = 0
        self._n_spec_accepted = 0
        self._n_spec_verifies = 0       # per-slot verify windows
        self._n_spec_emitted = 0
        # -- batched multi-LoRA serving -------------------------------
        # resolved ONCE at construction: config (lora_rank > 0) AND
        # the PADDLE_TPU_LORA env kill switch (0 beating an explicit
        # rank — the base engine returns bit-for-bit: no module is
        # tagged, the tick executable takes no extra operand and the
        # slots pack carries no extra row, so the jaxpr is identical)
        lora_rank = int(getattr(cfg, "lora_rank", 0) or 0)
        self._lora_on = lora_rank > 0 and _lora.lora_enabled()
        self._lora_pool: Optional[_lora.AdapterPool] = None
        self._lora_dev = None           # device image of the stacks
        self._lora_dev_version = -1     # pool.version the image holds
        self._lora_swaps_seen = 0       # counter-delta bookkeeping
        # per-slot RESIDENT STACK ROW (not adapter id; 0 = the null
        # all-zero adapter) — rides the slots pack as one more int32
        # row next to the sampling tensor, so adapter churn is a VALUE
        # change at a fixed shape: zero steady-state recompiles
        self._slot_adapter = np.zeros(cfg.num_slots, np.int64)
        if self._lora_on:
            if not self._ragged or not self._chunked:
                raise NotImplementedError(
                    "multi-LoRA serving requires the ragged engine "
                    "with chunked prefill (ragged_batch=True and "
                    "chunked_prefill on, without their env kill "
                    "switches) — prompt rows must ride the ragged "
                    "tick so adapter deltas reach the prefill KV; to "
                    "disable LoRA itself use PADDLE_TPU_LORA=0")
            specs = _lora.tag_modules(model, str(getattr(
                cfg, "lora_targets", "attn")))
            if not specs:
                raise NotImplementedError(
                    "no LoRA-taggable projection layers found on this "
                    "model (expected q/k/v/o | qkv/out projections "
                    "named per Llama/GPT idiom)")
            self._lora_pool = _lora.AdapterPool(
                specs, lora_rank,
                alpha=getattr(cfg, "lora_alpha", None),
                max_resident=int(getattr(cfg, "max_adapters", 8)),
                quant=bool(getattr(cfg, "lora_quant", False)))

        # -- telemetry ------------------------------------------------
        self._m_occupancy = monitor.gauge(
            "serving_slot_occupancy", "active serving slots")
        self._m_util = monitor.histogram(
            "serving_batch_utilization",
            "active slots / num_slots per decode step",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        self._m_queue_wait = monitor.histogram(
            "serving_queue_wait_ms",
            "submit -> queue-exit wait, labeled by outcome (admitted |"
            " cancelled | rejected | shutdown) — EVERY exit path "
            "observes, so the distribution can't survivor-bias toward "
            "admitted requests",
            labels=("outcome",))
        self._m_tokens = monitor.counter(
            "serving_tokens_total", "tokens generated (all requests)")
        self._m_steps = monitor.counter(
            "serving_decode_steps", "batched decode steps executed")
        self._m_decode_compiles = monitor.counter(
            "serving_decode_compiles",
            "decode-step compilations (steady state: stays at 1)")
        self._m_prefill_compiles = monitor.counter(
            "serving_prefill_compiles",
            "prefill compilations per prompt bucket",
            labels=("bucket",))
        self._m_completed = monitor.counter(
            "serving_requests_completed", "requests fully served")
        self._m_prefix_blocks = monitor.counter(
            "serving_prefix_blocks_reused",
            "cached KV blocks mapped into admitted slots")
        self._m_prefix_tokens = monitor.counter(
            "serving_prefix_tokens_reused",
            "prompt tokens served from the prefix cache (not "
            "re-prefilled)")
        self._m_cow = monitor.counter(
            "serving_cow_copies",
            "copy-on-write block duplications (shared block appended "
            "into)")
        self._m_evict = monitor.counter(
            "serving_cache_evictions",
            "cached blocks evicted under memory pressure (LRU)")
        self._m_hit_rate = monitor.gauge(
            "serving_prefix_hit_rate",
            "cumulative reused / admitted prompt tokens")
        self._m_kv_transfer = monitor.counter(
            "serving_kv_blocks_transferred",
            "KV blocks streamed between engine pools (disaggregated "
            "prefill -> decode handoffs; counted at import, data + "
            "scales travel together on int8 pools)")
        # -- preemption + host-tier telemetry (registered
        # unconditionally so stats()/JSONL always carry the keys —
        # FIFO/killed engines report zeros, dashboards never KeyError
        # across a mixed or rolled-back fleet)
        self._m_preempt = monitor.counter(
            "serving_preemptions",
            "victim slots preempted (blocks published + spilled, slot "
            "freed, request re-enqueued at the front of its priority "
            "class)")
        self._m_spill = monitor.counter(
            "serving_kv_blocks_spilled",
            "KV blocks spilled to the host-DRAM tier (preempted "
            "victims' live blocks + LRU-evicted published blocks; "
            "int8 data + scales travel together)")
        self._m_restore = monitor.counter(
            "serving_kv_blocks_restored",
            "KV blocks restored from the host-DRAM tier (swap resumes "
            "+ prefix hits on spilled published blocks)")
        self._m_host_bytes = monitor.gauge(
            "serving_host_tier_bytes",
            "bytes resident in the host-DRAM KV tier (spilled block "
            "payloads awaiting restore or LRU eviction)")
        # -- multi-LoRA telemetry (registered unconditionally so
        # stats()/JSONL always carry the keys — non-LoRA and
        # PADDLE_TPU_LORA=0 engines report zeros, dashboards never
        # KeyError across a mixed or rolled-back fleet)
        self._m_lora_resident = monitor.gauge(
            "serving_lora_adapters_resident",
            "LoRA adapters resident in the device stacks (excludes "
            "the always-present null adapter)")
        self._m_lora_swaps = monitor.counter(
            "serving_lora_adapter_swaps",
            "adapter loads that evicted an unpinned resident adapter "
            "to make room (LRU churn against the max_adapters budget)")
        self._m_lora_host = monitor.gauge(
            "serving_lora_host_tier_bytes",
            "bytes of registered adapters NOT currently resident on "
            "device (host-DRAM registry tier awaiting an LRU swap-in)")
        monitor.info(
            "serving_tp_degree",
            "tensor-parallel degree of the most recent engine").set(
            self._tp)
        self._m_tp_bytes = monitor.counter(
            "serving_tp_collective_bytes",
            "explicit cross-shard collective payload executed per "
            "engine step (per-shard bytes, jaxpr census: decode OR "
            "draft-loop + verify; GSPMD-inserted collectives not "
            "included)")
        self._m_tp_pool = monitor.gauge(
            "serving_tp_pool_bytes_per_shard",
            "KV block-pool bytes each shard holds (kv_head slice)")
        pool_bytes = _pc.pool_bytes(self._pools)
        target_pool_bytes = pool_bytes
        if self._draft_model is not None:
            pool_bytes += _pc.pool_bytes(self._dpools)
        self._pool_bytes_per_shard = pool_bytes // self._tp
        self._m_tp_pool.set(self._pool_bytes_per_shard)
        # -- KV-pool telemetry (quantization observability) -----------
        # registered unconditionally, so stats()/JSONL always carry the
        # keys — fp engines report the fp numbers, consumers never
        # KeyError on a mixed or rolled-back fleet
        self._kv_dtype_name = "int8" if self._kv_dtype == "int8" \
            else str(jnp.dtype(self._pools[0][0].dtype))
        self._kv_pool_bytes = pool_bytes            # data + scales
        # bytes ONE cached position costs across all target layers
        # (int8: data + scale rows) — the analytic per-step KV read
        # gauge multiplies this by the tick's attended positions
        self._kv_pos_bytes = target_pool_bytes / float(
            self._pools[0][0].shape[0] * self._bs)
        self._kv_step_bytes_last = 0
        self._kv_read_pend = 0      # legacy-path chunk reads this tick
        monitor.info(
            "serving_kv_cache_dtype",
            "KV block-pool storage dtype of the most recent engine "
            "(int8 = quantized pool + absmax scales)").set(
            self._kv_dtype_name)
        self._m_kv_pool = monitor.gauge(
            "serving_kv_pool_bytes",
            "total KV block-pool bytes (all layers + scale pools, "
            "target and draft models, every shard)")
        self._m_kv_pool.set(pool_bytes)
        self._m_kv_step = monitor.gauge(
            "serving_kv_bytes_per_step",
            "analytic target-pool KV bytes the last engine tick's "
            "attention streamed from HBM (attended positions x bytes "
            "per cached position; int8 pools count data + scales)")
        # -- decode-tick fusion observability -------------------------
        # the headline "kernel count per decode layer down" metric is
        # MEASURED, not asserted: every _aot_compile runs
        # monitor.kernel_census over the compiled HLO + traced jaxpr,
        # and this gauge tracks the tick executable's kernel count
        self._m_kernels = monitor.gauge(
            "serving_kernels_per_tick",
            "kernel count of the engine's compiled tick executable "
            "(optimized-HLO entry instructions — fusions, dots, "
            "custom calls; the decode-tick fusion headline metric)")
        # -- per-tick roofline attribution (ISSUE 15 layer 2) ---------
        # static half: every _aot_compile captures the executable's
        # cost_analysis FLOPs + bytes accessed (the "Operator Fusion
        # in XLA" accounting, live); measured half: each step path
        # clocks its launch->sync wall time into a per-executable EMA.
        # Fused they give per-executable MFU, HBM-bandwidth
        # utilization and a compute-vs-bandwidth-bound classification
        # (stats()['roofline']). Pure host accounting, independent of
        # the trace kill switch — like the SLO digests.
        self._exec_cost = {}        # exec name -> cost_analysis dict
        self._step_time = {}        # exec name -> wall-seconds EMA
        self._step_ticks = {}       # exec name -> timed launches
        self._peak_flops = monitor.device_peak_flops()
        self._peak_hbm_bw = monitor.device_peak_hbm_bw()
        self._ridge = self._peak_flops / self._peak_hbm_bw
        self._cpu_proxy = jax.default_backend() != "tpu"
        self._m_mfu = monitor.gauge(
            "serving_step_mfu",
            "per-tick model FLOPs utilization of the tick executable "
            "(cost_analysis FLOPs / measured launch->sync time / chip "
            "peak FLOPs; nominal peaks off-TPU — cpu_proxy)")
        self._m_bw_util = monitor.gauge(
            "serving_hbm_bw_util",
            "per-tick HBM-bandwidth utilization of the tick "
            "executable (cost_analysis bytes accessed / measured "
            "launch->sync time / chip peak HBM bytes/s; nominal "
            "peaks off-TPU — cpu_proxy)")
        # -- on-demand profiling windows (ISSUE 15 layer 3) -----------
        # profile(n_ticks) arms a bounded jax.profiler capture around
        # the next N ticks; PADDLE_TPU_TRACE=0 keeps it inert
        self._prof = _tracing.ProfilerWindow()
        # MoE routing telemetry: per-expert load fractions + routing
        # entropy of every dispatch the engine's executables run,
        # observed at DECODE time through the trace-armed tap in
        # distributed/moe.py (one tiny debug callback per sparse layer
        # per tick). Metrics registered unconditionally so stats() and
        # the JSONL export always carry the keys.
        self._m_moe_load = monitor.histogram(
            "serving_moe_expert_load",
            "per-expert share of routed (token, slot) pairs per "
            "dispatch (0 = expert idle this step)",
            buckets=(0.001, 0.01, 0.05, 0.1, 0.2, 0.4, 0.6, 0.8, 1.0))
        self._m_moe_entropy = monitor.gauge(
            "serving_moe_routing_entropy",
            "decode-time routing entropy, normalized to [0, 1] by "
            "log(num_experts) (1 = perfectly balanced routing)")
        self._moe_ent_last = 0.0
        self._moe_load_max_last = 0.0
        self._n_moe_dispatches = 0
        # -- request-lifecycle tracing + SLO latency digests ----------
        # One Tracer per engine (one trace-viewer pid): tid 0 is the
        # engine tick timeline, tid 1+i slot i's request timeline, the
        # last tid the admission queue. PADDLE_TPU_TRACE=0 leaves
        # self._trace None and every call site skips — the killed hot
        # path runs zero tracer instructions (tracing is host-only
        # code either way, so executables and outputs are identical).
        self._engine_id = next(_ENGINE_IDS)
        self._tid_queue = cfg.num_slots + 1
        self._trace = None
        if _tracing.tracing_enabled():
            tr = _tracing.Tracer(f"ServingEngine[{self._engine_id}]")
            tr.set_thread(0, "engine")
            for i in range(cfg.num_slots):
                tr.set_thread(1 + i, f"slot {i}")
            tr.set_thread(self._tid_queue, "queue")
            self._trace = tr
        # always-on per-engine SLO digests (P², bounded memory) —
        # independent of the trace kill switch; surfaced as stats()
        # keys, the serving_*_ms quantile gauges, and the JSONL/prom
        # exports those gauges ride
        self._d_ttft = LatencyDigest()
        self._d_itl = LatencyDigest()
        self._d_queue = LatencyDigest()
        self._d_e2e = LatencyDigest()
        # tokens emitted per slot verify window, as a P² digest —
        # unconditional (a non-speculative engine just reports a
        # zeroed summary) so stats()['spec_accept_len'] and the
        # serving_spec_accept_len gauge are always present
        self._d_accept = LatencyDigest()
        # dispatch -> dispatch host time, as a P² digest —
        # unconditional (sync engines observe too: their gap includes
        # the blocking token fetch + commit bookkeeping, which is
        # exactly what async_depth=1 moves off the critical path), so
        # stats()['host_gap_ms'] and the serving_host_gap_ms gauge are
        # always present
        self._d_host_gap = LatencyDigest()
        self._m_host_gap = monitor.gauge(
            "serving_host_gap_ms",
            "host time between consecutive tick dispatches (P2 "
            "digest; under async_depth=1 commit bookkeeping overlaps "
            "device execution, so the gap shrinks toward pure "
            "pack+launch time)", labels=("q",))
        self._m_accept = monitor.gauge(
            "serving_spec_accept_len",
            "accepted-length quantiles per slot verify window (P2 "
            "digest; tokens emitted = accepted drafts + bonus — tree "
            "and linear speculation both observe; empty on "
            "non-speculative engines)", labels=("q",))
        self._submit_t = {}     # rid -> submit monotonic (live reqs)
        self._last_emit = {}    # rid -> last token-emit monotonic
        self._m_lat = {
            "ttft": monitor.gauge(
                "serving_ttft_ms",
                "time-to-first-token quantiles (P2 digest; submit -> "
                "first streamed token)", labels=("q",)),
            "itl": monitor.gauge(
                "serving_itl_ms",
                "inter-token latency quantiles (P2 digest; gap "
                "between consecutive streamed tokens of one request)",
                labels=("q",)),
            "queue_wait": monitor.gauge(
                "serving_queue_wait_quantile_ms",
                "queue-wait quantiles (P2 digest; terminal "
                "cancelled/rejected/shutdown outcomes included)",
                labels=("q",)),
            "e2e": monitor.gauge(
                "serving_e2e_ms",
                "submit -> retirement latency quantiles (P2 digest)",
                labels=("q",)),
        }
        if gamma:
            self._m_spec_len = monitor.histogram(
                "serving_spec_accepted_len",
                "tokens emitted per slot verify window "
                "(accepted drafts + the correction/bonus token)",
                buckets=(1, 2, 3, 4, 5, 6, 7, 8, 9))
            self._m_spec_proposed = monitor.counter(
                "spec_tokens_proposed", "draft tokens proposed")
            self._m_spec_accepted = monitor.counter(
                "spec_tokens_accepted", "draft tokens accepted")
            self._m_spec_rate = monitor.gauge(
                "serving_spec_acceptance_rate",
                "accepted / proposed draft tokens (cumulative)")

        # -- fleet health engine (monitor/health.py) ------------------
        # Gauges register UNCONDITIONALLY (the always-present metrics
        # contract); the monitor itself only exists when the kill
        # switch is up. Under PADDLE_TPU_HEALTH=0 every health hook is
        # a no-op and the compiled executables are bit-identical (the
        # nonfinite probe output is always computed; only the HOST
        # fetch is gated).
        self._health_on = (bool(getattr(cfg, "health", True))
                           and os.environ.get("PADDLE_TPU_HEALTH", "1")
                           != "0")
        self._m_health = monitor.gauge(
            "serving_health_score",
            "engine health in [0,1]: 1 - severity penalties of firing "
            "alerts (page 0.5, warn 0.15)")
        self._m_burn = monitor.gauge(
            "serving_slo_burn_rate",
            "fast-window SLO burn rate (violation fraction / error "
            "budget; 1.0 = budget consumed exactly on schedule)")
        self._m_alerts = monitor.gauge(
            "serving_alerts_firing", "number of currently-firing alerts")
        self._m_health.set(1.0)
        self._nonfinite_ticks = 0
        self._nf_last = False
        self._slo_ok: Dict[int, bool] = {}
        self._h_slo_ttft = float(cfg.health_slo_ttft_ms)
        self._h_slo_itl = float(cfg.health_slo_itl_ms)
        if self._health_on:
            profile_cb = None
            if int(cfg.health_profile_ticks) > 0:
                n_prof = int(cfg.health_profile_ticks)

                def profile_cb(n=n_prof):
                    try:
                        self.profile(n)
                    except Exception:
                        pass
            self._health = _health.HealthMonitor(
                slo_target=cfg.health_slo_target,
                burn_fast_s=cfg.health_burn_fast_s,
                burn_slow_s=cfg.health_burn_slow_s,
                burn_threshold=cfg.health_burn_threshold,
                burn_min_requests=cfg.health_burn_min_requests,
                watchdog_mult=cfg.health_watchdog_mult,
                watchdog_floor_s=cfg.health_watchdog_floor_s,
                stats_cb=self.stats,
                trace_cb=self._health_trace,
                profile_cb=profile_cb,
                incident=_health.IncidentCapture())
        else:
            self._health = None

    def _health_trace(self):
        """Chrome-trace dict for incident bundles (None w/o a tracer)."""
        if self._trace is None:
            return None
        return {"traceEvents": list(self._trace.chrome_events()),
                "displayTimeUnit": "ms"}

    # -- public API ---------------------------------------------------

    def load_adapter(self, adapter_id, weights) -> int:
        """Register (or hot-reload) LoRA adapter ``adapter_id`` from a
        ``{module_name: (A, B)}`` dict — names either fully qualified
        (``model.layers.0.self_attn.q_proj``) or bare leaf names
        (``q_proj``, broadcast to every matching layer); ``A`` is
        ``[d_in, rank]``, ``B`` ``[rank, d_out]``. The weights land in
        the host-DRAM registry immediately and are device-loaded
        lazily on first acquire (LRU within the ``max_adapters``
        resident budget). Safe mid-serving: re-registering a RESIDENT
        id rewrites its stack row in place (requests already pinned to
        it pick up the new weights next tick — stack VALUES change,
        never shapes, so nothing recompiles)."""
        if self._lora_pool is None:
            raise ValueError(
                "load_adapter requires a LoRA-serving engine "
                "(ServingConfig(lora_rank=...) and PADDLE_TPU_LORA "
                "not 0)")
        aid = self._lora_pool.register(adapter_id, weights)
        self._sync_lora_metrics()
        return aid

    def adapter_resident(self, adapter_id) -> bool:
        """True when the adapter currently occupies a device stack row
        (the router's adapter-affinity probe — residency means a
        submit against it seats without an LRU swap)."""
        return self._lora_pool is not None \
            and self._lora_pool.resident(adapter_id)

    def submit(self, prompt, max_new_tokens=None, temperature=None,
               top_k=None, top_p=None, priority=0,
               max_queue_wait_ms=None, adapter_id=None) -> int:
        """Queue one request; returns its request id. Tokens stream to
        ``stream_callback`` as ``step()``/``run()`` produce them.
        ``temperature``/``top_k``/``top_p`` override the engine's
        ``ServingConfig`` values FOR THIS REQUEST ONLY (sampling
        engines; they land in the per-slot sampling tensors at
        admission — device data, never a recompile). ``priority`` is
        the request's scheduling class under the preemptive scheduler
        (higher admits first and may preempt strictly-lower victims;
        FIFO within a class; ignored when preemption is off).
        ``max_queue_wait_ms`` bounds the queue wait — a request still
        queued past it exits with outcome="timeout" and an empty
        result (default: ``ServingConfig.max_queue_wait_ms``). A
        validation rejection still leaves a terminal queue-wait
        observation (outcome="rejected") so the latency digest sees
        every request that touched the front door, not only the
        admitted survivors; queue-depth shedding
        (``ServingConfig.shed_queue_depth``) refuses with
        :class:`QueueShedError` and an outcome="shed" observation.
        ``adapter_id`` decodes the request under a LoRA adapter
        previously registered via :meth:`load_adapter` (None = base
        model); unknown ids are rejected at this front door, never
        mid-flight."""
        t0 = time.monotonic()
        if self._shed_depth is not None \
                and len(self._queue) >= int(self._shed_depth):
            self._n_shed += 1
            self._m_queue_wait.labels(outcome="shed").observe(0.0)
            self._d_queue.observe(0.0)
            if self._trace is not None:
                self._trace.instant("shed", tid=self._tid_queue,
                                    args={"queued": len(self._queue)})
            raise QueueShedError(
                f"admission queue at shed threshold "
                f"({len(self._queue)} >= {int(self._shed_depth)}): "
                "request refused (load shedding)")
        try:
            ids = np.asarray(prompt, np.int32).reshape(-1)
            if ids.size == 0:
                raise ValueError("empty prompt")
            max_new = int(self.config.max_new_tokens
                          if max_new_tokens is None
                          else max_new_tokens)
            if max_new < 1:
                raise ValueError(f"max_new_tokens must be >= 1, "
                                 f"got {max_new}")
            if ids.size + max_new > self.config.max_model_len:
                raise ValueError(
                    f"prompt ({ids.size}) + max_new_tokens "
                    f"({max_new}) exceeds max_model_len "
                    f"({self.config.max_model_len})")
            worst = self._worst_for(ids.size, max_new)
            if worst > self._alloc.num_blocks - 1:
                raise ValueError(
                    f"request needs {worst} blocks; pool has only "
                    f"{self._alloc.num_blocks - 1}")
            has_samp = any(v is not None
                           for v in (temperature, top_k, top_p))
            if has_samp and not self._do_sample:
                # greedy argmax never reads the knobs — honoring the
                # unknown-option policy, fail instead of silently
                # producing tokens that ignore the request
                raise ValueError(
                    "per-request temperature/top_k/top_p require "
                    "decode_strategy='sampling' (this engine decodes "
                    f"{self.config.decode_strategy!r})")
            if temperature is not None and float(temperature) < 0.0:
                raise ValueError(
                    f"temperature must be >= 0, got {temperature}")
            if top_k is not None and int(top_k) < 0:
                raise ValueError(f"top_k must be >= 0, got {top_k}")
            if top_p is not None and not 0.0 < float(top_p) <= 1.0:
                raise ValueError(
                    f"top_p must be in (0, 1], got {top_p}")
            if isinstance(priority, bool) or not isinstance(
                    priority, (int, np.integer)):
                raise ValueError(
                    f"priority must be an int, got {priority!r}")
            if max_queue_wait_ms is None:
                max_queue_wait_ms = self._default_qwait
            if max_queue_wait_ms is not None \
                    and float(max_queue_wait_ms) <= 0.0:
                raise ValueError(
                    f"max_queue_wait_ms must be > 0 (or None), got "
                    f"{max_queue_wait_ms}")
            if adapter_id is not None:
                if self._lora_pool is None:
                    raise ValueError(
                        "adapter_id requires a LoRA-serving engine "
                        "(ServingConfig(lora_rank=...) and "
                        "PADDLE_TPU_LORA not 0); this engine serves "
                        "the base model only")
                adapter_id = int(adapter_id)
                if not self._lora_pool.known(adapter_id):
                    raise ValueError(
                        f"unknown adapter_id {adapter_id}: register "
                        "it with load_adapter() before submitting "
                        "against it")
        except ValueError:
            wait = 1000.0 * (time.monotonic() - t0)
            self._m_queue_wait.labels(outcome="rejected").observe(wait)
            self._d_queue.observe(wait)
            if self._trace is not None:
                self._trace.instant("rejected", tid=self._tid_queue)
            raise
        rid = self._next_rid
        self._next_rid += 1
        req = ServingRequest(
            rid, ids, max_new,
            temperature=None if temperature is None
            else float(temperature),
            top_k=None if top_k is None else int(top_k),
            top_p=None if top_p is None else float(top_p),
            priority=int(priority),
            max_queue_wait_ms=None if max_queue_wait_ms is None
            else float(max_queue_wait_ms),
            adapter_id=adapter_id)
        self._queue.append(req)
        self._submit_t[rid] = req.submit_time
        if self._trace is not None:
            self._trace.instant(
                "submit", tid=self._tid_queue,
                args={"rid": rid, "prompt_tokens": int(ids.size),
                      "max_new": max_new, "priority": int(priority)})
        return rid

    def cancel(self, request_id: int) -> bool:
        """Cancel a request ANYWHERE in its lifetime. Queued: removed
        with a terminal queue-wait observation (outcome="cancelled");
        a queued PREEMPTED request additionally lands its e2e
        observation and surfaces the tokens already streamed. In
        flight (mid-prefill or mid-decode): the slot is retired
        immediately — its blocks are freed WITHOUT publishing (a
        cancelled stream's continuation must not seed the prefix
        cache), its spilled payload (if any) is dropped from the host
        tier, the partial tokens land in ``run()``'s results, and the
        e2e digest observes submit -> cancel. Returns False only when
        the id is unknown (never submitted, already finished, or
        already cancelled)."""
        self._flush_pipe()      # commit in-flight ticks before mutating
        for k, req in enumerate(self._queue):
            if req.request_id == request_id:
                del self._queue[k]
                self._queue_exit(req, "cancelled")
                self._finish_unserved(req)
                return True
        for i, s in enumerate(self._slots):
            if s is not None and s.rid == request_id:
                self._cancel_slot(i)
                return True
        return False

    def _finish_unserved(self, req, record_empty=False):
        """Terminal bookkeeping for a request leaving the QUEUE without
        service (cancel / timeout): surface what already streamed (the
        partial tokens of a preempted request; ``record_empty`` lands
        an empty result for never-admitted timeouts so ``run()``
        consumers never KeyError), drop any spilled payload, and land
        the e2e observation for requests that DID stream (their
        clients saw tokens; the digest must see the request end)."""
        rid = req.request_id
        if req.resume is not None:
            if self._host_tier is not None:
                self._host_tier.pop(("victim", rid), restore=False)
                self._m_host_bytes.set(self._host_tier.bytes_used)
            # anchor on the request's own submit time — _queue_exit
            # already popped _submit_t for terminal outcomes, and a
            # preempted request DID stream, so its end must land in
            # the e2e digest
            self._submit_t.pop(rid, None)
            self._d_e2e.observe(
                1000.0 * (time.monotonic() - req.submit_time))
        self._slo_ok.pop(rid, None)
        self._last_emit.pop(rid, None)
        toks = self._results.pop(rid, None)
        if self.config.retain_results and (
                toks is not None or record_empty):
            self._done[rid] = np.asarray(toks or [], np.int64)

    def _cancel_slot(self, i):
        """Retire slot ``i`` mid-flight on behalf of ``cancel()``: no
        completion accounting, no publishing (the cancelled stream
        must not seed the prefix index with its continuation), blocks
        freed, stream terminated with the tokens already emitted."""
        slot = self._slots[i]
        now = time.monotonic()
        t0 = self._submit_t.pop(slot.rid, None)
        if t0 is not None:
            self._d_e2e.observe(1000.0 * (now - t0))
        self._slo_ok.pop(slot.rid, None)    # cancels don't burn budget
        self._last_emit.pop(slot.rid, None)
        if self._trace is not None:
            self._trace.emit(
                f"req{slot.rid}", tid=1 + i, t0=slot.admit_t, t1=now,
                args={"tokens": slot.n_emitted,
                      "cache_len": slot.cache_len, "cancelled": True})
            self._trace.instant("cancelled", tid=1 + i,
                                args={"rid": slot.rid})
        if slot.handoff and i in self._handoff_ready:
            self._handoff_ready.remove(i)
        self._alloc.free(slot.blocks)
        self._reserved -= slot.worst_blocks - len(slot.blocks)
        self._tables[i, :] = 0
        self._tables_dev = None
        self._slots[i] = None
        self._set_slot_samp(i)
        self._lora_release_slot(i, slot)
        toks = self._results.pop(slot.rid, [])
        if self.config.retain_results:
            self._done[slot.rid] = np.asarray(toks, np.int64)
        self._n_cancelled += 1
        self._m_occupancy.set(self.num_active)

    def _trace_tick(self, t_tick, exec_name: str, path: str, **extra):
        """One engine-tick span (tid 0) — ALL three step paths emit
        through here so the tick-span schema (exec/path/queued/
        kernel-fallback delta + per-path extras) cannot drift between
        ragged and legacy traces. Caller guards on ``self._trace``."""
        args = {"exec": exec_name, "path": path,
                "queued": len(self._queue),
                "kernel_fallbacks": int(sum(
                    _pa.kernel_fallback_counts().values())
                    - self._fallbacks0)}
        args.update(extra)
        self._trace.emit("tick", tid=0, t0=t_tick, args=args)

    def _queue_exit(self, req, outcome: str) -> float:
        """Terminal queue-wait observation — EVERY path a request
        leaves the admission queue by (admitted / cancelled /
        shutdown; submit rejections observe outcome="rejected"
        directly) funnels through here, so neither the histogram nor
        the digest can survivor-bias toward admitted requests."""
        now = time.monotonic()
        wait = 1000.0 * (now - req.submit_time)
        self._m_queue_wait.labels(outcome=outcome).observe(wait)
        self._d_queue.observe(wait)
        if outcome not in ("admitted", "resumed"):
            # request will never emit/retire (a "resumed" one keeps
            # its original submit anchor for the e2e digest)
            self._submit_t.pop(req.request_id, None)
        if self._trace is not None:
            self._trace.emit(
                f"req{req.request_id} queued", tid=self._tid_queue,
                t0=req.submit_time, t1=now, args={"outcome": outcome})
        return wait

    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def num_queued(self) -> int:
        return len(self._queue)

    def step(self) -> List[tuple]:
        """One engine tick: admit what fits, decode one token (or
        verify a speculative window) for every active slot, retire
        finished sequences. Returns this tick's
        ``[(request_id, token), ...]`` (admission prefills included).
        On the default ragged path one tick is ONE executable launch
        covering decode + verify + prefill rows together. An armed
        profiling window (``profile(n_ticks)``) brackets the tick —
        the capture starts before the first armed tick and stops
        after the last, bounding the profile to exactly N ticks."""
        if self._health is None:
            with self._prof.tick():
                return self._step_dispatch()
        t0 = time.monotonic()
        c0 = self._n_exec_compiled
        with self._prof.tick():
            out = self._step_dispatch()
        self._health_tick(t0, time.monotonic(), c0)
        return out

    def _health_tick(self, t0: float, t1: float, c0: int) -> None:
        """Feed one tick's signals to the health monitor (host only)."""
        h = self._health
        nf = self._nf_last
        self._nf_last = False
        if nf:
            self._nonfinite_ticks += 1
        ema = self._step_time.get(
            "verify" if self._gamma else "decode", 0.0)
        try:
            fallbacks = sum(_pa.kernel_fallback_counts().values())
        except Exception:
            fallbacks = 0
        h.on_tick(
            tick_s=t1 - t0,
            queued=len(self._queue),
            step_ema_s=ema,
            fallbacks=fallbacks,
            compiles=self._n_exec_compiled,
            spec_emitted=self._n_spec_emitted,
            spec_verifies=self._n_spec_verifies,
            preemptions=self._n_preempt,
            completed=self._n_completed,
            nonfinite=nf,
            compiled=self._n_exec_compiled > c0)
        self._m_health.set(h.score())
        self._m_burn.set(h._last_burn.get("fast", 0.0))
        self._m_alerts.set(float(len(h.firing())))

    def _step_dispatch(self) -> List[tuple]:
        if self._ragged:
            if self._async_on:
                return self._step_async()
            return self._step_ragged()
        if self._gamma:
            return self._step_spec()
        t_tick = time.monotonic()
        emitted = self._admit()
        self._advance_prefills(emitted)
        active = [i for i, s in enumerate(self._slots)
                  if s is not None and s.pend_pos is None
                  and not s.handoff]
        if not active:
            if self._kv_read_pend:      # prefill-only tick: the chunk
                self._note_kv_read(0)   # reads ARE the tick's traffic
            return emitted
        active = self._ensure_blocks(active)
        if not active:                  # everyone preempted for blocks
            return emitted

        cfg = self.config
        lens = np.zeros(cfg.num_slots, np.int32)
        toks = np.full(cfg.num_slots, self._pad, np.int32)
        for i in active:
            lens[i] = self._slots[i].cache_len
            toks[i] = self._slots[i].last_token
        sub = self._next_key()
        if self._tables_dev is None:    # only re-upload after changes
            self._tables_dev = self._dev(self._tables)
        samp = self._samp_operand()
        if self._decode_exec is None:
            self._decode_exec = self._compile_decode(lens, toks, samp,
                                                     sub)
        t_l0 = time.monotonic()
        with _quiet_donation():
            out, self._pools = self._decode_exec(
                self._params, self._pools, self._tables_dev,
                self._dev(lens), self._dev(toks), samp, sub)
        out = np.asarray(out)
        t_sync = time.monotonic()

        self._m_steps.inc()
        self._n_decode_steps += 1
        self._note_step_time("decode", t_sync - t_l0)
        if self._mesh is not None:
            self._m_tp_bytes.inc(self._tp_step_bytes)
            self._n_tp_bytes += self._tp_step_bytes
        self._m_util.observe(len(active) / cfg.num_slots)
        self._note_kv_read(int(lens.sum()) + len(active))
        tr = self._trace
        rid_of = {i: self._slots[i].rid for i in active} \
            if tr is not None else None
        for i in active:
            slot = self._slots[i]
            tok = int(out[i])
            slot.cache_len += 1
            slot.last_token = tok
            slot.n_emitted += 1
            slot.history.append(tok)
            self._emit(slot.rid, tok)
            emitted.append((slot.rid, tok))
            if tok == self._eos or slot.n_emitted >= slot.max_new:
                self._retire(i)
        if tr is not None:
            for i in active:
                tr.emit("decode tick", tid=1 + i, t0=t_l0, t1=t_sync,
                        args={"rid": rid_of[i], "rows": 1})
            self._trace_tick(
                t_tick, "decode", "legacy", active=len(active),
                occupancy=round(len(active) / cfg.num_slots, 3))
        return emitted

    def _step_spec(self) -> List[tuple]:
        """Speculative engine tick: draft gamma tokens per active slot,
        verify the whole window in ONE fixed-shape target forward, and
        commit 1..gamma+1 tokens per slot. The verify executable is
        AOT-compiled once — accept/reject never changes a shape, only
        the ``cache_lens`` values — so steady state stays at zero
        recompiles exactly like the plain decode step. Rollback of a
        rejected tail is ``cache_len`` simply not advancing over it,
        plus ``_trim_blocks`` returning overhang blocks."""
        from ..generation import speculative as _spec
        t_tick = time.monotonic()
        emitted = self._admit()
        self._advance_prefills(emitted)
        active = [i for i, s in enumerate(self._slots)
                  if s is not None and s.pend_pos is None
                  and not s.handoff]
        if not active:
            if self._kv_read_pend:      # prefill-only tick
                self._note_kv_read(0)
            return emitted
        g = self._gamma
        # room for the full window: positions cache_len .. cache_len+g
        active = self._ensure_blocks(active, horizon=g + 1)
        if not active:                  # everyone preempted for blocks
            return emitted

        cfg = self.config
        lens = np.zeros(cfg.num_slots, np.int32)
        toks = np.full((cfg.num_slots, g + 1), self._pad, np.int32)
        for i in active:
            lens[i] = self._slots[i].cache_len
            toks[i, 0] = self._slots[i].last_token
        if self._tables_dev is None:
            self._tables_dev = self._dev(self._tables)
        lens_dev = self._dev(lens)
        t_l0 = time.monotonic()         # draft + verify launch window

        samp = self._samp_operand()
        dq = None
        if self._draft_model is not None:
            sub = self._next_key()
            if self._draft_exec is None:
                self._draft_exec = self._compile_draft(lens, toks,
                                                       samp, sub)
            with _quiet_donation():
                props, dq, self._dpools = self._draft_exec(
                    self._dparams, self._dpools, self._tables_dev,
                    lens_dev, self._dev(toks[:, 0]), samp, sub)
            toks[:, 1:] = np.asarray(props)
        else:
            for i in active:
                toks[i, 1:] = _spec.ngram_propose(
                    self._slots[i].history, g, self._ngram_max)

        sub = self._next_key()
        if self._verify_exec is None:
            self._verify_exec = self._compile_verify(lens, toks, samp,
                                                     dq, sub)
        args = [self._params, self._pools, self._tables_dev, lens_dev,
                self._dev(toks), samp]
        if self._do_sample:
            if dq is not None:
                args.append(dq)
            args.append(sub)
        with _quiet_donation():
            out, accept, _logp, self._pools = self._verify_exec(*args)
        out = np.asarray(out)
        accept = np.asarray(accept)
        t_sync = time.monotonic()

        self._m_steps.inc()
        self._n_decode_steps += 1
        # the draft loop (if any) shares the window — the verify row
        # is conservatively charged the whole draft+verify interval
        self._note_step_time("verify", t_sync - t_l0)
        if self._mesh is not None:
            self._m_tp_bytes.inc(self._tp_step_bytes)
            self._n_tp_bytes += self._tp_step_bytes
        self._m_util.observe(len(active) / cfg.num_slots)
        # window row t attends lens + t + 1 positions
        self._note_kv_read((g + 1) * int(lens.sum())
                           + len(active) * (g + 1) * (g + 2) // 2)
        tr = self._trace
        rid_of = {i: self._slots[i].rid for i in active} \
            if tr is not None else None
        acc_lens = {}
        for i in active:
            acc_lens[i] = self._commit_verify_window(
                i, out[i], accept[i], emitted)
        if self._n_spec_proposed:
            self._m_spec_rate.set(
                self._n_spec_accepted / self._n_spec_proposed)
        if tr is not None:
            for i in active:
                tr.emit("verify tick", tid=1 + i, t0=t_l0, t1=t_sync,
                        args={"rid": rid_of[i], "rows": g + 1,
                              "accepted_len": acc_lens[i]})
            self._trace_tick(
                t_tick, "verify", "legacy", active=len(active),
                occupancy=round(len(active) / cfg.num_slots, 3))
        return emitted

    def _tree_draft(self, i) -> np.ndarray:
        """One slot's gamma-node tree proposal for this tick, in node
        order. drafter='heads': the verify executable computed it LAST
        tick from the accepted path's final hidden state (cached per
        slot); a slot with no cached proposal (fresh prefill, disagg
        import, post-preemption resume) falls back to the ngram-topk
        chains — the SAME rule on every engine, which keeps colocated
        and disaggregated head drafting token-exact. drafter='ngram':
        always the top-k prompt-lookup chains."""
        from ..generation import speculative as _spec
        if self._heads is not None:
            props = self._slot_props.get(i)
            if props is not None:
                return props
        chains = _spec.ngram_propose_topk(
            self._slots[i].history, self._tree_max_depth,
            self._tree_chains, self._ngram_max)
        return np.asarray(_spec.tree_fill_from_chains(
            self._spec_tree, chains), np.int32)

    def _commit_verify_window(self, i, out_row, accept_row, emitted):
        """Commit one slot's verified speculative window — the SHARED
        host-side half of acceptance (legacy ``_step_spec`` and the
        ragged tick both call it, so emission/rollback/metric
        semantics cannot drift between the paths): emit the kept
        prefix, account acceptance, retire on EOS/max_new, else
        advance ``cache_len`` over the accepted prefix (rollback of
        the rejected tail = NOT advancing over it) and trim overhang
        blocks. Returns the number of tokens emitted (the per-slot
        ``accepted_len`` the trace annotates verify-tick spans
        with)."""
        from ..generation import speculative as _spec
        g = self._gamma
        slot = self._slots[i]
        # EOS inside the window and max_new room both truncate
        kept, n_acc = _spec.commit_window(
            out_row, accept_row, slot.max_new - slot.n_emitted,
            self._eos)
        slot.n_emitted += len(kept)
        slot.history.extend(kept)
        for tok in kept:
            self._emit(slot.rid, tok)
            emitted.append((slot.rid, tok))
        # accepted drafts that were actually USED: EOS-inside-window
        # or max_new room can truncate the emission below n_acc+1,
        # and the metrics must agree with what clients received
        n_used = min(n_acc, len(kept))
        self._n_spec_proposed += g
        self._n_spec_accepted += n_used
        self._n_spec_verifies += 1
        self._n_spec_emitted += len(kept)
        self._d_accept.observe(float(len(kept)))
        self._m_spec_len.observe(len(kept))
        self._m_spec_proposed.inc(g)
        self._m_spec_accepted.inc(n_used)
        if kept[-1] == self._eos or slot.n_emitted >= slot.max_new:
            self._retire(i)
        else:
            # commit the window prefix [cur, accepted drafts]; the
            # rejected tail rolls back by NOT advancing over it
            slot.cache_len += n_acc + 1
            slot.last_token = kept[-1]
            self._trim_blocks(i)
        return len(kept)

    def _step_ragged(self) -> List[tuple]:
        """Ragged mixed-batch tick (the default path): pack every live
        query row — 1 per decoding slot, ``gamma + 1`` per verifying
        slot, up to the prefill row budget for pending prompts — into
        ONE launch of the engine's single compiled executable, then
        commit tokens, prefill progress, speculative accept/reject and
        retirements host-side. The packed width is static
        (``num_slots * (gamma+1) + prefill_rows``); slots with no work
        contribute zero rows, so raggedness lives entirely in the
        ``q_lens``/``row_starts`` VALUES and steady state runs zero
        recompiles exactly like the per-width path it replaces.

        The tick is split into a dispatch half (pack + launch) and a
        commit half (token fetch + host bookkeeping); this sync path
        runs them back to back, the async pipeline (``_step_async``)
        lags the commit one tick behind the dispatch."""
        pipe, emitted = self._ragged_dispatch()
        if pipe is not None:
            emitted.extend(self._ragged_commit(pipe))
        return emitted

    def _ragged_dispatch(self):
        """Dispatch half of one ragged tick: admit, pack the row
        layout, launch the ONE executable. Returns ``(pipe, emitted)``
        — ``pipe`` holds everything the commit half needs (``None`` on
        an idle tick), ``emitted`` carries admission-time prefill
        tokens."""
        from ..generation import speculative as _spec
        t_tick = time.monotonic()
        emitted = self._admit()
        cfg = self.config
        g = self._gamma
        n_slots = cfg.num_slots
        active = [i for i, s in enumerate(self._slots)
                  if s is not None and s.pend_pos is None
                  and not s.handoff]
        pending = [i for i, s in enumerate(self._slots)
                   if s is not None and s.pend_pos is not None]
        if not active and not pending:
            return None, emitted
        if active:
            # room for this tick's write positions (the verify window
            # overhangs by up to gamma speculated slots); growth under
            # an overcommitted pool may preempt — survivors only
            active = self._ensure_blocks(active, horizon=g + 1)
            if not active and not pending:
                return None, emitted

        # -- pack the tick's work into per-slot row counts -------------
        q_lens = np.zeros(n_slots, np.int64)
        base = np.zeros(n_slots, np.int64)
        given = {}              # slot -> prefill rows granted this tick
        cap = min(self._chunk, self._prefill_rows)
        budget = self._prefill_rows
        for i in active:
            q_lens[i] = g + 1
            base[i] = self._slots[i].cache_len
        # a growth preemption above may have victimized a pending slot
        pending = [i for i in pending if self._slots[i] is not None]
        if self._preempt_on and len(pending) > 1:
            # the per-tick prefill row budget is a scheduled resource
            # too: the highest class prefills first (its TTFT is the
            # SLO), FIFO within a class — under the kill switch the
            # slot-index order is untouched, bit-for-bit
            pending.sort(key=lambda i: (-self._slots[i].priority,
                                        self._slots[i].admit_t, i))
        for i in pending:
            if budget <= 0:
                break
            slot = self._slots[i]
            # ONE wide (chunk-width) slot per tick — the fallback's
            # two-lane contract; later pending slots still trickle at
            # the narrow (gamma+1) width, so nothing starves
            cap_i = cap if not given else (g + 1)
            k = min(int(slot.prompt.size) - slot.pend_pos, cap_i,
                    budget)
            if k <= 0:
                continue
            q_lens[i] = k
            base[i] = slot.pend_pos
            given[i] = k
            budget -= k
        if not int(q_lens.sum()):
            return None, emitted    # budget exhausted by earlier slots
        row_slot, row_pos, row_starts, last_rows = _pc.ragged_row_meta(
            q_lens, base, self._rows, self._overflow)
        if self._tables_dev is None:
            self._tables_dev = self._dev(self._tables)

        # -- draft proposals (speculative mode) ------------------------
        toks = None
        dq = None
        if g:
            toks = np.full((n_slots, g + 1), self._pad, np.int32)
            for i in active:
                toks[i, 0] = self._slots[i].last_token
        if g and self._draft_model is not None:
            # ONE fused draft executable: prime its cache over this
            # tick's prefill rows, then run the gamma+1 proposal scan.
            # Verify rows are parked at the overflow position for the
            # prime (their K/V comes from the scan itself), and
            # non-verifying slots' scan writes null-route past the
            # table's reach — a pending slot's real blocks are never
            # touched by the draft.
            prime_ids = np.full(self._rows, self._pad, np.int32)
            prime_pos = row_pos.copy()
            prime_q = q_lens.copy()
            for i in active:
                s0, n = int(row_starts[i]), int(q_lens[i])
                prime_pos[s0:s0 + n] = self._overflow
                prime_q[i] = 0
            for i, k in given.items():
                s0 = int(row_starts[i])
                slot = self._slots[i]
                prime_ids[s0:s0 + k] = \
                    slot.prompt[slot.pend_pos:slot.pend_pos + k]
            scan_lens = np.full(n_slots, self._overflow, np.int64)
            for i in active:
                scan_lens[i] = self._slots[i].cache_len
            sub = self._next_key()
            # TWO packed uploads carry the whole tick's draft metadata
            drows = np.stack([prime_ids, row_slot, prime_pos]) \
                .astype(np.int32)
            dslots = np.stack([base, prime_q, row_starts, scan_lens,
                               toks[:, 0]]).astype(np.int32)
            dargs = (self._dparams, self._dpools, self._tables_dev,
                     self._dev(drows), self._dev(dslots),
                     self._samp_operand(), sub)
            if self._ragged_draft_exec is None:
                self._ragged_draft_exec = self._compile_ragged_draft(
                    dargs)
            with _quiet_donation():
                outs = self._ragged_draft_exec(*dargs)
            if self._do_sample:
                props, dq, self._dpools = outs
            else:
                props, self._dpools = outs
            toks[:, 1:] = np.asarray(props)
        elif g and self._spec_tree is not None:
            for i in active:
                toks[i, 1:] = self._tree_draft(i)
        elif g:
            for i in active:
                toks[i, 1:] = _spec.ngram_propose(
                    self._slots[i].history, g, self._ngram_max)

        # -- the ONE mixed-batch launch --------------------------------
        ids = np.full(self._rows, self._pad, np.int32)
        for i in active:
            s0 = int(row_starts[i])
            if g:
                ids[s0:s0 + g + 1] = toks[i]
            else:
                ids[s0] = self._slots[i].last_token
        for i, k in given.items():
            s0 = int(row_starts[i])
            slot = self._slots[i]
            ids[s0:s0 + k] = \
                slot.prompt[slot.pend_pos:slot.pend_pos + k]
        sub = self._next_key()
        # TWO packed uploads carry the whole tick's row layout: the
        # per-row triple (ids, slot, position) and the per-slot quad
        # (base length, q_lens, row_starts, last_rows)
        rows_pack = np.stack([ids, row_slot, row_pos]).astype(np.int32)
        srows = [base, q_lens, row_starts, last_rows]
        if self._spec_tree is not None:
            # 5th per-slot row: which slots verify a TREE window this
            # tick (prefill rows keep the linear causal mask)
            tree_flags = np.zeros(n_slots, np.int64)
            for i in active:
                tree_flags[i] = 1
            srows.append(tree_flags)
        if self._lora_on:
            # per-slot adapter row (RESIDENT stack rows, 0 = the null
            # adapter) rides the slots pack next to the sampling
            # tensor — churn changes VALUES at a fixed shape, so no
            # adapter mix ever recompiles the tick
            srows.append(self._slot_adapter)
        if self._async_on and not g:
            # LAST row of the async slots pack: each slot's remaining
            # token budget (max_new - emitted). The executable's done
            # mask retires rows on device (budget <= 1 or EOS), so a
            # pipelined tick dispatched from the carry no-ops finished
            # slots without a host round trip.
            bud = np.zeros(n_slots, np.int64)
            for i in active:
                s = self._slots[i]
                bud[i] = s.max_new - s.n_emitted
            srows.append(bud)
        slots_pack = np.stack(srows).astype(np.int32)
        args = [self._params, self._pools, self._tables_dev,
                self._dev(rows_pack), self._dev(slots_pack)]
        if self._lora_on:
            # the stacked A/B weights are a runtime OPERAND (cached on
            # device until the pool version moves), same reasoning
            args.append(self._lora_operand())
        if g:
            args.append(self._dev(toks))
            if self._heads is not None:
                args.append(self._heads)
            if self._do_sample and dq is not None:
                args.append(dq)
        args.append(self._samp_operand())
        args.append(sub)
        if self._ragged_exec is None:
            self._ragged_exec = self._compile_ragged_step(tuple(args))
        # names/positions BEFORE the commit loops retire slots (the
        # async commit guard also keys on these: a slot reseated with
        # a DIFFERENT request between dispatch and commit must drop
        # the stale tick's token)
        rid_of = {i: self._slots[i].rid
                  for i in active + list(given)}
        pend_pos0 = {i: int(self._slots[i].pend_pos)
                     for i in given}
        t_l0 = time.monotonic()
        if self._last_dispatch_t is not None:
            self._d_host_gap.observe(
                1000.0 * (t_l0 - self._last_dispatch_t))
        self._last_dispatch_t = t_l0
        with _quiet_donation():
            outs = self._ragged_exec(*args)
        if self._async_on:
            # the pools advance at DISPATCH (device futures): the next
            # launch consumes them before this tick's commit runs
            self._pools = outs[-1]

        self._m_steps.inc()
        self._n_decode_steps += 1
        if self._mesh is not None:
            self._m_tp_bytes.inc(self._tp_step_bytes)
            self._n_tp_bytes += self._tp_step_bytes
        self._m_util.observe(len(active) / n_slots)
        # packed row t of slot s attends base[s] + t + 1 positions
        self._note_kv_read(int((q_lens * base).sum())
                           + int((q_lens * (q_lens + 1) // 2).sum()))
        pure = (self._async_on and not g and not given and not pending)
        pipe = _Pipe(
            outs=outs, active=list(active), given=given,
            n_pending=len(pending), q_lens=q_lens, rid_of=rid_of,
            pend_pos0=pend_pos0, t_tick=t_tick, t_l0=t_l0, pure=pure,
            carry=(outs[2], outs[3]) if pure else None)
        return pipe, emitted

    def _ragged_commit(self, pipe) -> List[tuple]:
        """Commit half of one ragged tick: fetch tokens, advance
        slots, retire, commit prefill progress, emit trace spans.
        Under async pipelining this runs one tick AFTER its dispatch —
        a slot retired, cancelled, preempted or migrated in between is
        skipped, dropping the speculative extra tick's token exactly
        (its KV write already null-routed on device via the carry's
        ``done`` mask, so there is nothing to trim)."""
        outs = pipe.outs
        g = self._gamma
        n_slots = self.config.num_slots
        active, given, q_lens = pipe.active, pipe.given, pipe.q_lens
        rid_of, pend_pos0 = pipe.rid_of, pipe.pend_pos0
        t_tick, t_l0 = pipe.t_tick, pipe.t_l0
        tr = self._trace
        emitted: List[tuple] = []
        committed = active
        if self._async_on:
            committed = [i for i in active
                         if self._slots[i] is not None
                         and self._slots[i].rid == rid_of[i]]

        # -- commit decode / verify rows -------------------------------
        acc_lens = {}
        if not g:
            tok_arr = np.asarray(outs[0])
            if self._health is not None:        # host fetch gated on
                self._nf_last = bool(outs[1])   # the kill switch only
            if not self._async_on:
                self._pools = outs[2]
            t_sync = time.monotonic()
            for i in committed:
                slot = self._slots[i]
                tok = int(tok_arr[i])
                slot.cache_len += 1
                slot.last_token = tok
                slot.n_emitted += 1
                slot.history.append(tok)
                self._emit(slot.rid, tok)
                emitted.append((slot.rid, tok))
                if tok == self._eos or slot.n_emitted >= slot.max_new:
                    self._retire(i)
        else:
            tok_arr = np.asarray(outs[0])       # prefill first tokens
            out = np.asarray(outs[1])
            accept = np.asarray(outs[2])
            k = 4 if self._heads is not None else 3
            if self._heads is not None:
                props_next = np.asarray(outs[3])
            if self._health is not None:        # gated host fetch
                self._nf_last = bool(outs[k])
            if not self._async_on:
                self._pools = outs[k + 1]
            t_sync = time.monotonic()
            for i in committed:
                acc_lens[i] = self._commit_verify_window(
                    i, out[i], accept[i], emitted)
            if self._heads is not None:
                # cache the heads' next-tick tree proposal for every
                # slot that survived the commit (retired/preempted
                # slots dropped theirs); fresh slots without a cached
                # proposal draft via the ngram-topk fallback next tick
                for i in committed:
                    if self._slots[i] is not None:
                        self._slot_props[i] = props_next[i]
            if self._n_spec_proposed:
                self._m_spec_rate.set(
                    self._n_spec_accepted / self._n_spec_proposed)

        # -- commit prefill progress -----------------------------------
        self._note_step_time("verify" if g else "decode",
                             t_sync - t_l0)
        if given:
            # cost-model input: rows prefilled this launch / wall time
            self._note_prefill_rate(sum(given.values()),
                                    t_sync - t_l0)
        for i, k in given.items():
            slot = self._slots[i]
            slot.pend_pos += k
            slot.cache_len = slot.pend_pos
            self._n_prefill_chunks += 1
            if slot.pend_pos >= int(slot.prompt.size):
                # the chunk's last row IS the final prompt row: its
                # sampled logits are the request's first token
                self._finish_prefill(i, int(tok_arr[i]), emitted)
        if tr is not None:
            # under async the span's [t_l0, t_sync] brackets dispatch
            # -> commit, i.e. it INCLUDES the one-tick overlap window
            # (commit-lag semantics, docs/OPS.md "Async tick
            # pipeline"); dropped (stale-slot) ticks emit no span
            for i in committed:
                args_i = {"rid": rid_of[i], "rows": int(q_lens[i])}
                if g:
                    args_i["accepted_len"] = acc_lens[i]
                tr.emit("verify tick" if g else "decode tick",
                        tid=1 + i, t0=t_l0, t1=t_sync, args=args_i)
            for i, k in given.items():
                tr.emit("prefill chunk", tid=1 + i, t0=t_l0,
                        t1=t_sync,
                        args={"rid": rid_of[i], "rows": int(k),
                              "pos": pend_pos0[i]})
            self._trace_tick(
                t_tick, "verify" if g else "decode", "ragged",
                rows=int(q_lens.sum()), active=len(active),
                pending=pipe.n_pending,
                occupancy=round(
                    (len(active) + pipe.n_pending) / n_slots, 3))
        return emitted

    # -- async tick pipeline (docs/OPS.md "Async tick pipeline") ------

    def _step_async(self) -> List[tuple]:
        """One engine tick with depth-1 dispatch-ahead: launch tick
        N+1 (from the device-resident carry when the slot composition
        is unchanged, sync-shaped otherwise), THEN commit tick N —
        host bookkeeping overlaps device execution."""
        out = self._tick_dispatch_async()
        out.extend(self._tick_commit_async())
        return out

    def _tick_dispatch_async(self) -> List[tuple]:
        emitted: List[tuple] = []
        prev = self._pipe
        if prev is not None and self._pipe_ready(prev):
            self._pipe = self._dispatch_pipelined(prev)
            self._commit_due = prev
            return emitted
        if prev is not None:
            # the slot composition wants to change (admission waiting,
            # a dispatched slot retired/cancelled/preempted/migrated,
            # prefill rows due, pool dry): drain the pipeline first,
            # then dispatch sync-shaped
            self._pipe = None
            self._n_pipe_flushes += 1
            emitted.extend(self._ragged_commit(prev))
        pipe, pre = self._ragged_dispatch()
        emitted.extend(pre)
        if pipe is None:
            return emitted
        if pipe.pure:
            self._pipe = pipe           # commit lags one tick
        else:
            self._commit_due = pipe     # commits this very tick
        return emitted

    def _tick_commit_async(self) -> List[tuple]:
        due, self._commit_due = self._commit_due, None
        if due is None:
            return []
        return self._ragged_commit(due)

    def tick_dispatch(self) -> List[tuple]:
        """Dispatch phase of an overlapped CLUSTER tick: launch this
        engine's next tick and defer the lagging commit to
        ``tick_commit()``, so N replicas' executables run concurrently
        instead of serially. Sync engines (async off) run their whole
        step here — the cluster's dispatch-all-then-commit-all loop
        then degrades to today's serial ticking bit-for-bit."""
        if not self._async_on:
            return self.step()
        self._split_t0 = time.monotonic()
        self._split_c0 = self._n_exec_compiled
        with self._prof.tick():
            return self._tick_dispatch_async()

    def tick_commit(self) -> List[tuple]:
        """Commit phase of an overlapped cluster tick (no-op on sync
        engines — their ``tick_dispatch`` already committed)."""
        if not self._async_on:
            return []
        out = self._tick_commit_async()
        if self._health is not None:
            self._health_tick(self._split_t0, time.monotonic(),
                              self._split_c0)
        return out

    def _pipe_ready(self, pipe) -> bool:
        """May the next tick dispatch straight from the in-flight
        tick's device carry? Requires an unchanged slot composition
        (every dispatched slot still seated with the same request,
        nothing queued, pending or parked) and block headroom for one
        more position per slot — grown WITHOUT preemption (a
        mid-pipeline victim would spill stale host state); a dry pool
        flushes instead and the sync path re-runs growth with
        preemption armed."""
        if not pipe.pure or self._handoff_ready:
            return False
        for i in pipe.active:
            s = self._slots[i]
            if s is None or s.rid != pipe.rid_of[i]:
                return False
        if self._queue:
            # a backed-up queue is safe to pipeline over ONLY when the
            # in-flight commit provably frees no slot: no EOS
            # configured and no dispatched slot on its last budgeted
            # token. Then no admission is possible this tick in the
            # sync schedule either — composition provably unchanged.
            # Otherwise flush, so a retirement admits the newcomer on
            # exactly the tick the blocking loop would have.
            if self._eos >= 0:
                return False
            for i in pipe.active:
                s = self._slots[i]
                if s.max_new - s.n_emitted <= 1:
                    return False
            if self._preempt_on and any(
                    q.priority > min(self._slots[i].priority
                                     for i in pipe.active)
                    for q in self._queue):
                # a queued request that outranks a seated slot must
                # reach the slot-pressure preemption scan NOW, not
                # after the backlog drains
                return False
        if any(s is not None and s.pend_pos is not None
               for s in self._slots):
            return False
        if all(self._slots[i].max_new - self._slots[i].n_emitted <= 1
               for i in pipe.active):
            # every slot retires at the in-flight commit (the carry
            # zeroed all its rows) — a pipelined tick would be a pure
            # no-op launch
            return False
        return self._pipe_grow(pipe)

    def _pipe_grow(self, pipe) -> bool:
        """Grow blocks for the pipelined tick's write positions: the
        in-flight tick writes position ``cache_len``, the pipelined
        one ``cache_len + 1``, both uncommitted host-side. No
        preemption and no COW: decode appends into tail blocks the
        slot owns privately; a dry pool returns False (caller
        flushes)."""
        for i in pipe.active:
            slot = self._slots[i]
            if slot.max_new - slot.n_emitted <= 1:
                # retires at the in-flight commit (its pipelined row
                # is zeroed on device) — never writes another block
                continue
            need = _pc.blocks_for(slot.cache_len + 2, self._bs)
            while len(slot.blocks) < need:
                try:
                    (blk,) = self._alloc.alloc(1)
                except RuntimeError:
                    return False
                self._tables[i, len(slot.blocks)] = blk
                slot.blocks.append(blk)
                self._tables_dev = None
                self._reserved -= 1
        return True

    def _dispatch_pipelined(self, prev) -> "_Pipe":
        """Dispatch the next tick straight from the in-flight tick's
        device-resident carry: no host packing, no token upload, no
        blocking fetch — the only host work left is the block-table
        re-upload when growth touched it. Operand count and shapes
        are EXACTLY the steady-state sync tick's (the carry rows ARE
        next tick's packs), so pipelining adds zero executables."""
        t_tick = time.monotonic()
        carry_rows, carry_slots = prev.carry
        if self._tables_dev is None:
            self._tables_dev = self._dev(self._tables)
        args = [self._params, self._pools, self._tables_dev,
                carry_rows, carry_slots]
        if self._lora_on:
            args.append(self._lora_operand())
        args.append(self._samp_operand())
        args.append(self._next_key())
        t_l0 = time.monotonic()
        if self._last_dispatch_t is not None:
            self._d_host_gap.observe(
                1000.0 * (t_l0 - self._last_dispatch_t))
        self._last_dispatch_t = t_l0
        with _quiet_donation():
            outs = self._ragged_exec(*args)
        self._pools = outs[-1]
        self._m_steps.inc()
        self._n_decode_steps += 1
        if self._mesh is not None:
            self._m_tp_bytes.inc(self._tp_step_bytes)
            self._n_tp_bytes += self._tp_step_bytes
        n_slots = self.config.num_slots
        active = list(prev.active)
        self._m_util.observe(len(active) / n_slots)
        # committed cache_len lags the device by one tick: the
        # pipelined row of slot s attends cache_len + 2 positions
        # (device-retired rows over-count by their window — analytic
        # gauge, documented)
        self._note_kv_read(sum(
            self._slots[i].cache_len + 2 for i in active))
        q_lens = np.zeros(n_slots, np.int64)
        for i in active:
            q_lens[i] = 1
        if self._trace is not None:
            self._trace.instant("pipelined dispatch", tid=0,
                                args={"active": len(active)})
        return _Pipe(
            outs=outs, active=active, given={}, n_pending=0,
            q_lens=q_lens, rid_of=dict(prev.rid_of), pend_pos0={},
            t_tick=t_tick, t_l0=t_l0, pure=True,
            carry=(outs[2], outs[3]))

    def _flush_pipe(self) -> List[tuple]:
        """Commit any in-flight pipelined tick NOW. Every
        slot-composition mutator (cancel, preempt, handoff pop,
        prefilled/migrated admits, session export/drain, shutdown)
        calls this before touching slot or queue state, so the
        pipeline only ever overlaps pure steady-state decode. No-op
        on sync engines and an idle pipeline."""
        out: List[tuple] = []
        due, self._commit_due = self._commit_due, None
        if due is not None:
            out.extend(self._ragged_commit(due))
        pipe, self._pipe = self._pipe, None
        if pipe is not None:
            self._n_pipe_flushes += 1
            out.extend(self._ragged_commit(pipe))
        return out

    def run(self) -> Dict[int, np.ndarray]:
        """Drive ``step()`` until queue and slots drain; returns (and
        drains) the tokens of every request completed since the last
        ``run()``, keyed by request id — a long-lived engine therefore
        never accumulates finished results."""
        if self._role == "prefill":
            # parked handoff slots only free via pop_prefilled() —
            # run() would spin forever waiting on them
            raise RuntimeError(
                "a role='prefill' engine cannot run() to completion: "
                "drive step() and collect pop_prefilled() handoffs "
                "(EngineCluster does this)")
        while self._queue or self.num_active:
            self.step()
        done, self._done = self._done, {}
        return done

    def serve(self, prompts, max_new_tokens=None) -> List[np.ndarray]:
        """Batch convenience: submit all, run to completion, return
        token arrays in submission order."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        done = self.run()
        return [done[r] for r in rids]

    def stats(self) -> dict:
        """Scheduler/counter snapshot (tests + ops dashboards). In
        speculative mode ``decode_steps``/``decode_compiles`` count the
        verify executable (the spec-mode decode step)."""
        self._sync_cache_metrics()
        out = {
            "active": self.num_active,
            "queued": self.num_queued,
            "free_blocks": self._alloc.free_blocks,
            "reserved_blocks": self._reserved,
            "decode_steps": self._n_decode_steps,
            "decode_compiles": self._n_decode_compiles,
            "tokens_total": self._n_tokens,
            "requests_completed": self._n_completed,
            "prefill_compiles": self._n_prefill_compiles,
            "prefill_chunks": self._n_prefill_chunks,
            # EVERY executable this engine built (decode + verify +
            # chunk + prefill buckets + cow, target AND draft) — the
            # ragged collapse is assertable from telemetry: 1 in
            # steady state (2 with a draft model). Present on the
            # legacy path too, where it counts the whole zoo.
            "executables_compiled": self._n_exec_compiled,
            "ragged_batch": self._ragged,
            # paged-attention entry points that lost the Pallas kernel
            # on a TPU backend since THIS engine was created (0 on
            # CPU; the op-layer counter is process-wide, so the
            # engine-lifetime delta is what "my engine silently fell
            # off the kernel" means — a concurrent engine's events
            # still land in the window, but never another's history)
            "kernel_fallbacks": sum(
                _pa.kernel_fallback_counts().values())
            - self._fallbacks0,
            # decode-tick fusion: mode (False | "kernel" |
            # "interpret") + the MEASURED kernel census of the tick
            # executable (0 before first compile). kernels_per_tick is
            # the optimized-HLO entry instruction count (≈ kernel
            # launches on this backend); the launch proxy counts
            # jaxpr-level launch-rooted ops (dot/pallas/gather/...) —
            # backend-independent, what the fused collapse shows on a
            # CPU census with interpret-routed kernels
            "fused_decode": self._fused_mode is not None,
            "fused_decode_mode": self._fused_mode or "off",
            "kernels_per_tick": self._kcensus.get(
                "verify" if self._gamma else "decode", {}).get(
                "hlo_kernels", 0),
            "kernel_launch_proxy_per_tick": self._kcensus.get(
                "verify" if self._gamma else "decode", {}).get(
                "launch_proxy", 0),
            "chunked_prefill": self._chunked,
            "prefix_cache_enabled": self._prefix_on,
            "prefix_blocks_reused": self._n_prefix_blocks,
            "prefix_tokens_reused": self._n_prefix_tokens,
            "prefix_hit_rate":
                self._n_prefix_tokens / self._n_prompt_tokens
                if self._n_prompt_tokens else 0.0,
            "cow_copies": self._n_cow,
            "cache_evictions": self._alloc.evictions,
            "cached_blocks": self._alloc.cached_blocks,
            # KV-pool quantization keys are ALWAYS present (fp engines
            # report the fp dtype/bytes), so dashboards never KeyError
            # across a mixed fleet or a PADDLE_TPU_KV_INT8=0 rollback
            "kv_cache_dtype": self._kv_dtype_name,
            "kv_pool_bytes": self._kv_pool_bytes,
            "kv_bytes_per_step": self._kv_step_bytes_last,
            # disaggregated-cluster keys: ALWAYS present (0 /
            # role="both" on a standalone engine) so fleet dashboards
            # never KeyError on a mixed colocated/disaggregated fleet
            "role": self._role,
            "prefills_exported": self._n_handoffs,
            "kv_blocks_exported": self._n_blocks_exported,
            "kv_blocks_imported": self._n_blocks_imported,
            # preemptive-scheduler + host-tier keys: ALWAYS present
            # (zeros under the PADDLE_TPU_PREEMPT=0 kill switch or
            # enable_preemption=False), so dashboards never KeyError
            # across a mixed or rolled-back fleet
            "preemption_enabled": self._preempt_on,
            "preemptions": self._n_preempt,
            "kv_blocks_spilled": self._n_spilled,
            "kv_blocks_restored": self._n_restored,
            "host_tier_bytes": self._host_tier.bytes_used
            if self._host_tier is not None else 0,
            "host_tier_capacity_bytes": self._host_tier.capacity
            if self._host_tier is not None else 0,
            "preempt_swap_resumes": self._n_swap_resumes,
            "preempt_recompute_resumes": self._n_recompute_resumes,
            "prefill_rows_per_s_est": round(self._prefill_rows_s, 3),
            "host_xfer_bytes_per_s_est": round(self._xfer_bytes_s, 1),
            "requests_shed": self._n_shed,
            "requests_timed_out": self._n_timeout,
            "requests_cancelled": self._n_cancelled,
            # live-session migration (ISSUE 19): ALWAYS present (0 on
            # engines that never joined an elastic cluster) so
            # dashboards never KeyError across a mixed fleet
            "sessions_migrated_out": self._n_migrated_out,
            "sessions_migrated_in": self._n_migrated_in,
            # multi-LoRA keys: ALWAYS present (False/0 on base-model
            # or PADDLE_TPU_LORA=0 engines) so dashboards never
            # KeyError across a mixed or rolled-back fleet
            "lora_enabled": self._lora_on,
            "lora_adapters_resident": self._lora_pool.n_resident
            if self._lora_pool is not None else 0,
            "lora_adapter_swaps": self._lora_pool.swaps
            if self._lora_pool is not None else 0,
            "lora_host_tier_bytes": self._lora_pool.host_tier_bytes
            if self._lora_pool is not None else 0,
            "tp_degree": self._tp,
            # always present (0 / full pool when single-device), so a
            # tp_degree>1 request downgraded by the PADDLE_TPU_SERVE_TP=0
            # kill switch never KeyErrors stats() consumers mid-rollback
            "tp_collective_bytes_per_step": self._tp_step_bytes,
            "tp_collective_bytes_total": self._n_tp_bytes,
            "tp_pool_bytes_per_shard": self._pool_bytes_per_shard,
            # MoE keys are ALWAYS present (False/0.0 for dense models)
            # so dashboards and rollbacks never KeyError on a mixed
            # fleet
            "moe": self._moe,
            "moe_fused_gmm": self._moe_fused_traced,
            "moe_routing_entropy": self._moe_ent_last,
            "moe_expert_load_max": self._moe_load_max_last,
            "moe_dispatches": self._n_moe_dispatches,
            # request-lifecycle tracing + SLO latency digests: ALWAYS
            # present (zeroed summaries on an idle engine; the digests
            # run regardless of the PADDLE_TPU_TRACE kill switch) —
            # each *_ms value is a P² digest summary {count, mean,
            # min, max, p50, p95, p99}
            "engine_id": self._engine_id,
            "tracing": self._trace is not None,
            "trace_events": len(self._trace)
            if self._trace is not None else 0,
            # ring-wrap loss accounting (ISSUE 15 satellite): events
            # the bounded PADDLE_TPU_TRACE_EVENTS ring overwrote —
            # the observer is no longer unobservable (0 when killed)
            "trace_events_dropped": self._trace.dropped
            if self._trace is not None else 0,
            # on-demand profiling windows: completed captures +
            # ticks left in an armed window (both 0 when idle/killed)
            "profile_captures": self._prof.captures,
            "profile_ticks_remaining": self._prof.pending,
            # per-tick roofline attribution (always present — an
            # un-ticked engine reports zeros; cpu_proxy flags
            # nominal off-TPU peaks)
            "roofline": self._roofline(),
            "ttft_ms": self._d_ttft.summary(),
            "itl_ms": self._d_itl.summary(),
            "queue_wait_ms": self._d_queue.summary(),
            "e2e_ms": self._d_e2e.summary(),
            # tree-speculation keys: ALWAYS present (zeroed digest /
            # 0 nodes on linear-spec and non-speculative engines) so
            # dashboards never KeyError across a mixed or
            # PADDLE_TPU_SPEC_TREE=0 rolled-back fleet.
            # spec_accept_len is the P² digest of tokens emitted per
            # slot verify window (accepted + bonus)
            "spec_accept_len": self._d_accept.summary(),
            "spec_tree_nodes": (len(self._spec_tree) + 1)
            if self._spec_tree is not None else 0,
            # fleet-health keys: ALWAYS present (1.0 score / zeros
            # under the PADDLE_TPU_HEALTH=0 kill switch) so
            # dashboards never KeyError across a mixed or rolled-back
            # fleet. alerts_firing is a COUNT here; the named set
            # lives in engine.health()["alerts_firing"].
            "health_score": self._health.score()
            if self._health is not None else 1.0,
            "alerts_firing": len(self._health.firing())
            if self._health is not None else 0,
            "alerts_fired_total": self._health.fired_total
            if self._health is not None else 0,
            "incidents_captured": self._health._incident.captured
            if self._health is not None
            and self._health._incident is not None else 0,
            "nonfinite_logits_ticks": self._nonfinite_ticks,
            # async-tick-pipeline keys: ALWAYS present (0 depth / 0
            # flushes under the PADDLE_TPU_ASYNC_TICK=0 kill switch or
            # async_depth unset; host_gap_ms observes on sync engines
            # too — their gap includes the blocking fetch the pipeline
            # removes) so dashboards never KeyError across a mixed or
            # rolled-back fleet
            "async_depth": self._async_depth,
            "pipeline_flushes": self._n_pipe_flushes,
            "host_gap_ms": self._d_host_gap.summary(),
        }
        if self._gamma:
            out.update({
                "spec_tokens_proposed": self._n_spec_proposed,
                "spec_tokens_accepted": self._n_spec_accepted,
                "spec_acceptance_rate":
                    self._n_spec_accepted / self._n_spec_proposed
                    if self._n_spec_proposed else 0.0,
                "spec_mean_accepted_len":
                    self._n_spec_emitted / self._n_spec_verifies
                    if self._n_spec_verifies else 0.0,
            })
        return out

    def health(self) -> Optional[dict]:
        """Health snapshot: score, firing alerts, burn rates, per-alert
        state, and the recent transition journal. None when the health
        engine is off (``health=False`` or ``PADDLE_TPU_HEALTH=0``)."""
        if self._health is None:
            return None
        return self._health.snapshot()

    def watchdog_stuck(self) -> bool:
        """Stuck-tick watchdog probe (the cluster sweep calls this
        between ticks): True when this engine's last completed
        non-compile tick blew the deadline ``max(floor, mult x
        step-EMA)``. Always False when the health engine is off."""
        if self._health is None:
            return False
        ema = self._step_time.get(
            "verify" if self._gamma else "decode", 0.0)
        return self._health.watchdog_check(ema)

    def shutdown(self, check_leaks: bool = True) -> bool:
        """Engine teardown hook (tests / graceful ops restarts):
        sweeps the allocator's invariants — every block must be exactly
        one of free, LRU-cached, or owned by a live slot, with a
        bijective hash index — raising RuntimeError on any leak or
        double-accounting. Call after draining (or at any quiescent
        point; live slots' blocks are passed as the expected live
        set). Requests still waiting in the admission queue are
        drained with a terminal queue-wait observation
        (outcome="shutdown") — they would otherwise leave no latency
        record at all."""
        self._flush_pipe()      # surface in-flight tokens first
        while self._queue:
            self._queue_exit(self._queue.popleft(), "shutdown")
        self._sync_cache_metrics()
        if check_leaks:
            live = [b for s in self._slots if s is not None
                    for b in s.blocks]
            self._alloc.check_leaks(live)
        return True

    # -- disaggregated prefill -> decode ------------------------------

    def published_overlap(self, hashes) -> int:
        """Leading run of ``hashes`` (``ops/paged_cache.
        prompt_block_hashes`` output, materialized once by the caller)
        present in this engine's content index — the cluster router's
        affinity probe: the replica with the longest run already holds
        that many of the prompt's KV blocks and will prefill only the
        suffix. 0 when the prefix cache is off (nothing to hit)."""
        if not self._prefix_on:
            return 0
        n = 0
        for h in hashes:
            if self._alloc.lookup(h) is None and not (
                    self._host_tier is not None
                    and ("pub", h) in self._host_tier):
                # host-tier entries count: a spilled published block
                # restores on admission, so the replica still serves
                # the prefix without re-prefilling it
                break
            n += 1
        return n

    def pop_prefilled(self) -> List[PrefilledRequest]:
        """Collect every prefill this role="prefill" engine finished
        since the last call: each parked slot's blocks are exported
        through the ONE fixed-width export executable into a
        self-contained :class:`PrefilledRequest` payload, the prompt's
        full blocks are published into the prefix index (the next turn
        of the same session prefills only its suffix HERE — what the
        router's affinity probe keys on), and the slot is freed for
        the next admission. The caller (``EngineCluster``) imports the
        payload into a decode replica via ``admit_prefilled()``."""
        self._flush_pipe()      # commit in-flight ticks before mutating
        out = []
        for i in self._handoff_ready:
            slot = self._slots[i]
            ids = np.zeros(self._mb_xfer, np.int32)
            ids[:len(slot.blocks)] = slot.blocks
            ids_dev = self._dev(ids)
            if self._export_exec is None:
                # pools are NOT donated: the blocks stay live until
                # _release_handoff publishes + frees them
                self._export_exec = self._aot_compile(
                    "export", jax.jit(_pc.export_blocks),
                    (self._pools, ids_dev))
            payload = self._export_exec(self._pools, ids_dev)
            self._n_handoffs += 1
            self._n_blocks_exported += len(slot.blocks)
            samp = self._slot_samp[i]
            fid = None
            if self._trace is not None:
                # flow START on the exporting slot: the matching
                # finish lands wherever admit_prefilled seats the
                # payload, so the merged trace draws the handoff as
                # an arrow across the two replicas' lanes
                fid = _tracing.next_flow_id()
                self._trace.flow(
                    "kv handoff", tid=1 + i, flow_id=fid, phase="s",
                    args={"rid": slot.rid,
                          "blocks": len(slot.blocks)})
            out.append(PrefilledRequest(
                request_id=slot.rid, prompt=slot.prompt,
                first_token=int(slot.last_token),
                max_new_tokens=slot.max_new,
                n_blocks=len(slot.blocks), payload=payload,
                temperature=float(samp[0]), top_k=float(samp[1]),
                top_p=float(samp[2]), priority=int(slot.priority),
                flow_id=fid, adapter_id=slot.adapter_id))
            self._release_handoff(i)
        self._handoff_ready = []
        return out

    def admit_prefilled(self, prefilled: PrefilledRequest):
        """Admit a prefill ANOTHER engine completed (the disaggregated
        decode side): allocate this pool's blocks, import the payload
        bytes at those ids through the ONE fixed-width import
        executable, and seat a decoding slot at ``cache_len ==
        len(prompt)`` with the prefill's first token as its last token
        — exactly the state a colocated engine holds after its own
        prefill, so greedy continuation is token-exact by construction
        (int8 payloads carry data + scales, so imported blocks
        dequantize bitwise). Returns the engine-local request id, or
        None when no slot / block capacity is available right now (the
        cluster keeps the handoff pending and retries next tick). No
        TTFT is observed here — the first token already streamed from
        the prefill engine; this request's later emits feed the ITL
        digest only."""
        self._flush_pipe()      # commit in-flight ticks before mutating
        prompt = np.asarray(prefilled.prompt, np.int32).reshape(-1)
        n_real = int(prompt.size)
        max_new = int(prefilled.max_new_tokens)
        if n_real + max_new > self.config.max_model_len:
            raise ValueError(
                f"prefilled prompt ({n_real}) + max_new_tokens "
                f"({max_new}) exceeds max_model_len "
                f"({self.config.max_model_len})")
        init = _pc.blocks_for(n_real, self._bs)
        if prefilled.n_blocks != init:
            raise ValueError(
                f"prefilled payload holds {prefilled.n_blocks} blocks "
                f"but a {n_real}-token prompt needs {init} at "
                f"block_size={self._bs} — exporter and importer must "
                "share the serving layout")
        free = [i for i, s in enumerate(self._slots) if s is None]
        if not free:
            return None
        worst = self._worst_for(n_real, max_new)
        if self._alloc.free_blocks - self._reserved < worst:
            return None
        aid = getattr(prefilled, "adapter_id", None)
        lrow = 0
        if aid is not None:
            # the payload's KV was computed under this adapter — the
            # decode replica must seat it under the SAME deltas
            if self._lora_pool is None:
                raise ValueError(
                    "prefilled handoff carries adapter_id "
                    f"{int(aid)} but this engine serves the base "
                    "model only (lora_rank=0 / PADDLE_TPU_LORA=0)")
            if not self._lora_pool.known(int(aid)):
                raise ValueError(
                    f"prefilled handoff carries unknown adapter_id "
                    f"{int(aid)}: load_adapter() it on the decode "
                    "replica (the cluster broadcasts registrations)")
            lrow = self._lora_pool.acquire(int(aid))
            if lrow is None:
                return None     # every row pinned; cluster retries
            self._sync_lora_metrics()
        i = free[0]
        self._slot_adapter[i] = lrow
        blocks = self._alloc.alloc(init)
        self._reserved += worst - len(blocks)
        ids = np.zeros(self._mb_xfer, np.int32)
        ids[:init] = blocks
        ids_dev = self._dev(ids)
        if self._import_exec is None:
            self._import_exec = self._aot_compile(
                "import",
                jax.jit(_pc.import_blocks, donate_argnums=(0,)),
                (self._pools, ids_dev, prefilled.payload))
        with _quiet_donation():
            self._pools = self._import_exec(self._pools, ids_dev,
                                            prefilled.payload)
        self._n_blocks_imported += init
        self._m_kv_transfer.inc(init)
        rid = self._next_rid
        self._next_rid += 1
        self._results[rid] = []
        self._tables[i, :] = 0
        self._tables[i, :init] = blocks
        self._tables_dev = None
        tok = int(prefilled.first_token)
        self._slots[i] = _Slot(
            rid, blocks, worst, n_real, tok, max_new,
            history=list(map(int, prompt)) + [tok],
            prompt=prompt, pend_pos=None)
        self._slots[i].priority = int(getattr(prefilled, "priority",
                                              0) or 0)
        self._slots[i].adapter_id = None if aid is None else int(aid)
        self._set_slot_samp(i, prefilled)
        self._m_occupancy.set(self.num_active)
        if self._trace is not None:
            self._trace.instant(
                "admit_prefilled", tid=1 + i,
                args={"rid": rid, "blocks": init,
                      "prompt_tokens": n_real})
            fid = getattr(prefilled, "flow_id", None)
            if fid:
                self._trace.flow("kv handoff", tid=1 + i,
                                 flow_id=int(fid), phase="f",
                                 args={"rid": rid})
        return rid

    def _release_handoff(self, i):
        """Free a handed-off slot WITHOUT completion accounting — the
        request is still live, on another engine. The prompt's full
        blocks are published first (multi-turn affinity: the session's
        next turn hits this engine's prefix cache), mirroring
        ``_retire``'s publish; e2e latency belongs to the cluster's
        client-side rollup, not this engine's digest."""
        slot = self._slots[i]
        now = time.monotonic()
        self._submit_t.pop(slot.rid, None)
        self._last_emit.pop(slot.rid, None)
        if self._trace is not None:
            self._trace.emit(
                f"req{slot.rid}", tid=1 + i, t0=slot.admit_t, t1=now,
                args={"tokens": slot.n_emitted,
                      "cache_len": slot.cache_len, "handoff": True})
            self._trace.instant("handoff", tid=1 + i,
                                args={"rid": slot.rid,
                                      "blocks": len(slot.blocks)})
        if self._prefix_on and slot.cache_len >= self._bs:
            # cache position p holds history[p] for p < cache_len (the
            # sampled first token is NOT in the cache), so the publish
            # walk is identical to _retire's
            n_full = min(len(slot.blocks), slot.cache_len // self._bs)
            for b, h in zip(slot.blocks[:n_full],
                            _pc.chain_hashes(
                                self._fp,
                                slot.history[:n_full * self._bs],
                                self._bs)):
                self._alloc.publish(b, h)
        self._alloc.free(slot.blocks)
        self._reserved -= slot.worst_blocks - len(slot.blocks)
        self._tables[i, :] = 0
        self._tables_dev = None
        self._slots[i] = None
        self._set_slot_samp(i)
        self._lora_release_slot(i, slot)
        self._results.pop(slot.rid, None)
        self._m_occupancy.set(self.num_active)

    def _worst_for(self, n_real, max_new) -> int:
        """Worst-case block reservation for one request. A
        role="prefill" engine reserves only the PROMPT's blocks — the
        first token's K/V is never written there (chunked prefill
        writes prompt positions only; decode happens on the importing
        replica), so the decode horizon (max_new + gamma) would only
        inflate admission pressure on the prefill tier."""
        if self._role == "prefill":
            return _pc.blocks_for(int(n_real), self._bs)
        return _pc.blocks_for(int(n_real) + int(max_new) + self._gamma,
                              self._bs)

    # -- live session migration (elastic fleet, ISSUE 19) --------------

    def export_session(self, i) -> MigratedSession:
        """Package slot ``i``'s LIVE session for another replica
        (scale-down drain / cluster rebalancing) and free the slot
        with NO terminal accounting — the request stays live; its
        stream continues wherever ``admit_migrated`` seats the record.
        A decoding slot ships its trimmed live bytes through THE
        fixed-width export executable (shared with the disaggregated
        handoff and the preemption spill — still zero extra
        executables); a mid-re-prefill slot (partial cache) ships
        ``payload=None`` and resumes by recompute on the target.
        Nothing is published locally: the session's prefix affinity
        must FOLLOW the KV to the target (``admit_migrated``
        republishes there), not linger on a replica that is going
        away."""
        self._flush_pipe()      # commit in-flight ticks before mutating
        slot = self._slots[i]
        self._slot_props.pop(i, None)
        samp_row = self._slot_samp[i].copy()
        if slot.handoff and i in self._handoff_ready:
            self._handoff_ready.remove(i)
        # trim the verify-window overhang: blocks past cache_len hold
        # rolled-back/garbage positions — same walk as _preempt, so
        # the payload is exactly the live bytes
        keep = max(_pc.blocks_for(slot.cache_len, self._bs), 1)
        while len(slot.blocks) > keep:
            blk = slot.blocks.pop()
            self._alloc.free([blk])
            self._tables[i, len(slot.blocks)] = 0
            self._reserved += 1
            self._tables_dev = None
        if slot.resume is not None:
            # mid-re-prefill: the ORIGINAL continuation carries over;
            # its partial KV cannot back a payload
            last_token, n_emitted = slot.resume
        else:
            last_token, n_emitted = slot.last_token, slot.n_emitted
        n_ctx = len(slot.history) - 1   # == cache_len for a decoding
        #                                 slot (the pending last_token
        #                                 is not in the cache)
        payload = None
        if slot.pend_pos is None and slot.blocks \
                and len(slot.blocks) <= self._mb_xfer:
            payload = _pc.payload_rows(
                self._export_payload(slot.blocks), len(slot.blocks))
        fid = None
        now = time.monotonic()
        if self._trace is not None:
            fid = _tracing.next_flow_id()
            self._trace.flow(
                "kv migrate", tid=1 + i, flow_id=fid, phase="s",
                args={"rid": slot.rid, "blocks": len(slot.blocks)})
            self._trace.emit(
                f"req{slot.rid}", tid=1 + i, t0=slot.admit_t, t1=now,
                args={"tokens": slot.n_emitted,
                      "cache_len": slot.cache_len, "migrated": True})
        rec = MigratedSession(
            request_id=slot.rid,
            prompt=np.asarray(slot.prompt, np.int32),
            history=list(map(int, slot.history)),
            cache_len=int(n_ctx), last_token=int(last_token),
            n_emitted=int(n_emitted),
            max_new_tokens=int(slot.max_new),
            worst_blocks=int(slot.worst_blocks),
            n_blocks=_pc.blocks_for(n_ctx, self._bs), payload=payload,
            temperature=float(samp_row[0]), top_k=float(samp_row[1]),
            top_p=float(samp_row[2]), priority=int(slot.priority),
            adapter_id=slot.adapter_id, flow_id=fid)
        self._alloc.free(slot.blocks)
        self._reserved -= slot.worst_blocks - len(slot.blocks)
        self._tables[i, :] = 0
        self._tables_dev = None
        self._slots[i] = None
        self._set_slot_samp(i)
        self._lora_release_slot(i, slot)
        self._submit_t.pop(slot.rid, None)
        self._last_emit.pop(slot.rid, None)
        self._slo_ok.pop(slot.rid, None)
        self._results.pop(slot.rid, None)
        self._n_migrated_out += 1
        self._m_occupancy.set(self.num_active)
        return rec

    def admit_migrated(self, rec: MigratedSession):
        """Seat a LIVE session ANOTHER replica exported: allocate
        blocks, import the payload bytes through THE fixed-width
        import executable, and seat a DECODING slot at the exact
        continuation point — cache_len, last token, emit count,
        history, sampling row, priority, adapter pin — so the resumed
        stream is token-exact vs never-migrated by construction (int8
        payloads carry data + per-row scales, bitwise like the
        handoff). ``payload=None`` seats the recompute path instead:
        the context re-prefills through the ordinary chunk machinery
        and ``_finish_prefill`` restores the continuation — still
        token-exact (it IS the preemption recompute resume). The
        session's full blocks are PUBLISHED here at import, so the
        router's prefix-affinity probe follows the KV to this replica
        (the source unpublished at export). Returns the engine-local
        rid, or None when no slot / block / adapter-row capacity is
        available right now (the cluster retries or tries another
        replica). No TTFT is observed — the session already
        streamed; later emits feed the ITL digest only."""
        if self._role == "prefill":
            raise ValueError(
                "a role='prefill' engine cannot seat a migrated "
                "session: migration targets must decode")
        self._flush_pipe()      # commit in-flight ticks before mutating
        n_ctx = int(rec.cache_len)
        history = list(map(int, rec.history))
        if len(history) > self.config.max_model_len:
            raise ValueError(
                f"migrated session history ({len(history)} tokens) "
                f"exceeds max_model_len ({self.config.max_model_len})"
                " — exporter and importer must share the serving "
                "layout")
        payload = rec.payload
        need = _pc.blocks_for(n_ctx, self._bs)
        if payload is not None and int(rec.n_blocks) != need:
            raise ValueError(
                f"migrated payload holds {rec.n_blocks} blocks but a "
                f"{n_ctx}-token cache needs {need} at block_size="
                f"{self._bs} — exporter and importer must share the "
                "serving layout")
        ctx = np.asarray(history[:n_ctx], np.int32)
        free = [i for i, s in enumerate(self._slots) if s is None]
        if not free:
            return None
        worst = int(rec.worst_blocks)
        if self._alloc.free_blocks - self._reserved < worst:
            return None
        aid = rec.adapter_id
        lrow = 0
        if aid is not None:
            if self._lora_pool is None:
                raise ValueError(
                    f"migrated session carries adapter_id {int(aid)} "
                    "but this engine serves the base model only "
                    "(lora_rank=0 / PADDLE_TPU_LORA=0)")
            if not self._lora_pool.known(int(aid)):
                raise ValueError(
                    "migrated session carries unknown adapter_id "
                    f"{int(aid)}: load_adapter() it on the target "
                    "(the cluster broadcasts registrations)")
            lrow = self._lora_pool.acquire(int(aid))
            if lrow is None:
                return None     # every row pinned; caller retries
            self._sync_lora_metrics()
        i = free[0]
        self._slot_adapter[i] = lrow
        rid = self._next_rid
        self._next_rid += 1
        self._results[rid] = []
        if payload is not None:
            n_blocks = int(rec.n_blocks)
            blocks = self._alloc.alloc(n_blocks)
            self._import_payload(blocks, payload)
            self._n_blocks_imported += n_blocks
            self._m_kv_transfer.inc(n_blocks)
            self._reserved += worst - n_blocks
            self._tables[i, :] = 0
            self._tables[i, :n_blocks] = blocks
            self._tables_dev = None
            slot = _Slot(rid, blocks, worst, n_ctx,
                         int(rec.last_token),
                         int(rec.max_new_tokens),
                         history=list(history), prompt=ctx,
                         pend_pos=None)
            slot.n_emitted = int(rec.n_emitted)
            # publish the session's full blocks NOW: the prefix
            # affinity that pointed at the source must resolve HERE
            # from the next router probe on (positions < cache_len
            # are committed — decode appends never write a published
            # block, same invariant as _retire's publish-then-free)
            if self._prefix_on and n_ctx >= self._bs:
                n_full = min(len(blocks), n_ctx // self._bs)
                for b, h in zip(blocks[:n_full],
                                _pc.chain_hashes(
                                    self._fp,
                                    history[:n_full * self._bs],
                                    self._bs)):
                    self._alloc.publish(b, h)
            mode = "swap"
        else:
            blocks, cached = self._map_prefix(ctx, n_ctx)
            self._reserved += worst - len(blocks)
            self._tables[i, :] = 0
            if self._ragged or not (self._chunked
                                    and self._chunk_budget > 0):
                self._tables[i, :len(blocks)] = blocks
            self._tables_dev = None
            slot = _Slot(rid, blocks, worst, cached, None,
                         int(rec.max_new_tokens),
                         history=list(history), prompt=ctx,
                         pend_pos=cached)
            slot.resume = (int(rec.last_token), int(rec.n_emitted))
            mode = "recompute"
        slot.priority = int(rec.priority)
        slot.adapter_id = None if aid is None else int(aid)
        self._slots[i] = slot
        self._set_slot_samp(i, rec)
        self._n_migrated_in += 1
        self._m_occupancy.set(self.num_active)
        if self._trace is not None:
            self._trace.instant(
                "admit_migrated", tid=1 + i,
                args={"rid": rid, "cache_len": n_ctx, "mode": mode})
            if rec.flow_id:
                self._trace.flow("kv migrate", tid=1 + i,
                                 flow_id=int(rec.flow_id), phase="f",
                                 args={"rid": rid})
        if mode != "swap":
            # shared suffix-boundary block: COW before the recomputed
            # tail writes into it (same as _seat_resume's path)
            bidx = cached // self._bs
            if self._alloc.is_shared(blocks[bidx]):
                self._cow(i, bidx)
            if not self._ragged and self._chunk_budget <= 0:
                tok = self._advance_prefill(i)
                self._finish_prefill(i, tok, [])
        return rid

    def drain_sessions(self):
        """Drain this engine for a scale-down: every RESIDENT session
        leaves as a :class:`MigratedSession` (live-migrated — the
        client's stream continues on the target, token-exact), every
        queued-but-unserved request comes back as its ServingRequest
        for plain re-routing, and the engine ends empty. Preempted
        queue residents (resume-carrying) migrate too, shipping their
        host-tier spill payload when one survives (a missing payload
        degrades to the recompute path on the target — correctness
        never depends on the tier). Mid-prefill slots that have
        streamed nothing are preempted back to the queue first (there
        is nothing to move) and leave as fresh requests. Parked
        handoff slots are NOT drained here — collect them with
        ``pop_prefilled()`` first; their payloads are self-contained.
        Queue exits observe outcome="migrated". Returns
        ``(migrations, fresh_requests)``."""
        self._flush_pipe()      # commit in-flight ticks before mutating
        for i, slot in enumerate(self._slots):
            if slot is None or slot.handoff:
                continue
            if slot.pend_pos is not None and slot.resume is None:
                # streamed nothing yet: cheaper to re-prefill on the
                # target than to move a partial cache (counts as a
                # preemption; the published blocks are purged by the
                # caller, so the warm-start publish is moot here)
                self._preempt(i)
        migrations, fresh = [], []
        while self._queue:
            req = self._queue.popleft()
            self._queue_exit(req, "migrated")
            if req.resume is not None:
                migrations.append(self._migrate_queued(req))
            else:
                fresh.append(req)
        for i, slot in enumerate(self._slots):
            if slot is not None and not slot.handoff:
                migrations.append(self.export_session(i))
        return migrations, fresh

    def _migrate_queued(self, req) -> MigratedSession:
        """A PREEMPTED request still waiting to resume leaves the
        queue as a migration record: its continuation state rides the
        resume dict, its KV rides the host-tier spill payload (when
        one survives — otherwise the target recomputes from
        history)."""
        r = req.resume
        rid = req.request_id
        payload = None
        if self._host_tier is not None and r.get("key") is not None:
            payload = self._host_tier.get(r["key"])
            self._host_tier.pop(r["key"], restore=False)
            self._m_host_bytes.set(self._host_tier.bytes_used)
        self._last_emit.pop(rid, None)
        self._slo_ok.pop(rid, None)
        self._results.pop(rid, None)
        self._n_migrated_out += 1
        return MigratedSession(
            request_id=rid, prompt=np.asarray(req.prompt, np.int32),
            history=list(map(int, r["history"])),
            cache_len=int(r["cache_len"]),
            last_token=int(r["last_token"]),
            n_emitted=int(r["n_emitted"]),
            max_new_tokens=int(req.max_new_tokens),
            worst_blocks=int(r["worst_blocks"]),
            n_blocks=int(r["n_blocks"]), payload=payload,
            temperature=req.temperature, top_k=req.top_k,
            top_p=req.top_p, priority=int(req.priority),
            adapter_id=req.adapter_id)

    def shed_queued(self, n: int) -> list:
        """Pop up to ``n`` queued-but-unserved FRESH requests (newest
        first — the oldest waiters keep their place) for the cluster
        to re-route after a scale-up: without this, new capacity only
        absorbs future arrivals while the burst that triggered the
        scale keeps queueing here. Preempted resume-carrying waiters
        are skipped — their KV lives on this replica. Queue exits
        observe outcome="migrated", same as a scale-down drain."""
        out, keep = [], []
        while self._queue and len(out) < int(n):
            req = self._queue.pop()
            if req.resume is not None:
                keep.append(req)
                continue
            self._queue_exit(req, "migrated")
            out.append(req)
        while keep:
            self._queue.append(keep.pop())
        return out

    def purge_published(self) -> int:
        """Wipe this engine's prefix-affinity surface — the
        allocator's content index AND the host tier's published-block
        spill entries — so ``published_overlap()`` scores 0 from now
        on. Called when a replica drains (scale-down) or fails: the
        router must never again steer a multi-turn session at KV this
        replica no longer serves. Returns the number of index entries
        dropped."""
        n = self._alloc.unpublish_all()
        if self._host_tier is not None:
            n += self._host_tier.purge_published()
            self._m_host_bytes.set(self._host_tier.bytes_used)
        self._sync_cache_metrics()
        return n

    def warm_migration(self):
        """Pre-build the export/import executable pair off the hot
        path (scale-up warm): one null-block round trip, so the first
        real migration or handoff on this replica compiles nothing —
        the zero-steady-state-recompile pin holds across scale
        cycles."""
        payload = _pc.payload_rows(self._export_payload([]), 0)
        if self._role != "prefill":
            self._import_payload([], payload)

    # -- tracing ------------------------------------------------------

    @property
    def tracer(self):
        """This engine's span tracer, or None when tracing is disabled
        (``PADDLE_TPU_TRACE=0``)."""
        return self._trace

    def dump_trace(self, path: str):
        """Write this engine's request-lifecycle trace as Chrome
        trace-event JSON (load it at https://ui.perfetto.dev or
        chrome://tracing). Returns the path written, or None when
        tracing is disabled."""
        if self._trace is None:
            return None
        return self._trace.dump_chrome_trace(path)

    # thin alias: the fingerprint (and the prompt -> block-hash walk
    # seeded by it) lives in ops/paged_cache so the cluster router and
    # engine admission hash IDENTICALLY — see model_fingerprint /
    # prompt_block_hashes there
    _model_fingerprint = staticmethod(_pc.model_fingerprint)

    # -- tensor parallelism -------------------------------------------

    def _init_caches(self, mdl, nb):
        """Per-layer paged pools. The ``sharding``/``kv_cache_dtype``
        kwargs are passed only when needed (TP / int8), so duck-typed
        models implementing the pre-TP two-argument
        ``init_paged_caches(num_blocks, block_size)`` protocol keep
        working on the default path."""
        kw = {}
        if self._pool_sharding is not None:
            kw["sharding"] = self._pool_sharding
        if self._kv_dtype is not None:
            kw["kv_cache_dtype"] = self._kv_dtype
        return mdl.init_paged_caches(nb, self._bs, **kw)

    @staticmethod
    def _build_tp_mesh(model, draft_model, tp: int) -> Mesh:
        """Validate ``tp_degree`` against the device count and BOTH
        models' head/vocab divisibility — a clear error here instead of
        a shape crash inside shard_map tracing — then build the serving
        mesh: the first ``tp`` devices on one ``mp`` axis."""
        devs = jax.devices()
        if tp > len(devs):
            raise ValueError(
                f"tp_degree={tp} needs {tp} devices, but only "
                f"{len(devs)} are visible")
        for mdl, who in ((model, "model"), (draft_model, "draft model")):
            if mdl is None:
                continue
            c = getattr(mdl, "config", None)
            h = getattr(c, "num_attention_heads", None)
            hkv = getattr(c, "num_key_value_heads", None) or h
            v = getattr(c, "vocab_size", None)
            if hkv is not None and hkv % tp:
                ok = [d for d in range(1, hkv + 1) if hkv % d == 0]
                raise ValueError(
                    f"tp_degree={tp} does not divide the {who}'s "
                    f"num_kv_heads={hkv}: the KV block pool is sharded "
                    f"on the kv_heads dim, so tp_degree must divide it "
                    f"(valid degrees for this model: {ok})")
            if h is not None and h % tp:
                raise ValueError(
                    f"tp_degree={tp} does not divide the {who}'s "
                    f"num_attention_heads={h}")
            if v is not None and v % tp:
                raise ValueError(
                    f"tp_degree={tp} does not divide the {who}'s "
                    f"vocab_size={v} (the logits all_gather needs an "
                    f"even vocab split)")
            # MoE: the stacked expert weights shard their ffn dim over
            # mp (gate_up [e, d, 2f] / down [e, f, d] PartitionSpecs),
            # so the per-expert width must split evenly — reject here,
            # before any compile, instead of silently replicating the
            # largest parameter group in the model
            f = getattr(c, "moe_intermediate_size", None)
            if _num_experts(c) and f is not None and f % tp:
                ok = [d_ for d_ in range(1, 17) if f % d_ == 0]
                raise ValueError(
                    f"tp_degree={tp} does not divide the {who}'s "
                    f"moe_intermediate_size={f}: the stacked expert "
                    f"gate_up/down projections shard their ffn dim "
                    f"over mp (valid degrees for this model: {ok})")
        return Mesh(np.array(devs[:tp]), ("mp",))

    def _shard_params(self, binder):
        """Place every parameter under the engine mesh: params carrying
        an ``mp`` PartitionSpec (the models' Column/Row-parallel linears
        and vocab-parallel embeddings annotate these at construction)
        shard along it; everything else — norms, biases without specs,
        int8 weights/scales from ``quantize_for_inference`` — is
        replicated. The serving mesh has ONLY the ``mp`` axis, so spec
        dims naming foreign fleet axes (``dp``/``sharding``/expert
        axes, e.g. a model previously placed by stage-3 sharding)
        replicate on that dim instead of crashing NamedSharding; a
        ``mp`` dim that does not divide ``tp`` falls back to fully
        replicated (correct, just not memory-split)."""
        out = []
        from ..framework.core import as_jax
        for _, p in binder.param_items:
            arr = as_jax(p)
            spec = getattr(p, "dist_spec", None)
            pspec = None
            if spec is not None:
                dims = []
                for dim, names in enumerate(spec):
                    axes = names if isinstance(names, tuple) \
                        else (names,)
                    if "mp" in axes:
                        if arr.shape[dim] % self._tp:
                            dims = None
                            break
                        dims.append("mp")
                    else:
                        dims.append(None)
                if dims is not None:
                    pspec = P(*dims)
            if pspec is None:
                pspec = P()
            out.append(jax.device_put(
                arr, NamedSharding(self._mesh, pspec)))
        return out

    def _dev(self, x):
        """Committed device operand: under TP every scheduler-produced
        array (tables, lengths, token ids, PRNG keys, COW indices) must
        be explicitly replicated across the mesh — compiled executables
        are strict about input shardings; single-device engines keep the
        plain ``asarray``. ``device_put`` takes host arrays directly, so
        the per-token hot path pays ONE transfer, not asarray + reshard."""
        if self._mesh is None:
            return jnp.asarray(x)
        return jax.device_put(
            x, NamedSharding(self._mesh, P(*([None] * np.ndim(x)))))

    def _gather_logits(self, logits):
        """THE step's explicit cross-shard collective: all_gather the
        vocab-sharded logits over ``mp`` so sampling sees the full
        replicated row on every shard (bitwise the same concatenation
        of per-shard columns the single-device matmul produces).
        Identity when TP is off — the single-device path traces
        unchanged."""
        if self._mesh is None:
            return logits
        from ..distributed.shard_utils import shard_map_compat
        nd = logits.ndim
        spec = P(*([None] * (nd - 1) + ["mp"]))
        logits = jax.lax.with_sharding_constraint(
            logits, NamedSharding(self._mesh, spec))
        gather = shard_map_compat(
            lambda x: jax.lax.all_gather(x, "mp", axis=nd - 1,
                                         tiled=True),
            self._mesh, in_specs=(spec,),
            out_specs=P(*([None] * nd)))
        return gather(logits)

    @contextlib.contextmanager
    def _trace_ctx(self):
        """Tracing context for every ``_compile_*``: arm the fused
        decode-tick scope (``ops/pallas/decode_fused`` — thread-local
        like ``serving_tp_scope``, so only THIS engine's traces route
        through the fused kernels), and under TP activate the engine's
        mesh (the TP layers' sharding constraints and the shard_map
        attention wrapper read the global mesh at trace time) and
        un-gather the lm_head so logits leave the model vocab-sharded
        — ``_gather_logits`` is then the step's ONE explicit logits
        collective instead of a gather/re-shard pair. Everything is
        restored on exit, so nothing leaks into other code."""
        if self._mesh is None:
            with self._df.fused_decode_scope(self._fused_mode):
                yield
            return
        from ..distributed import env as _denv
        prev = _denv.get_mesh()
        heads = []
        for mdl in (self.model, self._draft_model):
            head = getattr(mdl, "lm_head", None) \
                if mdl is not None else None
            if head is not None and getattr(head, "gather_output",
                                            False):
                heads.append(head)
                head.gather_output = False
        from ..ops.pallas.paged_attention import serving_tp_scope
        _denv.set_mesh(self._mesh)
        try:
            # the fused scope is armed even under TP: serving_tp_active
            # folds into fused_decode_mode(), which reports "off" there
            # (an opaque pallas_call cannot be GSPMD-partitioned)
            with serving_tp_scope(), \
                    self._df.fused_decode_scope(self._fused_mode):
                yield
        finally:
            _denv.set_mesh(prev)
            for head in heads:
                head.gather_output = True

    def _aot_compile(self, name, jitted, args):
        """Lower + AOT-compile one serving executable. Under TP the
        traced jaxpr is also walked for the collective census (PR 2's
        ``monitor.collective_census``): explicit shard_map collectives
        appear as op rows with per-shard payload bytes; GSPMD-inserted
        ones only materialize post-partitioning and are proxied by the
        ``sharding_constraint`` row. The decode/verify census feeds the
        per-step collective-bytes counter. Every executable the engine
        ever builds flows through here, so ``executables_compiled`` in
        ``stats()`` is exact on the ragged AND legacy paths."""
        self._n_exec_compiled += 1
        tap = _moe.serving_stats_tap(self._observe_moe_routing) \
            if self._moe_tap_on else contextlib.nullcontext()
        try:
            with self._trace_ctx(), _quiet_donation(), tap:
                trace = getattr(jitted, "trace", None)
                if trace is not None:
                    traced = trace(*args)
                    exec_ = traced.lower().compile()
                    if self._mesh is not None:
                        self._census[name] = monitor.collective_census(
                            traced.jaxpr)
                    kc = monitor.kernel_census(compiled=exec_,
                                               jaxpr=traced.jaxpr)
                else:
                    # older jax: no jit().trace — the executable still
                    # compiles once; the collective census (and the
                    # byte counters it feeds) stays empty
                    exec_ = jitted.lower(*args).compile()
                    kc = monitor.kernel_census(compiled=exec_)
                self._kcensus[name] = kc
                # roofline static half: the executable's cost-model
                # FLOPs + HBM bytes (per-tick MFU / bandwidth
                # utilization divide these by the measured step time)
                cost = monitor.executable_cost(exec_)
                if cost:
                    self._exec_cost[name] = cost
                if name in ("decode", "verify"):
                    # THE tick executable: the headline fusion metric
                    self._m_kernels.set(kc.get("hlo_kernels", 0))
                return exec_
        finally:
            # which grouped kernel the trace just stamped: the honest
            # source for stats()['moe_fused_gmm'] (env/config/backend/
            # shape gates all folded in by construction)
            if self._moe and \
                    _moe.MOE_STATS["grouped_mm_kernel"] == "fused_gmm":
                self._moe_fused_traced = True

    def _observe_moe_routing(self, load, entropy):
        """Run-time sink of the MoE routing tap (armed around every
        executable trace): fed the per-expert load fractions and raw
        routing entropy of each dispatch the compiled step executes.
        Mirrors into the monitor registry AND the per-engine fields
        ``stats()`` reports."""
        load = np.asarray(load)
        e = max(int(load.size), 2)
        self._m_moe_load.observe_many(load)
        ent = float(entropy) / float(np.log(e))
        self._m_moe_entropy.set(ent)
        self._moe_ent_last = ent
        self._moe_load_max_last = float(load.max())
        self._n_moe_dispatches += 1

    def collective_census(self) -> dict:
        """Per-executable jaxpr collective census (TP engines only):
        ``{exec_name: [{op, axis, count, bytes}, ...]}`` — the ops
        dashboard / test hook behind the "exactly one logits gather
        per step" assertion."""
        return dict(self._census)

    def kernel_census(self) -> dict:
        """Per-executable kernel census
        (``monitor.kernel_census`` — optimized-HLO entry instruction
        counts + the jaxpr-level launch proxy): ``{exec_name:
        {hlo_kernels, hlo_fusions, hlo_custom_calls, launch_proxy,
        ...}}``. The decode-tick fusion headline ("kernel count per
        decode layer down") is read off the ``decode``/``verify``
        row — measured on every engine, every compile."""
        return dict(self._kcensus)

    def _tp_census_bytes(self, name) -> int:
        """Explicit per-shard ``mp`` collective payload of one
        execution of ``name`` (the census-derived per-step cost)."""
        return sum(
            r["bytes"] for r in self._census.get(name, ())
            if r["op"] != "sharding_constraint"
            and "mp" in r["axis"].split(","))

    # -- scheduler internals ------------------------------------------

    def _emit(self, rid, tok):
        """Single exit point for generated tokens (prefill's first token
        AND every decode token) — the token counters and the TTFT /
        inter-token digests live here so they agree exactly with what
        clients receive."""
        now = time.monotonic()
        prev = self._last_emit.get(rid)
        if prev is None:                # this request's FIRST token
            t0 = self._submit_t.get(rid)
            if t0 is not None:
                ttft_ms = 1000.0 * (now - t0)
                self._d_ttft.observe(ttft_ms)
                if self._health is not None:
                    self._slo_ok[rid] = ttft_ms <= self._h_slo_ttft
        else:
            itl_ms = 1000.0 * (now - prev)
            self._d_itl.observe(itl_ms)
            if self._health is not None and itl_ms > self._h_slo_itl:
                self._slo_ok[rid] = False
        self._last_emit[rid] = now
        self._results[rid].append(tok)
        self._m_tokens.inc()
        self._n_tokens += 1
        if self._stream is not None:
            self._stream(rid, tok)

    def _set_slot_samp(self, i, req=None):
        """Seat slot ``i``'s row of the per-slot sampling tensor:
        the engine defaults overlaid with the request's overrides
        (``req`` may be a ServingRequest or a PrefilledRequest — both
        carry the three optional fields). The device mirror is
        invalidated only when the row actually changes, so steady
        uniform traffic re-uploads nothing."""
        row = self._samp_default.copy()
        if req is not None:
            if getattr(req, "temperature", None) is not None:
                row[0] = float(req.temperature)
            if getattr(req, "top_k", None) is not None:
                row[1] = float(req.top_k)
            if getattr(req, "top_p", None) is not None:
                row[2] = float(req.top_p)
        if not np.array_equal(self._slot_samp[i], row):
            self._slot_samp[i] = row
            self._samp_dev = None
            self._samp_row_dev.pop(i, None)

    def _samp_operand(self):
        """The [num_slots, 3] per-slot sampling tensor, uploaded only
        after a change (the ``_tables_dev`` pattern)."""
        if self._samp_dev is None:
            self._samp_dev = self._dev(self._slot_samp)
        return self._samp_dev

    def _lora_operand(self):
        """Device image of the stacked adapter weights, re-uploaded
        only when the pool version moved (register/LRU load rewrote a
        stack row — the ``_samp_dev`` invalidation pattern). Runtime
        OPERAND, never a closure capture: baking the stacks into the
        trace would turn every adapter churn into a recompile."""
        pool = self._lora_pool
        if self._lora_dev is None \
                or self._lora_dev_version != pool.version:
            self._lora_dev = jax.tree_util.tree_map(
                self._dev, pool.operand())
            self._lora_dev_version = pool.version
        return self._lora_dev

    def _lora_release_slot(self, i, slot):
        """Unpin slot ``i``'s adapter when the slot empties (retire /
        cancel / preempt / handoff-release). The adapter STAYS
        resident — release only drops the refcount that was blocking
        LRU eviction."""
        self._slot_adapter[i] = 0
        if self._lora_pool is not None \
                and getattr(slot, "adapter_id", None) is not None:
            self._lora_pool.release(slot.adapter_id)
            self._sync_lora_metrics()

    def _sync_lora_metrics(self):
        pool = self._lora_pool
        if pool is None:
            return
        self._m_lora_resident.set(pool.n_resident)
        self._m_lora_host.set(pool.host_tier_bytes)
        d = pool.swaps - self._lora_swaps_seen
        if d > 0:
            self._m_lora_swaps.inc(d)
            self._lora_swaps_seen = pool.swaps

    def _samp_row(self, i):
        """One slot's [3] sampling row for the single-slot executables
        (chunk / bucketed prefill) — cached per admission so a long
        prompt's chunk loop pays ONE upload, not one per chunk."""
        row = self._samp_row_dev.get(i)
        if row is None:
            row = self._samp_row_dev[i] = self._dev(self._slot_samp[i])
        return row

    def _select_rows(self, lg, key, samp):
        """Per-slot token selection: ``samp``'s trailing axis is
        (temperature, top_k, top_p) — traced DATA through the shared
        ``_filter_logits`` pipeline, so every sampling config rides
        one executable. ``lg``: [S, V] (or any leading shape samp
        broadcasts over); greedy engines argmax and never read
        ``samp``."""
        return self._select_token(
            lg, key, do_sample=self._do_sample,
            temperature=samp[..., 0], top_k=samp[..., 1],
            top_p=samp[..., 2])

    def _next_key(self):
        """Greedy decode never consumes randomness — skip the per-step
        split (one device dispatch per token saved). Under TP the key
        (and every split of it) stays replicated across shards: all
        shards draw the same sample from the same gathered logits."""
        if not self._do_sample:
            return self._key
        self._key, sub = jax.random.split(self._key)
        if self._mesh is not None:
            self._key = self._dev(self._key)
            sub = self._dev(sub)
        return sub

    def _admit(self) -> List[tuple]:
        emitted = []
        self._expire_queue()
        while self._queue:
            k = self._pick_next_idx()
            req = self._queue[k]
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                # slot-pressure preemption: a strictly-lower-priority
                # victim yields its slot to the waiting request
                # (blocks published + spilled, victim requeued at the
                # front of ITS class)
                if not self._preempt_on:
                    break
                v = self._pick_victim(below=req.priority)
                if v is None:
                    break
                self._preempt(v)
                free = [v]
            if not self._admission_fits(req):
                break
            lrow = 0
            if self._lora_pool is not None \
                    and req.adapter_id is not None:
                # pin the adapter's resident stack row for the life of
                # the slot (refcount blocks LRU eviction mid-request);
                # all rows pinned by OTHER in-flight adapters -> the
                # request waits its turn in the queue
                lrow = self._lora_pool.acquire(req.adapter_id)
                if lrow is None:
                    break
                self._sync_lora_metrics()
            # remove by IDENTITY: a preemption above appendleft'ed the
            # victim's resume request, shifting every index right —
            # ``k`` may no longer point at ``req``
            for k2, r2 in enumerate(self._queue):
                if r2 is req:
                    del self._queue[k2]
                    break
            i = free[0]
            self._slot_adapter[i] = lrow
            if req.resume is not None:
                # a preempted request re-admits through its own seat
                # path (swap-restore or recompute re-prefill)
                self._seat_resume(i, req, emitted)
                self._slots[i].adapter_id = req.adapter_id
                continue
            n_real = int(req.prompt.size)
            worst = self._worst_for(n_real, req.max_new_tokens)
            blocks, cached = self._map_prefix(req.prompt, n_real)
            self._reserved += worst - len(blocks)
            self._tables[i, :] = 0
            if self._ragged or not (self._chunked
                                    and self._chunk_budget > 0):
                # the ragged step needs the row live at once (a pending
                # slot contributes ZERO query rows, so nothing can
                # touch its blocks early — no NULL-row dance needed);
                # legacy interleaved prefill instead keeps the GLOBAL
                # table row null until the prefill completes: the
                # batched decode step masks pending slots by table
                # (null-block writes/reads are harmless by
                # construction, exactly like inactive slots) and the
                # chunk executable reads its row from ``slot.blocks``
                self._tables[i, :len(blocks)] = blocks
            self._tables_dev = None
            # observe BEFORE prefill so the histogram measures queue
            # wait, not prefill execution/compile time
            self._queue_exit(req, "admitted")
            self._results[req.request_id] = []
            self._slots[i] = _Slot(
                req.request_id, blocks, worst, cached, None,
                req.max_new_tokens,
                history=list(map(int, req.prompt)),
                prompt=np.asarray(req.prompt, np.int32),
                pend_pos=cached)
            self._slots[i].priority = int(req.priority)
            self._slots[i].adapter_id = req.adapter_id
            self._set_slot_samp(i, req)
            self._m_occupancy.set(self.num_active)
            if self._trace is not None:
                self._trace.instant(
                    "admit", tid=1 + i,
                    args={"rid": req.request_id,
                          "prefix_hit": cached > 0,
                          "cached_tokens": int(cached),
                          "prompt_tokens": n_real})
            if not self._chunked:
                tok = self._prefill_bucketed(i, req, n_real)
                self._finish_prefill(i, tok, emitted)
            else:
                # a shared suffix-boundary block (full-prompt cache
                # hit) must be copy-on-write duplicated before the
                # recomputed last token's K/V lands in it
                bidx = cached // self._bs
                if self._alloc.is_shared(blocks[bidx]):
                    self._cow(i, bidx)
                if not self._ragged and self._chunk_budget <= 0:
                    tok = self._advance_prefill(i)
                    self._finish_prefill(i, tok, emitted)
                # else: prefill rows ride the ragged step (or, on the
                # legacy interleaved path, chunks advance inside
                # step() ticks between running slots' decodes)
        self._sync_cache_metrics()
        return emitted

    # -- preemptive scheduling + host-DRAM KV tier --------------------

    def _pick_next_idx(self) -> int:
        """Queue position to admit next: highest priority class first,
        FIFO within a class (stable max — the leftmost of the winning
        class; a preempted request re-enters via ``appendleft``, so it
        leads its class). Plain FIFO when preemption is off."""
        if not self._preempt_on or len(self._queue) < 2:
            return 0
        best, bp = 0, self._queue[0].priority
        for k in range(1, len(self._queue)):
            p = self._queue[k].priority
            if p > bp:
                best, bp = k, p
        return best

    def _expire_queue(self):
        """Queue-wait timeouts: requests queued past their
        ``max_queue_wait_ms`` exit with outcome="timeout" and an empty
        result (the stream never started). Preempted requests are
        exempt — they already streamed tokens; timing them out
        mid-stream would truncate a live response."""
        if not any(r.max_queue_wait_ms is not None
                   for r in self._queue):
            return
        now = time.monotonic()
        kept = deque()
        for r in self._queue:
            w = r.max_queue_wait_ms
            if w is not None and r.resume is None \
                    and 1000.0 * (now - r.submit_time) > float(w):
                self._n_timeout += 1
                self._queue_exit(r, "timeout")
                self._finish_unserved(r, record_empty=True)
            else:
                kept.append(r)
        self._queue = kept

    def _admission_fits(self, req) -> bool:
        """Admission block policy. The worst-case reservation check
        (prompt + max_new + gamma covered for EVERY active slot) stays
        the first gate — when the pool is ample, behavior is identical
        to the pre-preemption scheduler. When it fails and the
        preemptive scheduler is on, the WATERMARK policy may overcommit:
        admit on the immediately-needed blocks plus
        ``admission_watermark_blocks`` of growth headroom, preempting
        strictly-lower-priority victims to reach it — growth past the
        headroom is reclaimed by preemption against the host tier.
        Resume re-admissions need only their restored block set (their
        reservation was already granted once)."""
        if req.resume is not None:
            need, target = int(req.resume["n_blocks"]), 0
        else:
            n_real = int(req.prompt.size)
            worst = self._worst_for(n_real, req.max_new_tokens)
            if self._alloc.free_blocks - self._reserved >= worst:
                return True
            if not self._preempt_on:
                return False
            need = _pc.blocks_for(n_real, self._bs)
            target = self._watermark
        while self._alloc.free_blocks - need < target:
            v = self._pick_victim(below=req.priority)
            if v is None:
                break
            self._preempt(v)
        return self._alloc.free_blocks - need >= target

    def _pick_victim(self, below=None, exclude=()):
        """Victim policy: the lowest priority class loses first;
        within a class MID-PREFILL slots lose before decoding ones
        (they have streamed nothing yet — preempting them costs no
        client-visible stall, and their full blocks publish into the
        prefix index so the re-prefill is mostly a cache hit), then
        the most recently admitted slot (LIFO — the oldest resident
        keeps its progress, which is what bounds thrash).
        Parked-handoff slots are never victims. ``below`` restricts to
        strictly-lower classes (slot/admission preemption);
        ``exclude`` keeps a growing slot from victimizing itself."""
        cands = [i for i, s in enumerate(self._slots)
                 if s is not None and not s.handoff
                 and i not in exclude
                 and (below is None or s.priority < below)]
        if not cands:
            return None
        return min(cands, key=lambda i: (
            self._slots[i].priority,
            0 if self._slots[i].pend_pos is not None else 1,
            -self._slots[i].admit_t))

    def _alloc_with_preempt(self, n, exclude=(), below=None):
        """Allocate ``n`` blocks, preempting victims under pool
        pressure (preemptive scheduler only; lowest class first,
        optionally bounded by ``below``). Raises like ``alloc`` when
        even preemption cannot cover the demand."""
        if self._preempt_on:
            while self._alloc.free_blocks < n:
                v = self._pick_victim(below=below, exclude=exclude)
                if v is None:
                    break
                self._preempt(v)
        return self._alloc.alloc(n)

    def _preempt(self, i):
        """Preempt slot ``i``: trim the verify-window overhang, publish
        the full blocks into the prefix index (the recompute path's
        warm start), spill the live bytes to the host-DRAM tier (the
        swap path), free everything, and re-enqueue the request at the
        FRONT of its priority class carrying the exact continuation
        state (cache_len / last_token / n_emitted / history / sampling
        row) — resume is token-exact by construction on either
        path."""
        self._flush_pipe()      # no-op mid-tick (pipe already drained)
        slot = self._slots[i]
        self._slot_props.pop(i, None)
        samp_row = self._slot_samp[i].copy()
        # a mid-prefill slot is "pending" ONLY when it carries no
        # continuation: a previously-preempted request re-prefilling
        # its context (slot.resume set) must keep that continuation —
        # treating it as fresh would reset n_emitted and overrun the
        # client's stream past max_new
        pending = slot.pend_pos is not None and slot.resume is None
        # 1) blocks past cache_len hold rolled-back/garbage positions
        # (or not-yet-prefilled prompt room, for a mid-prefill victim)
        # — return them first so the spill payload is exactly live
        # bytes
        keep = max(_pc.blocks_for(slot.cache_len, self._bs), 1)
        while len(slot.blocks) > keep:
            blk = slot.blocks.pop()
            self._alloc.free([blk])
            self._tables[i, len(slot.blocks)] = 0
            self._reserved += 1
            self._tables_dev = None
        # 2) publish full blocks (same walk as _retire)
        if self._prefix_on and slot.cache_len >= self._bs:
            n_full = min(len(slot.blocks), slot.cache_len // self._bs)
            for b, h in zip(slot.blocks[:n_full],
                            _pc.chain_hashes(
                                self._fp,
                                slot.history[:n_full * self._bs],
                                self._bs)):
                self._alloc.publish(b, h)
        # 3) spill live bytes to the host tier (swap-resume payload).
        # A MID-PREFILL victim skips the spill: it has streamed
        # nothing, so it requeues as a FRESH request — its published
        # full blocks (step 2) make the re-prefill mostly a prefix-
        # cache hit, no continuation state needed.
        key = None
        nbytes = 0
        if slot.pend_pos is None and self._host_tier is not None \
                and slot.blocks \
                and len(slot.blocks) <= self._mb_xfer:
            # spill only a fully-valid cache (a decoding victim); a
            # mid-re-prefill victim keeps its continuation but its
            # partial KV cannot back a swap — it resumes by recompute
            payload = _pc.payload_rows(
                self._export_payload(slot.blocks), len(slot.blocks))
            nbytes = _pc.payload_nbytes(payload)
            key = ("victim", slot.rid)
            if self._host_tier.put(key, payload, nbytes):
                self._n_spilled += len(slot.blocks)
                self._m_spill.inc(len(slot.blocks))
            else:
                key = None      # refused (too big): recompute resume
            self._m_host_bytes.set(self._host_tier.bytes_used)
        n_spilled_blocks = len(slot.blocks) if key is not None else 0
        # 4) free the blocks (published ones park in the LRU cache)
        self._alloc.free(slot.blocks)
        self._reserved -= slot.worst_blocks - len(slot.blocks)
        self._tables[i, :] = 0
        self._tables_dev = None
        self._slots[i] = None
        self._set_slot_samp(i)
        # the adapter pin drops with the slot (LRU may now evict it);
        # re-admission re-acquires, reloading from the host registry
        # if churn swapped it out meanwhile — the request carries the
        # ID, never a stack-row index
        self._lora_release_slot(i, slot)
        self._m_occupancy.set(self.num_active)
        # 5) re-enqueue at the front of its class; a DECODING victim
        # carries the exact continuation state, a mid-prefill victim
        # goes back as a fresh request (nothing streamed yet). The
        # ORIGINAL submit time anchors queue-wait/e2e observations
        # either way.
        resume = None
        if not pending:
            if slot.resume is not None:
                # twice-preempted mid-re-prefill: the ORIGINAL
                # continuation carries over; its full context is the
                # stored history minus the pending last_token
                last_token, n_emitted = slot.resume
            else:
                last_token, n_emitted = slot.last_token, slot.n_emitted
            n_ctx = len(slot.history) - 1   # == cache_len for a
            #                                 decoding victim
            resume = {"cache_len": int(n_ctx),
                      "last_token": int(last_token),
                      "n_emitted": int(n_emitted),
                      "history": list(slot.history),
                      "worst_blocks": int(slot.worst_blocks),
                      "n_blocks": _pc.blocks_for(n_ctx, self._bs),
                      "nbytes": int(nbytes), "key": key}
        req = ServingRequest(
            slot.rid, np.asarray(slot.prompt, np.int32), slot.max_new,
            temperature=float(samp_row[0]) if self._do_sample
            else None,
            top_k=int(samp_row[1]) if self._do_sample else None,
            top_p=float(samp_row[2]) if self._do_sample else None,
            priority=int(slot.priority), resume=resume,
            adapter_id=slot.adapter_id)
        req.submit_time = self._submit_t.get(slot.rid,
                                             req.submit_time)
        self._queue.appendleft(req)
        self._n_preempt += 1
        self._m_preempt.inc()
        if self._trace is not None:
            self._trace.instant(
                "preempt", tid=1 + i,
                args={"rid": slot.rid, "priority": int(slot.priority),
                      "cache_len": int(slot.cache_len),
                      "blocks_spilled": n_spilled_blocks})

    def _seat_resume(self, i, req, emitted):
        """Re-admit a preempted request into slot ``i`` exactly where
        it stopped. Swap: import the spilled bytes at freshly
        allocated blocks (bitwise the preempted pool state) and seat
        the slot ACTIVE. Recompute: map the published prefix blocks
        (the prefix cache IS the recompute fast path) and re-prefill
        only what eviction lost, through the ordinary chunk machinery;
        ``_finish_prefill`` then restores the continuation instead of
        emitting. Either way last_token / n_emitted / history / the
        sampling row carry over, so the resumed stream is token-exact
        vs never-preempted."""
        r = req.resume
        rid = req.request_id
        n_ctx = int(r["cache_len"])
        ctx = np.asarray(r["history"][:n_ctx], np.int32)
        payload = None
        if self._host_tier is not None and r["key"] is not None:
            payload = self._host_tier.get(r["key"])
        mode = self._resume_mode(r, payload)
        self._queue_exit(req, "resumed")
        if rid not in self._results:        # kept across preemption
            self._results[rid] = []
        if mode == "swap":
            n_blocks = int(r["n_blocks"])
            blocks = self._alloc.alloc(n_blocks)
            self._import_payload(blocks, payload)
            self._host_tier.pop(r["key"])
            self._n_restored += n_blocks
            self._m_restore.inc(n_blocks)
            self._m_host_bytes.set(self._host_tier.bytes_used)
            self._n_swap_resumes += 1
            self._reserved += int(r["worst_blocks"]) - n_blocks
            self._tables[i, :] = 0
            self._tables[i, :n_blocks] = blocks
            self._tables_dev = None
            slot = _Slot(rid, blocks, int(r["worst_blocks"]), n_ctx,
                         int(r["last_token"]), int(req.max_new_tokens),
                         history=list(r["history"]), prompt=ctx,
                         pend_pos=None)
            slot.n_emitted = int(r["n_emitted"])
        else:
            if self._host_tier is not None and r["key"] is not None:
                # the stale payload (if any) will never be imported
                self._host_tier.pop(r["key"], restore=False)
                self._m_host_bytes.set(self._host_tier.bytes_used)
            self._n_recompute_resumes += 1
            blocks, cached = self._map_prefix(ctx, n_ctx)
            self._reserved += int(r["worst_blocks"]) - len(blocks)
            self._tables[i, :] = 0
            if self._ragged or not (self._chunked
                                    and self._chunk_budget > 0):
                self._tables[i, :len(blocks)] = blocks
            self._tables_dev = None
            slot = _Slot(rid, blocks, int(r["worst_blocks"]), cached,
                         None, int(req.max_new_tokens),
                         history=list(r["history"]), prompt=ctx,
                         pend_pos=cached)
            slot.resume = (int(r["last_token"]), int(r["n_emitted"]))
        slot.priority = int(req.priority)
        self._slots[i] = slot
        self._set_slot_samp(i, req)
        self._m_occupancy.set(self.num_active)
        if self._trace is not None:
            self._trace.instant(
                "resume", tid=1 + i,
                args={"rid": rid, "mode": mode, "cache_len": n_ctx})
        if mode != "swap":
            # a shared suffix-boundary block is COW'd before the
            # recomputed tail writes into it — same as a fresh
            # admission's full-prompt-hit path
            bidx = cached // self._bs
            if self._alloc.is_shared(blocks[bidx]):
                self._cow(i, bidx)
            if not self._ragged and self._chunk_budget <= 0:
                tok = self._advance_prefill(i)
                self._finish_prefill(i, tok, emitted)

    def _resume_mode(self, r, payload) -> str:
        """Recompute-vs-swap, per victim: restore time ~= payload
        bytes / measured host-transfer bandwidth; recompute time ~=
        cached tokens / measured chunk-prefill row throughput. A
        missing payload (tier off, dropped under pressure, or refused)
        forces recompute; un-measured rates default to swap (bytes
        beat re-running the model until the prefill rate proves
        otherwise). ``ServingConfig.preempt_resume`` pins one path."""
        if payload is None:
            return "recompute"
        if self._resume_policy in ("swap", "recompute"):
            return self._resume_policy
        if self._prefill_rows_s > 0 and self._xfer_bytes_s > 0:
            t_swap = float(r["nbytes"]) / self._xfer_bytes_s
            t_rec = float(r["cache_len"]) / self._prefill_rows_s
            return "swap" if t_swap <= t_rec else "recompute"
        return "swap"

    def _export_payload(self, blocks):
        """Gather ``blocks``' self-contained bytes to host DRAM through
        THE fixed-width export executable (shared with the
        disaggregated handoff — compiled once per engine). The
        ``payload_to_host`` materialization blocks on the gather, so
        the timing feeds the cost model's transfer-bandwidth EMA."""
        ids = np.zeros(self._mb_xfer, np.int32)
        ids[:len(blocks)] = blocks
        ids_dev = self._dev(ids)
        if self._export_exec is None:
            self._export_exec = self._aot_compile(
                "export", jax.jit(_pc.export_blocks),
                (self._pools, ids_dev))
        t0 = time.monotonic()
        host = _pc.payload_to_host(
            self._export_exec(self._pools, ids_dev))
        self._note_xfer(_pc.payload_nbytes(host),
                        time.monotonic() - t0)
        return host

    def _import_payload(self, blocks, payload):
        """Scatter a host payload back into this engine's pools at
        ``blocks`` through THE fixed-width import executable (shared
        with ``admit_prefilled`` — compiled once). Short payloads are
        zero-padded back to the fixed width; pad rows scatter into the
        null block."""
        ids = np.zeros(self._mb_xfer, np.int32)
        ids[:len(blocks)] = blocks
        ids_dev = self._dev(ids)
        dev = self._payload_dev(
            _pc.payload_pad(payload, self._mb_xfer))
        if self._import_exec is None:
            self._import_exec = self._aot_compile(
                "import",
                jax.jit(_pc.import_blocks, donate_argnums=(0,)),
                (self._pools, ids_dev, dev))
        with _quiet_donation():
            self._pools = self._import_exec(self._pools, ids_dev, dev)

    def _payload_dev(self, payload):
        """Host payload -> device operands for the import executable;
        under TP each array is placed with the pool's kv_head sharding
        (the compiled executable is strict about input shardings)."""
        if self._mesh is None:
            def d(x):
                if isinstance(x, _pc.QuantKV):
                    return _pc.QuantKV(jnp.asarray(x.data),
                                       jnp.asarray(x.scale))
                return jnp.asarray(x)
        else:
            dsh = self._pool_sharding
            ssh = _pc.scale_sharding(dsh)

            def d(x):
                if isinstance(x, _pc.QuantKV):
                    return _pc.QuantKV(jax.device_put(x.data, dsh),
                                       jax.device_put(x.scale, ssh))
                return jax.device_put(x, dsh)
        return [(d(k), d(v)) for k, v in payload]

    def _spill_evicted(self, b, h):
        """Allocator eviction hook (``BlockAllocator.on_evict``): an
        LRU-cached published block is being reclaimed — gather its
        bytes to the host tier first, keyed by content hash, so a
        later prefix hit restores it instead of re-prefilling. The
        export launch is issued before the evicting caller's next
        write, so the bytes read are the published ones."""
        payload = _pc.payload_rows(self._export_payload([b]), 1)
        if self._host_tier.put(("pub", h), payload,
                               _pc.payload_nbytes(payload)):
            self._n_spilled += 1
            self._m_spill.inc()
        self._m_host_bytes.set(self._host_tier.bytes_used)

    def _restore_published(self, h):
        """Host-tier prefix restore: a prompt hash that misses the
        device index but hits the host tier re-materializes the block
        — alloc (opportunistic: never preempts for a cache hit),
        import, re-publish — and the admission walk continues as if
        the block had never been evicted. Returns the block id (one
        reference, owned by the caller's slot) or None."""
        if self._host_tier is None:
            return None
        payload = self._host_tier.get(("pub", h))
        if payload is None:
            return None
        if self._alloc.free_blocks < 1:
            return None
        (b,) = self._alloc.alloc(1)
        self._import_payload([b], payload)
        self._host_tier.pop(("pub", h))
        self._alloc.publish(b, h)
        self._n_restored += 1
        self._m_restore.inc()
        self._m_host_bytes.set(self._host_tier.bytes_used)
        return b

    def _note_xfer(self, nbytes, dt):
        """Host-transfer bandwidth EMA (the swap side of the
        recompute-vs-swap cost model)."""
        if dt <= 0.0 or nbytes <= 0:
            return
        bps = nbytes / dt
        self._xfer_bytes_s = bps if not self._xfer_bytes_s \
            else 0.7 * self._xfer_bytes_s + 0.3 * bps

    def _note_prefill_rate(self, rows, dt):
        """Chunk-prefill throughput EMA (the recompute side of the
        cost model). Fed by ticks that carried prefill rows — the
        whole launch is attributed to them, so the estimate is
        conservative (recompute looks slower than it is, biasing
        toward swap; the transfer EMA is measured the same
        wall-clock way)."""
        if dt <= 0.0 or rows <= 0:
            return
        rps = rows / dt
        self._prefill_rows_s = rps if not self._prefill_rows_s \
            else 0.7 * self._prefill_rows_s + 0.3 * rps

    def queue_depth(self, priority=None):
        """Queued + active work. With ``priority`` given (and the
        preemptive scheduler on) lower-priority work is DISCOUNTED to
        0.25 — it can be preempted or bypassed by an arrival of that
        class, so it blocks the arrival far less than peer work does.
        The cluster router's priority-weighted tiebreak reads this."""
        if priority is None or not self._preempt_on:
            return self.num_queued + self.num_active
        w = 0.0
        for r in self._queue:
            w += 1.0 if r.priority >= priority else 0.25
        for s in self._slots:
            if s is not None:
                w += 1.0 if s.priority >= priority else 0.25
        return w

    def _map_prefix(self, prompt, n_real):
        """Map the longest cached prefix of ``prompt`` — leading FULL
        blocks whose rolling content hashes hit the allocator's index
        get refcount++'d straight into the slot's block list — then
        allocate fresh blocks for the remainder. Returns ``(blocks,
        cached_tokens)``. ``cached_tokens`` is block-aligned except on
        a full-prompt hit, where the last prompt token is recomputed
        anyway (admission must produce first-token logits) and its
        shared block is COW-duplicated by the caller before the
        write."""
        init = _pc.blocks_for(n_real, self._bs)
        matched = []
        if self._prefix_on:
            # lazy hashing: a cache-cold prompt stops at block 0. THE
            # shared prompt->hash walk (ops/paged_cache) — the cluster
            # router probes replicas with exactly these keys, so a
            # router hit here IS an admission hit
            for h in _pc.prompt_block_hashes(self._fp, prompt,
                                             self._bs):
                b = self._alloc.lookup(h)
                if b is not None:
                    matched.append(self._alloc.ref(b))
                    continue
                # device-index miss: the block may have been LRU-
                # evicted INTO the host tier — restore it (one
                # fixed-width import) and keep walking
                rb = self._restore_published(h)
                if rb is None:
                    break
                matched.append(rb)
        cached = len(matched) * self._bs
        if cached >= n_real:                     # full-prompt hit
            cached = n_real - 1
        if matched:
            self._n_prefix_blocks += len(matched)
            self._n_prefix_tokens += cached
            self._m_prefix_blocks.inc(len(matched))
            self._m_prefix_tokens.inc(cached)
        self._n_prompt_tokens += n_real
        if self._prefix_on:
            self._m_hit_rate.set(
                self._n_prefix_tokens / self._n_prompt_tokens)
        fresh = self._alloc.alloc(init - len(matched)) \
            if init > len(matched) else []
        return matched + fresh, cached

    def _cow(self, i, bidx):
        """Copy-on-write: duplicate the shared block at table position
        ``bidx`` of slot ``i`` into a fresh block (ONE device block
        copy per pool — target and draft pools share block ids), swap
        it into the table, and drop this slot's reference on the
        original (which stays intact for the cache / its other
        holders)."""
        slot = self._slots[i]
        old = slot.blocks[bidx]
        (new,) = self._alloc_with_preempt(1, exclude=(i,),
                                          below=slot.priority + 1)
        if self._cow_exec is None:
            self._cow_exec = self._compile_cow(self._pools)
        with _quiet_donation():
            self._pools = self._cow_exec(
                self._pools, self._dev(np.int32(old)),
                self._dev(np.int32(new)))
        if self._draft_model is not None:
            if self._draft_cow_exec is None:
                self._draft_cow_exec = self._compile_cow(self._dpools)
            with _quiet_donation():
                self._dpools = self._draft_cow_exec(
                    self._dpools, self._dev(np.int32(old)),
                    self._dev(np.int32(new)))
        self._alloc.free([old])
        slot.blocks[bidx] = new
        slot.pend_row = None                 # (always pre-chunk today)
        if self._tables[i, bidx] == old:     # row may be null (pending)
            self._tables[i, bidx] = new
            self._tables_dev = None
        self._n_cow += 1
        self._m_cow.inc()

    def _advance_prefill(self, i, budget=None):
        """Run up to ``budget`` chunk steps (None = to completion) of
        slot ``i``'s pending prompt suffix through the ONE compiled
        chunk executable. Returns the sampled first token when the
        prefill completes, else None."""
        slot = self._slots[i]
        if self._chunk_exec is None:
            self._chunk_exec = self._compile_chunk(self._next_key())
        if self._draft_model is not None \
                and self._draft_chunk_exec is None:
            self._draft_chunk_exec = self._compile_draft_chunk()
        c = self._chunk
        n_real = int(slot.prompt.size)
        if slot.pend_row is None:
            # the row is invariant for the prefill's lifetime (the one
            # possible COW happens at admission, before any chunk) —
            # upload it once, not per interleaved tick
            row = np.zeros((self._mb,), np.int32)
            row[:len(slot.blocks)] = slot.blocks
            slot.pend_row = self._dev(row)
        table_dev = slot.pend_row
        while budget is None or budget > 0:
            part = slot.prompt[slot.pend_pos:slot.pend_pos + c]
            # chunk row t attends pend_pos + t + 1 positions — folded
            # into this tick's KV-read gauge at the next _note_kv_read
            n_part = int(part.size)
            self._kv_read_pend += n_part * slot.pend_pos \
                + n_part * (n_part + 1) // 2
            ids = np.full((1, c), self._pad, np.int32)
            ids[0, :part.size] = part
            ids_dev = self._dev(ids)
            pos = self._dev(np.int32(slot.pend_pos))
            t_c0 = time.monotonic()
            with _quiet_donation():
                tok, self._pools = self._chunk_exec(
                    self._params, ids_dev, self._pools, table_dev,
                    pos, self._dev(np.int32(int(part.size) - 1)),
                    self._samp_row(i), self._next_key())
            if self._draft_model is not None:
                # prime the draft cache over the same positions (its
                # pools ride the same block table)
                with _quiet_donation():
                    self._dpools = self._draft_chunk_exec(
                        self._dparams, ids_dev, self._dpools,
                        table_dev, pos)
            # roofline sample for the chunk executable (wall clock
            # around the launch — on async backends only the final
            # chunk's first-token materialization syncs, so off-TPU
            # treat the chunk row as structure, like every cpu_proxy)
            self._note_step_time("chunk", time.monotonic() - t_c0)
            if self._trace is not None:
                self._trace.emit(
                    f"prefill chunk[{slot.pend_pos // c}]",
                    tid=1 + i, t0=t_c0,
                    args={"rid": slot.rid,
                          "pos": int(slot.pend_pos),
                          "rows": n_part})
            self._n_prefill_chunks += 1
            slot.pend_pos += int(part.size)
            slot.cache_len = slot.pend_pos
            if budget is not None:
                budget -= 1
            if slot.pend_pos >= n_real:
                slot.pend_pos = None
                slot.pend_row = None
                return int(tok)
        return None

    def _advance_prefills(self, emitted):
        """Interleaved-prefill tick: spend the per-step chunk budget
        across pending slots (lowest slot index first), finishing
        admissions whose last chunk lands."""
        if self._chunk_budget <= 0:
            return
        budget = self._chunk_budget
        for i, s in enumerate(self._slots):
            if budget <= 0:
                break
            if s is None or s.pend_pos is None:
                continue
            n0 = self._n_prefill_chunks
            tok = self._advance_prefill(i, budget)
            budget -= self._n_prefill_chunks - n0
            if tok is not None:
                self._finish_prefill(i, tok, emitted)

    def _finish_prefill(self, i, tok, emitted):
        """Shared admission epilogue (synchronous and interleaved
        prefill): record and emit the first token, retire immediately
        on EOS / max_new_tokens == 1. On a role="prefill" engine a
        surviving slot parks for ``pop_prefilled()`` instead of
        entering decode — the request's remaining tokens belong to the
        decode replica the blocks stream to."""
        slot = self._slots[i]
        slot.cache_len = int(slot.prompt.size)
        slot.pend_pos = None
        if self._tables[i, 0] == 0:          # interleaved: publish the
            self._tables[i, :len(slot.blocks)] = slot.blocks   # row now
            self._tables_dev = None
        if slot.resume is not None:
            # recompute resume completing: the re-prefilled cache now
            # holds EXACTLY the preempted state — restore the
            # continuation instead of emitting (the client already
            # holds these tokens; the stream resumes next decode tick)
            last_token, n_emitted = slot.resume
            slot.resume = None
            slot.last_token = int(last_token)
            slot.n_emitted = int(n_emitted)
            if self._trace is not None:
                self._trace.instant("resumed", tid=1 + i,
                                    args={"rid": slot.rid})
            return
        slot.last_token = tok
        slot.history.append(tok)
        self._emit(slot.rid, tok)
        emitted.append((slot.rid, tok))
        if tok == self._eos or slot.max_new <= 1:
            self._retire(i)
        elif self._role == "prefill":
            slot.handoff = True
            self._handoff_ready.append(i)

    def _note_step_time(self, name, dt):
        """Measured half of the roofline: one launch->sync wall-time
        sample for executable ``name``, folded into a per-executable
        EMA (so the estimate tracks the live batch mix, like the
        preemption cost model's rates). The tick executable's sample
        also refreshes the ``serving_step_mfu`` /
        ``serving_hbm_bw_util`` gauges."""
        if dt <= 0.0:
            return
        ema = self._step_time.get(name)
        self._step_time[name] = dt if ema is None \
            else 0.7 * ema + 0.3 * dt
        self._step_ticks[name] = self._step_ticks.get(name, 0) + 1
        if name == ("verify" if self._gamma else "decode"):
            cost = self._exec_cost.get(name)
            if cost:
                if cost.get("flops"):
                    self._m_mfu.set(
                        cost["flops"] / dt / self._peak_flops)
                if cost.get("bytes_accessed"):
                    self._m_bw_util.set(
                        cost["bytes_accessed"] / dt
                        / self._peak_hbm_bw)

    def _roofline(self) -> dict:
        """Live per-executable roofline attribution (the
        ``stats()['roofline']`` block): the XLA cost model's FLOPs /
        HBM bytes of every executable this engine compiled, fused
        with the measured per-tick step-time EMA into MFU and
        HBM-bandwidth utilization. ``bound`` classifies each
        executable against the chip's ridge point (peak FLOPs / peak
        HBM bytes/s — arithmetic intensity below it means the
        executable saturates bandwidth before compute). Off TPU the
        chip peaks are nominal constants: read every number as
        structure, not truth (``cpu_proxy``)."""
        per = {}
        for name, cost in self._exec_cost.items():
            f = float(cost.get("flops", 0.0) or 0.0)
            b = float(cost.get("bytes_accessed", 0.0) or 0.0)
            ai = (f / b) if b else 0.0
            dt = self._step_time.get(name)
            per[name] = {
                "flops": f, "bytes_accessed": b,
                "arithmetic_intensity": round(ai, 4),
                "bound": "compute" if ai >= self._ridge
                else "bandwidth",
                "ticks": self._step_ticks.get(name, 0),
                "step_time_ms": round(1000.0 * dt, 4)
                if dt is not None else None,
                "mfu": round(f / dt / self._peak_flops, 6)
                if dt and f else 0.0,
                "hbm_bw_util": round(b / dt / self._peak_hbm_bw, 6)
                if dt and b else 0.0,
            }
        tick = "verify" if self._gamma else "decode"
        t = per.get(tick, {})
        # speculative token credit: the verify window's FLOPs/bytes
        # are charged ONCE per tick (the executable cost above) but
        # the tick emits accepted+1 tokens — the mean accepted length
        # is the divisor that turns per-tick roofline numbers into
        # per-TOKEN cost (tree speculation raises it at the same
        # verify node budget)
        acc = (self._n_spec_emitted / self._n_spec_verifies
               if self._n_spec_verifies else 0.0)
        return {"cpu_proxy": self._cpu_proxy,
                "tick_executable": tick,
                "step_mfu": t.get("mfu", 0.0),
                "step_hbm_bw_util": t.get("hbm_bw_util", 0.0),
                "verify_tokens_credited_per_tick": round(acc, 4),
                "verify_node_budget": (self._gamma + 1)
                if self._gamma else 1,
                "peak_flops_per_s": self._peak_flops,
                "peak_hbm_bytes_per_s": self._peak_hbm_bw,
                "ridge_flops_per_byte": round(self._ridge, 4),
                "per_executable": per}

    def profile(self, n_ticks: int, path: Optional[str] = None):
        """Arm a BOUNDED ``jax.profiler`` capture around the next
        ``n_ticks`` engine ticks (ISSUE 15 layer 3): the capture
        starts before the next tick and stops after the Nth, so an
        operator can grab a device-level profile of a live engine
        without an always-on tracer. ``path`` defaults to
        ``$PADDLE_TPU_PROFILE_DIR``. Returns the capture dir, or
        None under the ``PADDLE_TPU_TRACE=0`` kill switch (the whole
        flight recorder is inert there). Raises while a window is
        already armed (jax allows one live capture per process)."""
        return self._prof.arm(n_ticks, path)

    def _note_kv_read(self, positions):
        """Analytic KV HBM traffic of one tick: ``positions`` cache
        positions attended x bytes per position (the quantization win
        shows up here directly — int8 halves the multiplier). Folds in
        (and drains) the chunk-prefill positions the legacy path
        accumulated earlier in the same tick (``_kv_read_pend``) — on
        the ragged path prefill rows ride the one launch and are
        already counted."""
        b = int((positions + self._kv_read_pend) * self._kv_pos_bytes)
        self._kv_read_pend = 0
        self._kv_step_bytes_last = b
        self._m_kv_step.set(b)

    def _sync_cache_metrics(self):
        """Mirror allocator-side eviction counts into the monitor
        registry (the allocator stays monitor-free), and refresh the
        SLO latency quantile gauges from the per-engine digests."""
        d = self._alloc.evictions - self._n_evictions_seen
        if d:
            self._m_evict.inc(d)
            self._n_evictions_seen = self._alloc.evictions
        for key, dig in (("ttft", self._d_ttft), ("itl", self._d_itl),
                         ("queue_wait", self._d_queue),
                         ("e2e", self._d_e2e)):
            g = self._m_lat[key]
            for q, v in dig.quantiles().items():
                g.labels(q=q).set(round(v, 3))
        for q, v in self._d_accept.quantiles().items():
            self._m_accept.labels(q=q).set(round(v, 3))
        for q, v in self._d_host_gap.quantiles().items():
            self._m_host_gap.labels(q=q).set(round(v, 3))

    def _prefill_bucketed(self, i, req, n_real) -> int:
        """Legacy bucketed prefill (``PADDLE_TPU_CHUNKED_PREFILL=0`` /
        ``chunked_prefill=False``): dense cached forward over the
        right-padded prompt at a power-of-two bucket, K/V scattered
        into the slot's blocks, first token selected at the prompt's
        true last position. One compile per bucket."""
        bucket = self._bucket(n_real)
        ids = np.full((1, bucket), self._pad, np.int32)
        ids[0, :n_real] = req.prompt
        sub = self._next_key()
        exec_ = self._prefill_execs.get(bucket)
        if exec_ is None:
            exec_ = self._compile_prefill(bucket, sub)
            self._prefill_execs[bucket] = exec_
        t_p0 = time.monotonic()
        with _quiet_donation():
            tok, self._pools = exec_(
                self._params, self._dev(ids),
                self._dev(np.int32(n_real)), self._pools,
                self._dev(self._tables[i]), self._samp_row(i), sub)
        if self._draft_model is not None:
            # prime the draft model's cache with the same prompt K/V
            # (its pools share the slot's block table)
            dexec = self._draft_prefill_execs.get(bucket)
            if dexec is None:
                dexec = self._compile_draft_prefill(bucket)
                self._draft_prefill_execs[bucket] = dexec
            with _quiet_donation():
                self._dpools = dexec(
                    self._dparams, self._dev(ids),
                    self._dev(np.int32(n_real)), self._dpools,
                    self._dev(self._tables[i]))
        if self._trace is not None:
            self._trace.emit(
                f"prefill bucket{bucket}", tid=1 + i, t0=t_p0,
                args={"rid": req.request_id, "rows": n_real})
        return int(tok)

    def _ensure_blocks(self, active, horizon=1):
        """Grow any slot whose next ``horizon`` write positions cross
        into unallocated blocks (covered by the admission reservation;
        speculative mode needs ``gamma + 1`` positions of headroom for
        the verify window). Returns the SURVIVING active list: under
        the watermark policy the pool may be overcommitted, so a
        growth that finds it dry preempts the lowest
        same-or-lower-priority victim — or, when no other candidate
        exists, the growing slot itself (spilled + requeued; it skips
        this tick and resumes token-exact later)."""
        out = []
        for i in active:
            slot = self._slots[i]
            if slot is None:        # preempted as an earlier victim
                continue
            need = _pc.blocks_for(slot.cache_len + horizon, self._bs)
            grown = True
            while len(slot.blocks) < need:
                try:
                    (blk,) = self._alloc_with_preempt(
                        1, exclude=(i,), below=slot.priority + 1)
                except RuntimeError:
                    if not self._preempt_on:
                        raise
                    self._preempt(i)    # self-preempt: out of options
                    grown = False
                    break
                self._tables[i, len(slot.blocks)] = blk
                slot.blocks.append(blk)
                self._tables_dev = None
                self._reserved -= 1
            if grown:
                out.append(i)
        # a LATER slot's growth may have victimized an EARLIER
        # survivor — keep only slots still seated
        return [i for i in out if self._slots[i] is not None]

    def _trim_blocks(self, i):
        """Speculative rollback, block side: return blocks only the
        rejected window tail reached to the allocator (back under the
        slot's admission reservation; no cache data moves). Blocks
        within the NEXT window's reach (``cache_len + gamma + 1``
        positions) are kept: freeing them would be reservation-neutral
        (``free - reserved`` is invariant under trim, so admission
        capacity cannot improve) yet the very next `_ensure_blocks`
        would re-allocate them and re-upload the device block table —
        pure hot-loop churn. With a fixed gamma that makes mid-flight
        trims rare; retirement frees everything regardless."""
        slot = self._slots[i]
        need = _pc.blocks_for(slot.cache_len + self._gamma + 1,
                              self._bs)
        while len(slot.blocks) > need:
            blk = slot.blocks.pop()
            self._alloc.free([blk])
            self._tables[i, len(slot.blocks)] = 0
            self._reserved += 1
            self._tables_dev = None

    def _retire(self, i):
        slot = self._slots[i]
        self._slot_props.pop(i, None)
        now = time.monotonic()
        t0 = self._submit_t.pop(slot.rid, None)
        if t0 is not None:
            self._d_e2e.observe(1000.0 * (now - t0))
        if self._health is not None:
            # burn-rate intake: a retirement that never hit a latency
            # violation counts as SLO-met (requests retired before the
            # first token never entered _slo_ok)
            self._health.on_request(self._slo_ok.pop(slot.rid, True))
        else:
            self._slo_ok.pop(slot.rid, None)
        self._last_emit.pop(slot.rid, None)
        if self._trace is not None:
            # the request's whole residency on this slot, admission to
            # retirement — per-tick decode/verify/prefill spans nest
            # inside it on the same tid
            self._trace.emit(
                f"req{slot.rid}", tid=1 + i, t0=slot.admit_t, t1=now,
                args={"tokens": slot.n_emitted,
                      "cache_len": slot.cache_len})
            self._trace.instant("retired", tid=1 + i,
                                args={"rid": slot.rid})
        if self._prefix_on and slot.cache_len >= self._bs:
            # publish the retired sequence's FULL blocks into the
            # content index instead of just dropping them: the hash
            # chain runs over the tokens the cache actually holds
            # (prompt + committed continuation — position p holds
            # history[p] for p < cache_len), so a future prompt sharing
            # the prefix maps these blocks instead of re-prefilling.
            # Blocks go to the LRU cached list when their refcount hits
            # 0 below and survive until memory pressure evicts them.
            n_full = min(len(slot.blocks), slot.cache_len // self._bs)
            for b, h in zip(slot.blocks[:n_full],
                            _pc.chain_hashes(
                                self._fp,
                                slot.history[:n_full * self._bs],
                                self._bs)):
                self._alloc.publish(b, h)
        self._alloc.free(slot.blocks)
        self._reserved -= slot.worst_blocks - len(slot.blocks)
        self._tables[i, :] = 0
        self._tables_dev = None
        self._slots[i] = None
        self._set_slot_samp(i)
        self._lora_release_slot(i, slot)
        toks = self._results.pop(slot.rid)
        if self.config.retain_results:
            self._done[slot.rid] = np.asarray(toks, np.int64)
        self._m_completed.inc()
        self._n_completed += 1
        self._m_occupancy.set(self.num_active)

    def _bucket(self, n) -> int:
        from ..generation import _prompt_bucket
        return _prompt_bucket(n, self.config.min_prefill_bucket)

    # -- compiled steps -----------------------------------------------

    def _compile_decode(self, lens, toks, samp, key):
        """AOT-compile the fixed-shape batched decode step ONCE; every
        later tick reuses the executable (shape change is impossible —
        slots, tables and lengths are static width; the per-slot
        sampling knobs ride in ``samp`` as data)."""
        def decode(params, pools, tables, lens, toks, samp, key):
            # inactive slots (lens == 0) are pad rows — keep them out
            # of the MoE routing telemetry
            with _moe.serving_rows_mask(lens > 0):
                logits, pools = self._model_step(
                    params, toks[:, None], pools, None,
                    block_tables=tables, cache_lens=lens)
            row = self._gather_logits(logits[:, -1, :])
            _, sub = jax.random.split(key)
            tok, _ = self._select_rows(row, sub, samp)
            return tok, pools

        jitted = jax.jit(decode, donate_argnums=(1,))
        exec_ = self._aot_compile(
            "decode", jitted,
            (self._params, self._pools, self._dev(self._tables),
             self._dev(lens), self._dev(toks), samp, key))
        if self._mesh is not None:
            self._tp_step_bytes = self._tp_census_bytes("decode")
        self._m_decode_compiles.inc()
        self._n_decode_compiles += 1
        return exec_

    def _compile_chunk(self, key):
        """AOT-compile THE fixed-chunk prefill step ONCE (the whole
        prefill zoo, collapsed): ``[1, C]`` token ids run the same
        multi-query paged machinery as the speculative verify window
        (``paged_verify_attention`` with ``T = C`` query rows at
        ``cache_len + t``) — each row attends to every previously
        cached block plus its own in-chunk causal prefix, and K/V are
        written into the slot's blocks as the chunk executes. The next
        token is sampled at the chunk's last REAL row (non-final chunks
        ignore it). Pad rows of a short final chunk write past the
        table's reach (routed to the null block by ``write_tokens``)
        and are never read, so ONE executable serves every prompt
        length with zero padding-bucket waste."""
        c = self._chunk

        def chunk(params, ids, pools, table_row, pos, last, samp, key):
            lens = jnp.reshape(pos.astype(jnp.int32), (1,))
            live = jnp.arange(c, dtype=jnp.int32) <= last
            with _moe.serving_rows_mask(live):
                logits, pools = self._model_step(
                    params, ids, pools, None,
                    block_tables=table_row[None], cache_lens=lens)
            row = jax.lax.dynamic_slice_in_dim(
                logits, last, 1, axis=1)[:, 0, :]
            row = self._gather_logits(row)
            _, sub = jax.random.split(key)
            tok, _ = self._select_rows(row, sub, samp)
            return tok[0], pools

        jitted = jax.jit(chunk, donate_argnums=(2,))
        exec_ = self._aot_compile(
            "chunk", jitted,
            (self._params, self._dev(np.zeros((1, c), np.int32)),
             self._pools, self._dev(np.zeros((self._mb,), np.int32)),
             self._dev(np.int32(0)), self._dev(np.int32(0)),
             self._dev(self._samp_default), key))
        self._m_prefill_compiles.labels(bucket=f"chunk{c}").inc()
        self._n_prefill_compiles += 1
        return exec_

    def _compile_draft_chunk(self):
        """Draft-cache twin of ``_compile_chunk``: write the draft
        model's K/V for the same chunk positions through the SAME block
        table row (no token is selected — the target picks the first
        token). Also compiled exactly once."""
        c = self._chunk

        def dchunk(dparams, ids, dpools, table_row, pos):
            lens = jnp.reshape(pos.astype(jnp.int32), (1,))
            _, dpools = self._draft_step(
                dparams, ids, dpools, None,
                block_tables=table_row[None], cache_lens=lens)
            return dpools

        jitted = jax.jit(dchunk, donate_argnums=(2,))
        exec_ = self._aot_compile(
            "draft_chunk", jitted,
            (self._dparams, self._dev(np.zeros((1, c), np.int32)),
             self._dpools, self._dev(np.zeros((self._mb,), np.int32)),
             self._dev(np.int32(0))))
        self._m_prefill_compiles.labels(bucket=f"draft-chunk{c}").inc()
        self._n_prefill_compiles += 1
        return exec_

    def _compile_cow(self, pools):
        """AOT-compile the copy-on-write block duplicate (src/dst ride
        as traced scalars — one executable serves every COW)."""
        jitted = jax.jit(_pc.copy_blocks, donate_argnums=(0,))
        return self._aot_compile(
            "cow", jitted, (pools, self._dev(np.int32(0)),
                            self._dev(np.int32(0))))

    def _compile_prefill(self, bucket, key):
        def prefill(params, ids, n_real, pools, table_row, samp, key):
            dense = self.model.init_caches(1, bucket)
            live = jnp.arange(bucket, dtype=jnp.int32) < n_real
            with _moe.serving_rows_mask(live):
                logits, dense = self._model_step(
                    params, ids, dense, jnp.zeros((), jnp.int32))
            pools = [
                _pc.write_prefill(kp, vp, table_row[None], dk, dv,
                                  n_real=n_real)
                for (kp, vp), (dk, dv) in zip(pools, dense)]
            last = jax.lax.dynamic_slice_in_dim(
                logits, n_real - 1, 1, axis=1)[:, 0, :]
            last = self._gather_logits(last)
            _, sub = jax.random.split(key)
            tok, _ = self._select_rows(last, sub, samp)
            return tok[0], pools

        jitted = jax.jit(prefill, donate_argnums=(3,))
        exec_ = self._aot_compile(
            f"prefill{bucket}", jitted,
            (self._params, self._dev(np.zeros((1, bucket), np.int32)),
             self._dev(np.int32(0)), self._pools,
             self._dev(np.zeros((self._mb,), np.int32)),
             self._dev(self._samp_default), key))
        self._m_prefill_compiles.labels(bucket=bucket).inc()
        self._n_prefill_compiles += 1
        return exec_

    def _compile_verify(self, lens, toks, samp, dq, key):
        """AOT-compile the fixed-gamma multi-token verify step ONCE
        (the speculative decode executable — counted in
        ``decode_compiles`` so the zero-steady-state-recompile
        assertion covers speculative mode too). The per-slot sampling
        knobs ride as the ``samp`` operand (``slot_params`` mode of
        ``build_verify_step``) — distinct configs, one executable."""
        from ..generation import speculative as _spec
        verify = _spec.build_verify_step(
            self._model_step, gamma=self._gamma,
            do_sample=self._do_sample,
            onehot_draft=self._draft_model is None,
            gather_logits=self._gather_logits
            if self._mesh is not None else None, slot_params=True)
        g = self._gamma

        def verify_masked(params, pools, tables, lens, *rest):
            # inactive slots contribute gamma+1 pad rows each — keep
            # them out of the MoE routing telemetry
            with _moe.serving_rows_mask(jnp.repeat(lens > 0, g + 1)):
                return verify(params, pools, tables, lens, *rest)

        jitted = jax.jit(verify_masked, donate_argnums=(1,))
        args = [self._params, self._pools, self._dev(self._tables),
                self._dev(lens), self._dev(toks), samp]
        if self._do_sample:
            if dq is not None:
                args.append(dq)
            args.append(key)
        exec_ = self._aot_compile("verify", jitted, tuple(args))
        if self._mesh is not None:
            # a spec step executes the draft loop AND the verify gather.
            # The draft's gather sits inside a lax.scan body, which the
            # census walks ONCE — the engine knows the trip count
            # (gamma+1 iterations), so scale it to the bytes that
            # actually move per step
            self._tp_step_bytes = self._tp_census_bytes("verify") \
                + (self._gamma + 1) * self._tp_census_bytes("draft")
        self._m_decode_compiles.inc()
        self._n_decode_compiles += 1
        return exec_

    def _compile_ragged_step(self, args):
        """AOT-compile THE ragged mixed-batch executable ONCE — the
        whole per-width zoo (decode + verify + chunk prefill),
        collapsed: a packed ``[R]`` token buffer runs the model over
        every live row (``ragged_meta`` partitions it by slot), K/V
        scatter per row, and the sampling head takes each slot's
        continuation row from ``last_rows`` — decode rows sample their
        only row, completing prefills their final prompt row, verify
        windows run the shared acceptance core on their gamma+1 rows.
        ONE logits gather serves all of it (under TP: still exactly
        one explicit all_gather per step). Census name stays
        ``decode``/``verify`` so telemetry keeps the per-step
        collective contract of the per-width path."""
        from ..generation import _filter_logits
        from ..generation import speculative as _spec
        g = self._gamma
        r = self._rows
        do_sample = self._do_sample
        tree = self._spec_tree
        heads_on = self._heads is not None
        lora_on = self._lora_on
        # adapter row index in the slots pack: appended AFTER the tree
        # flags (when present) by _step_ragged
        lora_row = 5 if tree is not None else 4
        lora_scaling = self._lora_pool.scaling if lora_on else 1.0
        # grouped-matmul path only off-mesh: under TP the delta einsum
        # shards on the existing GSPMD cut instead (the gmm kernel's
        # scalar-prefetch gather is a single-device layout)
        lora_gmm_ok = self._mesh is None
        # async tick pipeline: the g=0 executable additionally returns
        # next-tick inputs as DEVICE arrays (the carry) — per-slot
        # sampled token, advanced base length, a decremented budget and
        # an in-executable ``done`` mask (EOS or budget exhausted) that
        # zeroes a finished slot's next-tick row so a pipelined tick
        # no-ops it on device (row parks at the overflow position — the
        # KV write null-routes, exactly like a pad row). Under the
        # PADDLE_TPU_ASYNC_TICK=0 kill switch this flag is False and
        # the compiled graph is bit-for-bit today's.
        async_carry = self._async_on and not g
        eos = self._eos
        pad = self._pad
        n_slots = self.config.num_slots
        overflow = self._overflow

        def ragged(params, pools, tables, rows_pack, slots_pack, *rest):
            if lora_on:
                # the stacked adapter weights ride at a FIXED operand
                # position (right after the packs) — strip them before
                # the g/heads/dq parsing below, which indexes rest
                # from both ends
                lora_ops, rest = rest[0], rest[1:]
            ids, row_slot, row_pos = (rows_pack[0], rows_pack[1],
                                      rows_pack[2])
            base, q_lens, row_starts, last_rows = (
                slots_pack[0], slots_pack[1], slots_pack[2],
                slots_pack[3])
            tree_rows = slots_pack[4] if tree is not None else None
            nwin = jnp.arange(g + 1, dtype=jnp.int32)
            win = jnp.arange(self._wmax, dtype=jnp.int32)
            meta = (q_lens, row_starts, row_slot, row_pos, nwin, win)
            # pad rows park at the overflow position — exclude them
            # from the MoE routing telemetry (they'd read as
            # hot-expert skew on lightly loaded ticks)
            step = self._model_step_h if heads_on else self._model_step
            with contextlib.ExitStack() as ctx:
                if tree is not None:
                    # the ancestor mask rides the ambient scope — the
                    # kernels read the static topology at trace time
                    # and tree_rows as a per-slot operand; prefill
                    # rows (tree_rows == 0) keep the linear mask
                    ctx.enter_context(
                        _pa.spec_tree_scope(tree, tree_rows))
                if lora_on:
                    # per-ROW adapter assignment: each packed query
                    # row applies its slot's adapter (decode, verify
                    # AND prefill rows — the prompt's KV must carry
                    # the deltas too); pad rows gather slot 0's value
                    # and contribute nothing downstream. The scope
                    # arms the tagged q/k/v/o projections' ragged
                    # grouped-matmul delta inside the SAME executable.
                    row_adapter = jnp.take(slots_pack[lora_row],
                                           row_slot)
                    ctx.enter_context(_lora.serving_lora_scope(
                        lora_ops, row_adapter, lora_scaling,
                        gmm_ok=lora_gmm_ok))
                ctx.enter_context(
                    _moe.serving_rows_mask(row_pos < self._overflow))
                logits, pools = step(
                    params, ids[None, :], pools, None,
                    block_tables=tables, cache_lens=base,
                    ragged_meta=meta)
            if heads_on:
                logits, hid = logits
            lg = logits[0]                          # [R, V(/tp)]
            if not g:
                samp, key = rest
                rows = jnp.take(lg, last_rows.astype(jnp.int32),
                                axis=0)
                rows = self._gather_logits(rows)    # the ONE collective
                # health probe: one any(~isfinite) reduction over the
                # rows already gathered for sampling — a scalar OUTPUT
                # of the same executable, never a new one. Always
                # computed (executable stays bit-identical under
                # PADDLE_TPU_HEALTH=0); only the host fetch is gated.
                if not async_carry:
                    nf = jnp.any(~jnp.isfinite(rows))
                    _, sel = jax.random.split(key)
                    tok, _ = self._select_rows(rows, sel, samp)
                    return tok, nf, pools
                # pipelined mode masks the probe to LIVE slots: a
                # device-carried tick packs row i <-> slot i, so a dead
                # slot's gathered row is an overflow pad row whose
                # fully-masked attention output is not meaningful
                live = q_lens > 0
                nf = jnp.any(~jnp.isfinite(rows) & live[:, None])
                _, sel = jax.random.split(key)
                tok, _ = self._select_rows(rows, sel, samp)
                tok = tok.astype(jnp.int32)
                # -- device-resident carry: tick N+1's packs ----------
                budget = slots_pack[-1]
                done = live & ((tok == eos) | (budget <= 1))
                live2 = live & ~done
                sl = jnp.arange(n_slots, dtype=jnp.int32)
                nxt_base = jnp.where(live, base + 1, base)
                nxt_budget = jnp.where(live, budget - 1, budget)
                tail = r - n_slots      # pad rows past the slot rows
                ids2 = jnp.concatenate(
                    [jnp.where(live2, tok, pad),
                     jnp.full((tail,), pad, jnp.int32)])
                slot2 = jnp.concatenate(
                    [sl, jnp.zeros((tail,), jnp.int32)])
                pos2 = jnp.concatenate(
                    [jnp.where(live2, nxt_base, overflow)
                     .astype(jnp.int32),
                     jnp.full((tail,), overflow, jnp.int32)])
                carry_rows = jnp.stack([ids2, slot2, pos2])
                crows = [nxt_base, live2.astype(base.dtype), sl, sl]
                if lora_on:
                    crows.append(slots_pack[lora_row])
                crows.append(nxt_budget)
                carry_slots = jnp.stack(
                    [c.astype(jnp.int32) for c in crows])
                if self._mesh is not None:
                    # compiled executables are strict about INPUT
                    # shardings — the carry feeds straight back as
                    # next tick's packs, so pin it replicated (what
                    # _dev commits host packs as)
                    rep = NamedSharding(self._mesh, P(None, None))
                    carry_rows = jax.lax.with_sharding_constraint(
                        carry_rows, rep)
                    carry_slots = jax.lax.with_sharding_constraint(
                        carry_slots, rep)
                return tok, nf, carry_rows, carry_slots, pools
            toks = rest[0]
            if tree is not None:
                heads = rest[1] if heads_on else None
                dq = None
            else:
                dq = rest[1] if len(rest) == 4 else None
            samp = rest[-2]
            key = rest[-1]
            # one take + ONE gather covers the per-slot continuation
            # rows AND the verify windows
            idx = row_starts.astype(jnp.int32)[:, None] \
                + jnp.arange(g + 1, dtype=jnp.int32)[None, :]
            take = jnp.concatenate(
                [last_rows.astype(jnp.int32)[:, None], idx], axis=1)
            rows = jnp.take(lg, jnp.clip(take, 0, r - 1).reshape(-1),
                            axis=0)
            rows = self._gather_logits(rows)
            nf = jnp.any(~jnp.isfinite(rows))   # health probe (see g=0)
            rows = rows.reshape(toks.shape[0], g + 2, -1)
            sel_key, acc_key = jax.random.split(key)
            first_tok, _ = self._select_rows(rows[:, 0, :], sel_key,
                                             samp)
            # per-slot knobs over the verify windows: [S] broadcasts
            # across each slot's gamma+1 rows inside _filter_logits
            f = _filter_logits(rows[:, 1:, :], do_sample=do_sample,
                               temperature=samp[:, 0],
                               top_k=samp[:, 1], top_p=samp[:, 2])
            if tree is None:
                out, accept, _logp = _spec.accept_from_filtered(
                    f, toks, dq, acc_key, gamma=g, do_sample=do_sample)
                return first_tok, out, accept, nf, pools
            out, accept, _logp, path, n_acc = \
                _spec.accept_tree_from_filtered(
                    f, toks, tree, acc_key, do_sample=do_sample)
            # compact the accepted root path in place: position
            # base+j must hold node path[j]'s K/V before the next
            # tick appends at base + n_acc + 1. Non-verifying slots
            # (prefill rows, idle) keep n_keep = 0 — their moves all
            # null-route, so a mid-prefill cache is never touched.
            n_keep = jnp.where(tree_rows > 0, n_acc + 1, 0)
            pools = [
                _pc.permute_window(kp, vp, tables, base, path, n_keep)
                for (kp, vp) in pools]
            if not heads_on:
                return first_tok, out, accept, nf, pools
            # next tick's tree proposal from the draft heads, drafted
            # off the accepted path's FINAL hidden row (the row whose
            # LM-head logits produced the bonus token): head d-1
            # predicts the token at depth d, node k+1 taking its
            # sibling-rank-th top entry
            fin = jnp.take_along_axis(path, n_acc[:, None],
                                      axis=1)[:, 0]
            hrow = row_starts.astype(jnp.int32) + fin
            h_fin = jnp.take(hid[0], jnp.clip(hrow, 0, r - 1),
                             axis=0).astype(jnp.float32)
            head_lg = jnp.einsum("sh,dhv->dsv", h_fin, heads)
            _, tidx = jax.lax.top_k(head_lg, self._tree_kmax)
            props = jnp.stack(
                [tidx[self._tree_depth[k + 1] - 1][:,
                      self._tree_sib[k]] for k in range(g)],
                axis=1).astype(jnp.int32)
            return first_tok, out, accept, props, nf, pools

        jitted = jax.jit(ragged, donate_argnums=(1,))
        name = "verify" if g else "decode"
        exec_ = self._aot_compile(name, jitted, args)
        if self._mesh is not None:
            self._tp_step_bytes = self._tp_census_bytes(name)
            if g and self._draft_model is not None:
                # the fused draft step's gather sits inside its scan
                # body (census walks it once; gamma+1 iterations move
                # bytes per step)
                self._tp_step_bytes += \
                    (g + 1) * self._tp_census_bytes("draft")
        self._m_decode_compiles.inc()
        self._n_decode_compiles += 1
        return exec_

    def _compile_ragged_draft(self, args):
        """AOT-compile the draft model's HALF of a ragged spec tick
        ONCE — one fused executable: (1) prime the draft cache over
        this tick's prefill rows (the ragged write, logits discarded —
        the legacy per-chunk draft prefill twin, folded in), then
        (2) run the gamma+1-step proposal scan. With a draft model the
        engine's steady state is therefore exactly TWO executables."""
        from ..generation import speculative as _spec
        g = self._gamma
        prime = self._chunked and self._prefill_rows > 0
        loop = _spec.build_draft_loop(
            self._draft_step, gamma=g, do_sample=self._do_sample,
            want_probs=self._do_sample,
            gather_logits=self._gather_logits
            if self._mesh is not None else None, slot_params=True)

        def dstep(dparams, dpools, tables, drows, dslots, samp, key):
            ids, row_slot, prime_pos = drows[0], drows[1], drows[2]
            base, prime_q, row_starts, scan_lens, cur = (
                dslots[0], dslots[1], dslots[2], dslots[3], dslots[4])
            if prime:
                nwin = jnp.arange(g + 1, dtype=jnp.int32)
                win = jnp.arange(self._wmax, dtype=jnp.int32)
                meta = (prime_q, row_starts, row_slot, prime_pos,
                        nwin, win)

                def _prime(dp):
                    with _moe.serving_rows_mask(
                            prime_pos < self._overflow):
                        _, dp = self._draft_step(
                            dparams, ids[None, :], dp, None,
                            block_tables=tables, cache_lens=base,
                            ragged_meta=meta)
                    return dp

                # no pending prefill rows this tick -> the prime
                # forward would only null-route pad writes; skip the
                # whole pass at runtime (same executable, zero
                # steady-state recompiles)
                dpools = jax.lax.cond(jnp.max(prime_q) > 0, _prime,
                                      lambda dp: dp, dpools)
            # non-verifying slots scan at the overflow length — pad
            # rows, excluded from the draft's routing telemetry
            with _moe.serving_rows_mask(scan_lens < self._overflow):
                props, qp, dpools = loop(dparams, dpools, tables,
                                         scan_lens, cur, samp, key)
            if qp is None:
                return props, dpools
            return props, qp, dpools

        jitted = jax.jit(dstep, donate_argnums=(1,))
        return self._aot_compile("draft", jitted, args)

    def _compile_draft(self, lens, toks, samp, key):
        """AOT-compile the draft model's gamma+1-step proposal scan
        ONCE (drafter='model'). ``samp`` carries the per-slot sampling
        knobs — the draft filters its proposal logits with the SAME
        values the verify step filters the target's (the
        rejection-sampling soundness requirement, per slot)."""
        from ..generation import speculative as _spec
        loop = _spec.build_draft_loop(
            self._draft_step, gamma=self._gamma,
            do_sample=self._do_sample,
            want_probs=self._do_sample,
            gather_logits=self._gather_logits
            if self._mesh is not None else None, slot_params=True)

        def draft_masked(dparams, dpools, tables, lens, cur, samp,
                         key):
            with _moe.serving_rows_mask(lens > 0):
                return loop(dparams, dpools, tables, lens, cur, samp,
                            key)

        jitted = jax.jit(draft_masked, donate_argnums=(1,))
        return self._aot_compile(
            "draft", jitted,
            (self._dparams, self._dpools, self._dev(self._tables),
             self._dev(lens), self._dev(toks[:, 0]), samp, key))

    def _compile_draft_prefill(self, bucket):
        """Draft-cache twin of ``_compile_prefill``: scatter the draft
        model's prompt K/V into its pools through the SAME block table
        row (no token is selected — the target picks the first
        token)."""
        def dprefill(dparams, ids, n_real, dpools, table_row):
            dense = self._draft_model.init_caches(1, bucket)
            _, dense = self._draft_step(dparams, ids, dense,
                                        jnp.zeros((), jnp.int32))
            return [
                _pc.write_prefill(kp, vp, table_row[None], dk, dv,
                                  n_real=n_real)
                for (kp, vp), (dk, dv) in zip(dpools, dense)]

        jitted = jax.jit(dprefill, donate_argnums=(3,))
        exec_ = self._aot_compile(
            f"draft_prefill{bucket}", jitted,
            (self._dparams, self._dev(np.zeros((1, bucket), np.int32)),
             self._dev(np.int32(0)), self._dpools,
             self._dev(np.zeros((self._mb,), np.int32))))
        self._m_prefill_compiles.labels(
            bucket=f"draft-{bucket}").inc()
        self._n_prefill_compiles += 1
        return exec_
