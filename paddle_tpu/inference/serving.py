"""Continuous-batching LLM serving engine over the paged KV cache.

The serving role PaddleNLP's ``llm/predict/predictor.py`` + a request
scheduler play over AnalysisPredictor, rebuilt TPU-first for the
compiler's static-shape world (arxiv 2603.09555) with the block-table
paged KV layout of *Ragged Paged Attention* (arxiv 2604.15464):

- **Fixed slots, one compiled decode step.** The engine owns
  ``num_slots`` serving slots. Every decode step runs ALL slots through
  one batched model call — token ids [S, 1], block tables [S, MB],
  per-slot lengths [S] — whose shapes never change, so the step is
  AOT-compiled exactly once and steady state runs ZERO recompiles
  (assert via the ``serving_decode_compiles`` / ``serving_decode_steps``
  monitor counters). Raggedness lives in the table/length VALUES.
- **Paged KV.** All slots share one block pool per layer
  (``ops/paged_cache.py``); the host-side ``BlockAllocator`` hands
  blocks to admitted requests and reclaims them at retirement, so HBM
  scales with live tokens, not ``slots x max_len``.
- **Continuous batching.** ``step()`` admits queued requests into freed
  slots (prefill compiled per power-of-two prompt bucket, K/V scattered
  straight into the slot's blocks), decodes one token for every active
  slot, streams tokens out, and retires slots on EOS/max-len — freed
  blocks and slots are reused by the next admission without ever
  draining the batch.
- **Ragged decode attention** reads the pool through the Pallas kernel
  on TPU (``ops/pallas/paged_attention.py``) and the gather fallback on
  CPU, behind the models' ordinary cached-attention path — the same
  code ``generate(cache_impl="paged")`` rides.
- **Speculative decoding** (``num_speculative_tokens = gamma > 0``): a
  drafter (model-free n-gram prompt lookup, or a smaller draft model
  sharing the block tables) proposes gamma tokens per slot and ONE
  fixed-shape multi-token verify forward (the multi-query paged
  kernel) accepts 1..gamma+1 of them — still exactly one compiled
  executable in steady state, because accept/reject lives in the
  LENGTH values: rejected tokens roll back by decrementing
  ``cache_lens`` and returning overhang blocks to the allocator (no
  data movement). The scheduler reserves ``prompt + max_new + gamma``
  blocks worst-case (the speculated window may overhang the final
  token), retires EOS found anywhere inside the window, and streams
  every accepted token through the ordinary callback. Kill switch:
  ``PADDLE_TPU_SPECULATIVE=0``; capacity-routed MoE is excluded (the
  window tokens would compete for expert capacity — same reasoning as
  prompt bucketing). See docs/OPS.md "Speculative decoding".

Admission is worst-case reserved: a request is admitted only when the
pool can cover ``prompt + max_new`` blocks for it PLUS the outstanding
reservations of every active slot, so mid-decode pool exhaustion is
impossible by construction (no preemption path needed).

Telemetry (monitor registry, exported in the JSONL dump):
``serving_slot_occupancy`` gauge, ``serving_batch_utilization`` /
``serving_queue_wait_ms`` histograms, ``serving_tokens_total`` /
``serving_decode_steps`` / ``serving_decode_compiles`` /
``serving_prefill_compiles`` / ``serving_requests_completed`` counters.
"""
from __future__ import annotations

import contextlib
import time
import warnings
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor
from ..ops import paged_cache as _pc

__all__ = ["ServingConfig", "ServingRequest", "ServingEngine"]


@contextlib.contextmanager
def _quiet_donation():
    """Pool donation is a TPU-side optimization (decode/prefill reuse
    the pool's HBM in place); CPU ignores donation with a warning that
    would fire every engine tick. Scoped here so other code's genuinely
    broken donations still surface."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


@dataclass
class ServingConfig:
    num_slots: int = 8                  # fixed decode batch width
    block_size: int = 16                # tokens per KV block
    max_model_len: int = 1024           # prompt + generated cap per seq
    # pool size; default covers every slot at max_model_len (admission
    # then never queues on blocks, only on slots) — shrink to trade HBM
    # for queueing
    num_blocks: Optional[int] = None
    max_new_tokens: int = 128           # per-request default
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    decode_strategy: str = "greedy_search"   # or "sampling"
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    min_prefill_bucket: int = 16        # smallest prompt bucket
    # speculative decoding: draft gamma tokens per slot per step and
    # verify them in one multi-token forward (0 = off)
    num_speculative_tokens: int = 0
    drafter: str = "ngram"              # ngram | model (pass draft_model)
    spec_ngram_max: int = 3             # longest prompt-lookup n-gram


@dataclass
class ServingRequest:
    request_id: int
    prompt: np.ndarray                  # [L] int32
    max_new_tokens: int
    submit_time: float = field(default_factory=time.monotonic)


class _Slot:
    __slots__ = ("rid", "blocks", "worst_blocks", "cache_len",
                 "last_token", "n_emitted", "max_new", "history")

    def __init__(self, rid, blocks, worst_blocks, cache_len, last_token,
                 max_new, history=None):
        self.rid = rid
        self.blocks = blocks            # allocated block ids (ordered)
        self.worst_blocks = worst_blocks
        self.cache_len = cache_len      # valid cache positions
        self.last_token = last_token
        self.n_emitted = 1              # prefill emitted the first token
        self.max_new = max_new
        self.history = history          # prompt + emitted (spec drafter)


class ServingEngine:
    """Continuous-batching serving over a causal-LM with the paged-KV
    protocol (``init_paged_caches`` + ``block_tables``/``cache_lens``
    forward kwargs — Llama/Qwen2/GPT families).

    Usage::

        engine = ServingEngine(model, ServingConfig(num_slots=8))
        rid = engine.submit([1, 2, 3], max_new_tokens=32)
        results = engine.run()          # {rid: np.ndarray of tokens}

    or stream: pass ``stream_callback=lambda rid, tok: ...`` and drive
    ``step()`` yourself.
    """

    def __init__(self, model, config: Optional[ServingConfig] = None,
                 stream_callback: Optional[Callable] = None,
                 draft_model=None):
        from ..generation import GenerationMixin, _select_token
        from ..generation import speculative as _spec
        if not isinstance(model, GenerationMixin):
            raise TypeError(
                f"{type(model).__name__} does not support generation "
                "(needs the KV-cache protocol)")
        if not hasattr(model, "init_paged_caches"):
            raise TypeError(
                f"{type(model).__name__} does not implement "
                "init_paged_caches (paged-KV serving)")
        cfg = config or ServingConfig()
        if cfg.decode_strategy not in ("greedy_search", "sampling"):
            raise NotImplementedError(
                f"serving decode_strategy {cfg.decode_strategy!r}; "
                "supported: greedy_search, sampling")
        gamma = int(cfg.num_speculative_tokens or 0)
        if gamma < 0:
            raise ValueError(
                f"num_speculative_tokens must be >= 0, got {gamma}")
        if draft_model is not None and \
                (gamma == 0 or cfg.drafter != "model"):
            # silently drafting via n-gram while the caller handed over
            # a draft model would measure the wrong configuration
            raise ValueError(
                "draft_model requires num_speculative_tokens > 0 and "
                "drafter='model' "
                f"(got gamma={gamma}, drafter={cfg.drafter!r})")
        if not _spec.speculative_enabled():  # PADDLE_TPU_SPECULATIVE=0
            gamma = 0
            draft_model = None
        if gamma:
            if cfg.drafter not in ("ngram", "model"):
                raise ValueError(f"drafter {cfg.drafter!r}; "
                                 "supported: ngram, model")
            if cfg.drafter == "model" and draft_model is None:
                raise ValueError(
                    "drafter='model' requires a draft_model")
            reason = _spec.spec_exclusion_reason(model)
            if reason is not None:
                raise NotImplementedError(
                    f"speculative serving unavailable: {reason}")
            if cfg.drafter == "model":
                reason = _spec.draft_exclusion_reason(model, draft_model)
                if reason is not None:
                    raise NotImplementedError(
                        f"draft model unusable: {reason}")
        max_pos = getattr(getattr(model, "config", None),
                          "max_position_embeddings", None)
        if max_pos is not None and cfg.max_model_len + gamma > max_pos:
            raise ValueError(
                f"max_model_len ({cfg.max_model_len})"
                + (f" + speculative window ({gamma})" if gamma else "")
                + f" exceeds the model's max_position_embeddings "
                f"({max_pos})")
        self.model = model
        self.config = cfg
        self._stream = stream_callback
        model.eval()

        from ..jit import _LayerBinder
        binder = _LayerBinder(model)
        self._params = binder.param_arrays()
        self._model_step = model._build_model_step(
            binder, binder.buffer_arrays())
        do_sample = cfg.decode_strategy == "sampling"
        self._do_sample = do_sample
        self._select = lambda lg, k: _select_token(
            lg, k, do_sample=do_sample, temperature=cfg.temperature,
            top_k=cfg.top_k, top_p=cfg.top_p)

        self._bs = int(cfg.block_size)
        # +gamma: the speculative verify window may overhang the last
        # emitted token by up to gamma written-then-rolled-back slots
        self._gamma = gamma
        self._ngram_max = int(cfg.spec_ngram_max)
        self._mb = _pc.blocks_for(cfg.max_model_len + gamma, self._bs)
        nb = (1 + cfg.num_slots * self._mb) if cfg.num_blocks is None \
            else int(cfg.num_blocks)
        self._alloc = _pc.BlockAllocator(nb)
        self._pools = model.init_paged_caches(nb, self._bs)
        self._draft_model = draft_model \
            if gamma and cfg.drafter == "model" else None
        if self._draft_model is not None:
            self._draft_model.eval()
            dbinder = _LayerBinder(self._draft_model)
            self._dbinder = dbinder
            self._dparams = dbinder.param_arrays()
            self._draft_step = self._draft_model._build_model_step(
                dbinder, dbinder.buffer_arrays())
            self._dpools = self._draft_model.init_paged_caches(
                nb, self._bs)
            self._draft_prefill_execs = {}
        self._verify_exec = None
        self._draft_exec = None
        self._tables = np.zeros((cfg.num_slots, self._mb), np.int32)
        self._slots: List[Optional[_Slot]] = [None] * cfg.num_slots
        self._reserved = 0              # blocks promised to active slots
        self._queue: deque = deque()
        self._results: Dict[int, list] = {}
        self._done: Dict[int, np.ndarray] = {}
        self._next_rid = 0
        self._eos = -1 if cfg.eos_token_id is None \
            else int(cfg.eos_token_id)
        self._pad = int(cfg.pad_token_id)
        self._key = jax.random.PRNGKey(int(cfg.seed))
        self._tables_dev = None         # device mirror of _tables
        self._decode_exec = None
        self._prefill_execs = {}
        # per-engine counts (the monitor counters below are process-
        # global telemetry shared by every engine; stats() must report
        # THIS engine)
        self._n_decode_compiles = 0
        self._n_decode_steps = 0
        self._n_tokens = 0
        self._n_completed = 0
        self._n_spec_proposed = 0
        self._n_spec_accepted = 0
        self._n_spec_verifies = 0       # per-slot verify windows
        self._n_spec_emitted = 0

        # -- telemetry ------------------------------------------------
        self._m_occupancy = monitor.gauge(
            "serving_slot_occupancy", "active serving slots")
        self._m_util = monitor.histogram(
            "serving_batch_utilization",
            "active slots / num_slots per decode step",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        self._m_queue_wait = monitor.histogram(
            "serving_queue_wait_ms", "submit -> admission wait")
        self._m_tokens = monitor.counter(
            "serving_tokens_total", "tokens generated (all requests)")
        self._m_steps = monitor.counter(
            "serving_decode_steps", "batched decode steps executed")
        self._m_decode_compiles = monitor.counter(
            "serving_decode_compiles",
            "decode-step compilations (steady state: stays at 1)")
        self._m_prefill_compiles = monitor.counter(
            "serving_prefill_compiles",
            "prefill compilations per prompt bucket",
            labels=("bucket",))
        self._m_completed = monitor.counter(
            "serving_requests_completed", "requests fully served")
        if gamma:
            self._m_spec_len = monitor.histogram(
                "serving_spec_accepted_len",
                "tokens emitted per slot verify window "
                "(accepted drafts + the correction/bonus token)",
                buckets=(1, 2, 3, 4, 5, 6, 7, 8, 9))
            self._m_spec_proposed = monitor.counter(
                "spec_tokens_proposed", "draft tokens proposed")
            self._m_spec_accepted = monitor.counter(
                "spec_tokens_accepted", "draft tokens accepted")
            self._m_spec_rate = monitor.gauge(
                "serving_spec_acceptance_rate",
                "accepted / proposed draft tokens (cumulative)")

    # -- public API ---------------------------------------------------

    def submit(self, prompt, max_new_tokens=None) -> int:
        """Queue one request; returns its request id. Tokens stream to
        ``stream_callback`` as ``step()``/``run()`` produce them."""
        ids = np.asarray(prompt, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        max_new = int(self.config.max_new_tokens
                      if max_new_tokens is None else max_new_tokens)
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new}")
        if ids.size + max_new > self.config.max_model_len:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens ({max_new}) "
                f"exceeds max_model_len ({self.config.max_model_len})")
        worst = _pc.blocks_for(ids.size + max_new + self._gamma,
                               self._bs)
        if worst > self._alloc.num_blocks - 1:
            raise ValueError(
                f"request needs {worst} blocks; pool has only "
                f"{self._alloc.num_blocks - 1}")
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(ServingRequest(rid, ids, max_new))
        return rid

    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def num_queued(self) -> int:
        return len(self._queue)

    def step(self) -> List[tuple]:
        """One engine tick: admit what fits, decode one token (or
        verify a speculative window) for every active slot, retire
        finished sequences. Returns this tick's
        ``[(request_id, token), ...]`` (admission prefills included)."""
        if self._gamma:
            return self._step_spec()
        emitted = self._admit()
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return emitted
        self._ensure_blocks(active)

        cfg = self.config
        lens = np.zeros(cfg.num_slots, np.int32)
        toks = np.full(cfg.num_slots, self._pad, np.int32)
        for i in active:
            lens[i] = self._slots[i].cache_len
            toks[i] = self._slots[i].last_token
        sub = self._next_key()
        if self._tables_dev is None:    # only re-upload after changes
            self._tables_dev = jnp.asarray(self._tables)
        if self._decode_exec is None:
            self._decode_exec = self._compile_decode(lens, toks, sub)
        with _quiet_donation():
            out, self._pools = self._decode_exec(
                self._params, self._pools, self._tables_dev,
                jnp.asarray(lens), jnp.asarray(toks), sub)
        out = np.asarray(out)

        self._m_steps.inc()
        self._n_decode_steps += 1
        self._m_util.observe(len(active) / cfg.num_slots)
        for i in active:
            slot = self._slots[i]
            tok = int(out[i])
            slot.cache_len += 1
            slot.last_token = tok
            slot.n_emitted += 1
            self._emit(slot.rid, tok)
            emitted.append((slot.rid, tok))
            if tok == self._eos or slot.n_emitted >= slot.max_new:
                self._retire(i)
        return emitted

    def _step_spec(self) -> List[tuple]:
        """Speculative engine tick: draft gamma tokens per active slot,
        verify the whole window in ONE fixed-shape target forward, and
        commit 1..gamma+1 tokens per slot. The verify executable is
        AOT-compiled once — accept/reject never changes a shape, only
        the ``cache_lens`` values — so steady state stays at zero
        recompiles exactly like the plain decode step. Rollback of a
        rejected tail is ``cache_len`` simply not advancing over it,
        plus ``_trim_blocks`` returning overhang blocks."""
        from ..generation import speculative as _spec
        emitted = self._admit()
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return emitted
        g = self._gamma
        # room for the full window: positions cache_len .. cache_len+g
        self._ensure_blocks(active, horizon=g + 1)

        cfg = self.config
        lens = np.zeros(cfg.num_slots, np.int32)
        toks = np.full((cfg.num_slots, g + 1), self._pad, np.int32)
        for i in active:
            lens[i] = self._slots[i].cache_len
            toks[i, 0] = self._slots[i].last_token
        if self._tables_dev is None:
            self._tables_dev = jnp.asarray(self._tables)
        lens_dev = jnp.asarray(lens)

        dq = None
        if self._draft_model is not None:
            sub = self._next_key()
            if self._draft_exec is None:
                self._draft_exec = self._compile_draft(lens, toks, sub)
            with _quiet_donation():
                props, dq, self._dpools = self._draft_exec(
                    self._dparams, self._dpools, self._tables_dev,
                    lens_dev, jnp.asarray(toks[:, 0]), sub)
            toks[:, 1:] = np.asarray(props)
        else:
            for i in active:
                toks[i, 1:] = _spec.ngram_propose(
                    self._slots[i].history, g, self._ngram_max)

        sub = self._next_key()
        if self._verify_exec is None:
            self._verify_exec = self._compile_verify(lens, toks, dq,
                                                     sub)
        args = [self._params, self._pools, self._tables_dev, lens_dev,
                jnp.asarray(toks)]
        if self._do_sample:
            if dq is not None:
                args.append(dq)
            args.append(sub)
        with _quiet_donation():
            out, accept, _logp, self._pools = self._verify_exec(*args)
        out = np.asarray(out)
        accept = np.asarray(accept)

        self._m_steps.inc()
        self._n_decode_steps += 1
        self._m_util.observe(len(active) / cfg.num_slots)
        for i in active:
            slot = self._slots[i]
            # EOS inside the window and max_new room both truncate
            kept, n_acc = _spec.commit_window(
                out[i], accept[i], slot.max_new - slot.n_emitted,
                self._eos)
            slot.n_emitted += len(kept)
            slot.history.extend(kept)
            for tok in kept:
                self._emit(slot.rid, tok)
                emitted.append((slot.rid, tok))
            # accepted drafts that were actually USED: EOS-inside-window
            # or max_new room can truncate the emission below n_acc+1,
            # and the metrics must agree with what clients received
            n_used = min(n_acc, len(kept))
            self._n_spec_proposed += g
            self._n_spec_accepted += n_used
            self._n_spec_verifies += 1
            self._n_spec_emitted += len(kept)
            self._m_spec_len.observe(len(kept))
            self._m_spec_proposed.inc(g)
            self._m_spec_accepted.inc(n_used)
            if kept[-1] == self._eos or slot.n_emitted >= slot.max_new:
                self._retire(i)
            else:
                # commit the window prefix [cur, accepted drafts]; the
                # rejected tail rolls back by NOT advancing over it
                slot.cache_len += n_acc + 1
                slot.last_token = kept[-1]
                self._trim_blocks(i)
        if self._n_spec_proposed:
            self._m_spec_rate.set(
                self._n_spec_accepted / self._n_spec_proposed)
        return emitted

    def run(self) -> Dict[int, np.ndarray]:
        """Drive ``step()`` until queue and slots drain; returns (and
        drains) the tokens of every request completed since the last
        ``run()``, keyed by request id — a long-lived engine therefore
        never accumulates finished results."""
        while self._queue or self.num_active:
            self.step()
        done, self._done = self._done, {}
        return done

    def serve(self, prompts, max_new_tokens=None) -> List[np.ndarray]:
        """Batch convenience: submit all, run to completion, return
        token arrays in submission order."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        done = self.run()
        return [done[r] for r in rids]

    def stats(self) -> dict:
        """Scheduler/counter snapshot (tests + ops dashboards). In
        speculative mode ``decode_steps``/``decode_compiles`` count the
        verify executable (the spec-mode decode step)."""
        out = {
            "active": self.num_active,
            "queued": self.num_queued,
            "free_blocks": self._alloc.free_blocks,
            "reserved_blocks": self._reserved,
            "decode_steps": self._n_decode_steps,
            "decode_compiles": self._n_decode_compiles,
            "tokens_total": self._n_tokens,
            "requests_completed": self._n_completed,
        }
        if self._gamma:
            out.update({
                "spec_tokens_proposed": self._n_spec_proposed,
                "spec_tokens_accepted": self._n_spec_accepted,
                "spec_acceptance_rate":
                    self._n_spec_accepted / self._n_spec_proposed
                    if self._n_spec_proposed else 0.0,
                "spec_mean_accepted_len":
                    self._n_spec_emitted / self._n_spec_verifies
                    if self._n_spec_verifies else 0.0,
            })
        return out

    # -- scheduler internals ------------------------------------------

    def _emit(self, rid, tok):
        """Single exit point for generated tokens (prefill's first token
        AND every decode token) — the token counters live here so they
        agree exactly with what clients receive."""
        self._results[rid].append(tok)
        self._m_tokens.inc()
        self._n_tokens += 1
        if self._stream is not None:
            self._stream(rid, tok)

    def _next_key(self):
        """Greedy decode never consumes randomness — skip the per-step
        split (one device dispatch per token saved)."""
        if not self._do_sample:
            return self._key
        self._key, sub = jax.random.split(self._key)
        return sub

    def _admit(self) -> List[tuple]:
        emitted = []
        while self._queue:
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                break
            req = self._queue[0]
            n_real = int(req.prompt.size)
            worst = _pc.blocks_for(
                n_real + req.max_new_tokens + self._gamma, self._bs)
            init = _pc.blocks_for(n_real, self._bs)
            # worst-case reservation: admit only what can NEVER run the
            # pool dry mid-decode (FIFO — no head-of-line bypass, which
            # keeps "every request completes exactly once" trivial)
            if self._alloc.free_blocks - self._reserved < worst:
                break
            self._queue.popleft()
            i = free[0]
            blocks = self._alloc.alloc(init)
            self._reserved += worst - init
            self._tables[i, :] = 0
            self._tables[i, :init] = blocks
            self._tables_dev = None
            # observe BEFORE prefill so the histogram measures queue
            # wait, not prefill execution/compile time
            self._m_queue_wait.observe(
                1000.0 * (time.monotonic() - req.submit_time))
            self._results[req.request_id] = []
            tok = self._prefill(i, req, n_real)
            history = list(map(int, req.prompt)) + [tok] \
                if self._gamma else None
            self._slots[i] = _Slot(req.request_id, blocks, worst,
                                   n_real, tok, req.max_new_tokens,
                                   history=history)
            self._emit(req.request_id, tok)
            emitted.append((req.request_id, tok))
            self._m_occupancy.set(self.num_active)
            if tok == self._eos or req.max_new_tokens <= 1:
                self._retire(i)
        return emitted

    def _prefill(self, i, req, n_real) -> int:
        """Run the bucketed prefill for slot ``i``: dense cached forward
        over the right-padded prompt, K/V scattered into the slot's
        blocks, first token selected at the prompt's true last
        position."""
        bucket = self._bucket(n_real)
        ids = np.full((1, bucket), self._pad, np.int32)
        ids[0, :n_real] = req.prompt
        sub = self._next_key()
        exec_ = self._prefill_execs.get(bucket)
        if exec_ is None:
            exec_ = self._compile_prefill(bucket, sub)
            self._prefill_execs[bucket] = exec_
        with _quiet_donation():
            tok, self._pools = exec_(
                self._params, jnp.asarray(ids),
                jnp.asarray(n_real, jnp.int32), self._pools,
                jnp.asarray(self._tables[i]), sub)
        if self._draft_model is not None:
            # prime the draft model's cache with the same prompt K/V
            # (its pools share the slot's block table)
            dexec = self._draft_prefill_execs.get(bucket)
            if dexec is None:
                dexec = self._compile_draft_prefill(bucket)
                self._draft_prefill_execs[bucket] = dexec
            with _quiet_donation():
                self._dpools = dexec(
                    self._dparams, jnp.asarray(ids),
                    jnp.asarray(n_real, jnp.int32), self._dpools,
                    jnp.asarray(self._tables[i]))
        return int(tok)

    def _ensure_blocks(self, active, horizon=1):
        """Grow any slot whose next ``horizon`` write positions cross
        into unallocated blocks (covered by the admission reservation;
        speculative mode needs ``gamma + 1`` positions of headroom for
        the verify window)."""
        for i in active:
            slot = self._slots[i]
            need = _pc.blocks_for(slot.cache_len + horizon, self._bs)
            while len(slot.blocks) < need:
                (blk,) = self._alloc.alloc(1)
                self._tables[i, len(slot.blocks)] = blk
                slot.blocks.append(blk)
                self._tables_dev = None
                self._reserved -= 1

    def _trim_blocks(self, i):
        """Speculative rollback, block side: return blocks only the
        rejected window tail reached to the allocator (back under the
        slot's admission reservation; no cache data moves). Blocks
        within the NEXT window's reach (``cache_len + gamma + 1``
        positions) are kept: freeing them would be reservation-neutral
        (``free - reserved`` is invariant under trim, so admission
        capacity cannot improve) yet the very next `_ensure_blocks`
        would re-allocate them and re-upload the device block table —
        pure hot-loop churn. With a fixed gamma that makes mid-flight
        trims rare; retirement frees everything regardless."""
        slot = self._slots[i]
        need = _pc.blocks_for(slot.cache_len + self._gamma + 1,
                              self._bs)
        while len(slot.blocks) > need:
            blk = slot.blocks.pop()
            self._alloc.free([blk])
            self._tables[i, len(slot.blocks)] = 0
            self._reserved += 1
            self._tables_dev = None

    def _retire(self, i):
        slot = self._slots[i]
        self._alloc.free(slot.blocks)
        self._reserved -= slot.worst_blocks - len(slot.blocks)
        self._tables[i, :] = 0
        self._tables_dev = None
        self._slots[i] = None
        self._done[slot.rid] = np.asarray(self._results.pop(slot.rid),
                                          np.int64)
        self._m_completed.inc()
        self._n_completed += 1
        self._m_occupancy.set(self.num_active)

    def _bucket(self, n) -> int:
        from ..generation import _prompt_bucket
        return _prompt_bucket(n, self.config.min_prefill_bucket)

    # -- compiled steps -----------------------------------------------

    def _compile_decode(self, lens, toks, key):
        """AOT-compile the fixed-shape batched decode step ONCE; every
        later tick reuses the executable (shape change is impossible —
        slots, tables and lengths are static width)."""
        def decode(params, pools, tables, lens, toks, key):
            logits, pools = self._model_step(
                params, toks[:, None], pools, None,
                block_tables=tables, cache_lens=lens)
            _, sub = jax.random.split(key)
            tok, _ = self._select(logits[:, -1, :], sub)
            return tok, pools

        jitted = jax.jit(decode, donate_argnums=(1,))
        with _quiet_donation():
            exec_ = jitted.lower(
                self._params, self._pools, jnp.asarray(self._tables),
                jnp.asarray(lens), jnp.asarray(toks), key).compile()
        self._m_decode_compiles.inc()
        self._n_decode_compiles += 1
        return exec_

    def _compile_prefill(self, bucket, key):
        def prefill(params, ids, n_real, pools, table_row, key):
            dense = self.model.init_caches(1, bucket)
            logits, dense = self._model_step(
                params, ids, dense, jnp.zeros((), jnp.int32))
            pools = [
                _pc.write_prefill(kp, vp, table_row[None], dk, dv,
                                  n_real=n_real)
                for (kp, vp), (dk, dv) in zip(pools, dense)]
            last = jax.lax.dynamic_slice_in_dim(
                logits, n_real - 1, 1, axis=1)[:, 0, :]
            _, sub = jax.random.split(key)
            tok, _ = self._select(last, sub)
            return tok[0], pools

        jitted = jax.jit(prefill, donate_argnums=(3,))
        with _quiet_donation():
            exec_ = jitted.lower(
                self._params, jnp.zeros((1, bucket), jnp.int32),
                jnp.zeros((), jnp.int32), self._pools,
                jnp.zeros((self._mb,), jnp.int32), key).compile()
        self._m_prefill_compiles.labels(bucket=bucket).inc()
        return exec_

    def _compile_verify(self, lens, toks, dq, key):
        """AOT-compile the fixed-gamma multi-token verify step ONCE
        (the speculative decode executable — counted in
        ``decode_compiles`` so the zero-steady-state-recompile
        assertion covers speculative mode too)."""
        from ..generation import speculative as _spec
        cfg = self.config
        verify = _spec.build_verify_step(
            self._model_step, gamma=self._gamma,
            do_sample=self._do_sample, temperature=cfg.temperature,
            top_k=cfg.top_k, top_p=cfg.top_p,
            onehot_draft=self._draft_model is None)
        jitted = jax.jit(verify, donate_argnums=(1,))
        args = [self._params, self._pools, jnp.asarray(self._tables),
                jnp.asarray(lens), jnp.asarray(toks)]
        if self._do_sample:
            if dq is not None:
                args.append(dq)
            args.append(key)
        with _quiet_donation():
            exec_ = jitted.lower(*args).compile()
        self._m_decode_compiles.inc()
        self._n_decode_compiles += 1
        return exec_

    def _compile_draft(self, lens, toks, key):
        """AOT-compile the draft model's gamma+1-step proposal scan
        ONCE (drafter='model')."""
        from ..generation import speculative as _spec
        cfg = self.config
        loop = _spec.build_draft_loop(
            self._draft_step, gamma=self._gamma,
            do_sample=self._do_sample, temperature=cfg.temperature,
            top_k=cfg.top_k, top_p=cfg.top_p,
            want_probs=self._do_sample)
        jitted = jax.jit(loop, donate_argnums=(1,))
        with _quiet_donation():
            exec_ = jitted.lower(
                self._dparams, self._dpools, jnp.asarray(self._tables),
                jnp.asarray(lens), jnp.asarray(toks[:, 0]),
                key).compile()
        return exec_

    def _compile_draft_prefill(self, bucket):
        """Draft-cache twin of ``_compile_prefill``: scatter the draft
        model's prompt K/V into its pools through the SAME block table
        row (no token is selected — the target picks the first
        token)."""
        def dprefill(dparams, ids, n_real, dpools, table_row):
            dense = self._draft_model.init_caches(1, bucket)
            _, dense = self._draft_step(dparams, ids, dense,
                                        jnp.zeros((), jnp.int32))
            return [
                _pc.write_prefill(kp, vp, table_row[None], dk, dv,
                                  n_real=n_real)
                for (kp, vp), (dk, dv) in zip(dpools, dense)]

        jitted = jax.jit(dprefill, donate_argnums=(3,))
        with _quiet_donation():
            exec_ = jitted.lower(
                self._dparams, jnp.zeros((1, bucket), jnp.int32),
                jnp.zeros((), jnp.int32), self._dpools,
                jnp.zeros((self._mb,), jnp.int32)).compile()
        self._m_prefill_compiles.labels(
            bucket=f"draft-{bucket}").inc()
        return exec_
