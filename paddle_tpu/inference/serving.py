"""Continuous-batching LLM serving engine over the paged KV cache.

The serving role PaddleNLP's ``llm/predict/predictor.py`` + a request
scheduler play over AnalysisPredictor, rebuilt TPU-first for the
compiler's static-shape world (arxiv 2603.09555) with the block-table
paged KV layout of *Ragged Paged Attention* (arxiv 2604.15464):

- **Fixed slots, one compiled decode step.** The engine owns
  ``num_slots`` serving slots. Every decode step runs ALL slots through
  one batched model call — token ids [S, 1], block tables [S, MB],
  per-slot lengths [S] — whose shapes never change, so the step is
  AOT-compiled exactly once and steady state runs ZERO recompiles
  (assert via the ``serving_decode_compiles`` / ``serving_decode_steps``
  monitor counters). Raggedness lives in the table/length VALUES.
- **Paged KV.** All slots share one block pool per layer
  (``ops/paged_cache.py``); the host-side ``BlockAllocator`` hands
  blocks to admitted requests and reclaims them at retirement, so HBM
  scales with live tokens, not ``slots x max_len``.
- **Continuous batching.** ``step()`` admits queued requests into freed
  slots (prefill compiled per power-of-two prompt bucket, K/V scattered
  straight into the slot's blocks), decodes one token for every active
  slot, streams tokens out, and retires slots on EOS/max-len — freed
  blocks and slots are reused by the next admission without ever
  draining the batch.
- **Ragged decode attention** reads the pool through the Pallas kernel
  on TPU (``ops/pallas/paged_attention.py``) and the gather fallback on
  CPU, behind the models' ordinary cached-attention path — the same
  code ``generate(cache_impl="paged")`` rides.

Admission is worst-case reserved: a request is admitted only when the
pool can cover ``prompt + max_new`` blocks for it PLUS the outstanding
reservations of every active slot, so mid-decode pool exhaustion is
impossible by construction (no preemption path needed).

Telemetry (monitor registry, exported in the JSONL dump):
``serving_slot_occupancy`` gauge, ``serving_batch_utilization`` /
``serving_queue_wait_ms`` histograms, ``serving_tokens_total`` /
``serving_decode_steps`` / ``serving_decode_compiles`` /
``serving_prefill_compiles`` / ``serving_requests_completed`` counters.
"""
from __future__ import annotations

import contextlib
import time
import warnings
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import monitor
from ..ops import paged_cache as _pc

__all__ = ["ServingConfig", "ServingRequest", "ServingEngine"]


@contextlib.contextmanager
def _quiet_donation():
    """Pool donation is a TPU-side optimization (decode/prefill reuse
    the pool's HBM in place); CPU ignores donation with a warning that
    would fire every engine tick. Scoped here so other code's genuinely
    broken donations still surface."""
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        yield


@dataclass
class ServingConfig:
    num_slots: int = 8                  # fixed decode batch width
    block_size: int = 16                # tokens per KV block
    max_model_len: int = 1024           # prompt + generated cap per seq
    # pool size; default covers every slot at max_model_len (admission
    # then never queues on blocks, only on slots) — shrink to trade HBM
    # for queueing
    num_blocks: Optional[int] = None
    max_new_tokens: int = 128           # per-request default
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    decode_strategy: str = "greedy_search"   # or "sampling"
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    min_prefill_bucket: int = 16        # smallest prompt bucket


@dataclass
class ServingRequest:
    request_id: int
    prompt: np.ndarray                  # [L] int32
    max_new_tokens: int
    submit_time: float = field(default_factory=time.monotonic)


class _Slot:
    __slots__ = ("rid", "blocks", "worst_blocks", "cache_len",
                 "last_token", "n_emitted", "max_new")

    def __init__(self, rid, blocks, worst_blocks, cache_len, last_token,
                 max_new):
        self.rid = rid
        self.blocks = blocks            # allocated block ids (ordered)
        self.worst_blocks = worst_blocks
        self.cache_len = cache_len      # valid cache positions
        self.last_token = last_token
        self.n_emitted = 1              # prefill emitted the first token
        self.max_new = max_new


class ServingEngine:
    """Continuous-batching serving over a causal-LM with the paged-KV
    protocol (``init_paged_caches`` + ``block_tables``/``cache_lens``
    forward kwargs — Llama/Qwen2/GPT families).

    Usage::

        engine = ServingEngine(model, ServingConfig(num_slots=8))
        rid = engine.submit([1, 2, 3], max_new_tokens=32)
        results = engine.run()          # {rid: np.ndarray of tokens}

    or stream: pass ``stream_callback=lambda rid, tok: ...`` and drive
    ``step()`` yourself.
    """

    def __init__(self, model, config: Optional[ServingConfig] = None,
                 stream_callback: Optional[Callable] = None):
        from ..generation import GenerationMixin, _select_token
        if not isinstance(model, GenerationMixin):
            raise TypeError(
                f"{type(model).__name__} does not support generation "
                "(needs the KV-cache protocol)")
        if not hasattr(model, "init_paged_caches"):
            raise TypeError(
                f"{type(model).__name__} does not implement "
                "init_paged_caches (paged-KV serving)")
        cfg = config or ServingConfig()
        if cfg.decode_strategy not in ("greedy_search", "sampling"):
            raise NotImplementedError(
                f"serving decode_strategy {cfg.decode_strategy!r}; "
                "supported: greedy_search, sampling")
        max_pos = getattr(getattr(model, "config", None),
                          "max_position_embeddings", None)
        if max_pos is not None and cfg.max_model_len > max_pos:
            raise ValueError(
                f"max_model_len ({cfg.max_model_len}) exceeds the "
                f"model's max_position_embeddings ({max_pos})")
        self.model = model
        self.config = cfg
        self._stream = stream_callback
        model.eval()

        from ..jit import _LayerBinder
        binder = _LayerBinder(model)
        self._params = binder.param_arrays()
        self._model_step = model._build_model_step(
            binder, binder.buffer_arrays())
        do_sample = cfg.decode_strategy == "sampling"
        self._do_sample = do_sample
        self._select = lambda lg, k: _select_token(
            lg, k, do_sample=do_sample, temperature=cfg.temperature,
            top_k=cfg.top_k, top_p=cfg.top_p)

        self._bs = int(cfg.block_size)
        self._mb = _pc.blocks_for(cfg.max_model_len, self._bs)
        nb = (1 + cfg.num_slots * self._mb) if cfg.num_blocks is None \
            else int(cfg.num_blocks)
        self._alloc = _pc.BlockAllocator(nb)
        self._pools = model.init_paged_caches(nb, self._bs)
        self._tables = np.zeros((cfg.num_slots, self._mb), np.int32)
        self._slots: List[Optional[_Slot]] = [None] * cfg.num_slots
        self._reserved = 0              # blocks promised to active slots
        self._queue: deque = deque()
        self._results: Dict[int, list] = {}
        self._done: Dict[int, np.ndarray] = {}
        self._next_rid = 0
        self._eos = -1 if cfg.eos_token_id is None \
            else int(cfg.eos_token_id)
        self._pad = int(cfg.pad_token_id)
        self._key = jax.random.PRNGKey(int(cfg.seed))
        self._tables_dev = None         # device mirror of _tables
        self._decode_exec = None
        self._prefill_execs = {}
        # per-engine counts (the monitor counters below are process-
        # global telemetry shared by every engine; stats() must report
        # THIS engine)
        self._n_decode_compiles = 0
        self._n_decode_steps = 0
        self._n_tokens = 0
        self._n_completed = 0

        # -- telemetry ------------------------------------------------
        self._m_occupancy = monitor.gauge(
            "serving_slot_occupancy", "active serving slots")
        self._m_util = monitor.histogram(
            "serving_batch_utilization",
            "active slots / num_slots per decode step",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
        self._m_queue_wait = monitor.histogram(
            "serving_queue_wait_ms", "submit -> admission wait")
        self._m_tokens = monitor.counter(
            "serving_tokens_total", "tokens generated (all requests)")
        self._m_steps = monitor.counter(
            "serving_decode_steps", "batched decode steps executed")
        self._m_decode_compiles = monitor.counter(
            "serving_decode_compiles",
            "decode-step compilations (steady state: stays at 1)")
        self._m_prefill_compiles = monitor.counter(
            "serving_prefill_compiles",
            "prefill compilations per prompt bucket",
            labels=("bucket",))
        self._m_completed = monitor.counter(
            "serving_requests_completed", "requests fully served")

    # -- public API ---------------------------------------------------

    def submit(self, prompt, max_new_tokens=None) -> int:
        """Queue one request; returns its request id. Tokens stream to
        ``stream_callback`` as ``step()``/``run()`` produce them."""
        ids = np.asarray(prompt, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        max_new = int(self.config.max_new_tokens
                      if max_new_tokens is None else max_new_tokens)
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, "
                             f"got {max_new}")
        if ids.size + max_new > self.config.max_model_len:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens ({max_new}) "
                f"exceeds max_model_len ({self.config.max_model_len})")
        worst = _pc.blocks_for(ids.size + max_new, self._bs)
        if worst > self._alloc.num_blocks - 1:
            raise ValueError(
                f"request needs {worst} blocks; pool has only "
                f"{self._alloc.num_blocks - 1}")
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(ServingRequest(rid, ids, max_new))
        return rid

    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def num_queued(self) -> int:
        return len(self._queue)

    def step(self) -> List[tuple]:
        """One engine tick: admit what fits, decode one token for every
        active slot, retire finished sequences. Returns this tick's
        ``[(request_id, token), ...]`` (admission prefills included)."""
        emitted = self._admit()
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return emitted
        self._ensure_blocks(active)

        cfg = self.config
        lens = np.zeros(cfg.num_slots, np.int32)
        toks = np.full(cfg.num_slots, self._pad, np.int32)
        for i in active:
            lens[i] = self._slots[i].cache_len
            toks[i] = self._slots[i].last_token
        sub = self._next_key()
        if self._tables_dev is None:    # only re-upload after changes
            self._tables_dev = jnp.asarray(self._tables)
        if self._decode_exec is None:
            self._decode_exec = self._compile_decode(lens, toks, sub)
        with _quiet_donation():
            out, self._pools = self._decode_exec(
                self._params, self._pools, self._tables_dev,
                jnp.asarray(lens), jnp.asarray(toks), sub)
        out = np.asarray(out)

        self._m_steps.inc()
        self._n_decode_steps += 1
        self._m_util.observe(len(active) / cfg.num_slots)
        for i in active:
            slot = self._slots[i]
            tok = int(out[i])
            slot.cache_len += 1
            slot.last_token = tok
            slot.n_emitted += 1
            self._emit(slot.rid, tok)
            emitted.append((slot.rid, tok))
            if tok == self._eos or slot.n_emitted >= slot.max_new:
                self._retire(i)
        return emitted

    def run(self) -> Dict[int, np.ndarray]:
        """Drive ``step()`` until queue and slots drain; returns (and
        drains) the tokens of every request completed since the last
        ``run()``, keyed by request id — a long-lived engine therefore
        never accumulates finished results."""
        while self._queue or self.num_active:
            self.step()
        done, self._done = self._done, {}
        return done

    def serve(self, prompts, max_new_tokens=None) -> List[np.ndarray]:
        """Batch convenience: submit all, run to completion, return
        token arrays in submission order."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        done = self.run()
        return [done[r] for r in rids]

    def stats(self) -> dict:
        """Scheduler/counter snapshot (tests + ops dashboards)."""
        return {
            "active": self.num_active,
            "queued": self.num_queued,
            "free_blocks": self._alloc.free_blocks,
            "reserved_blocks": self._reserved,
            "decode_steps": self._n_decode_steps,
            "decode_compiles": self._n_decode_compiles,
            "tokens_total": self._n_tokens,
            "requests_completed": self._n_completed,
        }

    # -- scheduler internals ------------------------------------------

    def _emit(self, rid, tok):
        """Single exit point for generated tokens (prefill's first token
        AND every decode token) — the token counters live here so they
        agree exactly with what clients receive."""
        self._results[rid].append(tok)
        self._m_tokens.inc()
        self._n_tokens += 1
        if self._stream is not None:
            self._stream(rid, tok)

    def _next_key(self):
        """Greedy decode never consumes randomness — skip the per-step
        split (one device dispatch per token saved)."""
        if not self._do_sample:
            return self._key
        self._key, sub = jax.random.split(self._key)
        return sub

    def _admit(self) -> List[tuple]:
        emitted = []
        while self._queue:
            free = [i for i, s in enumerate(self._slots) if s is None]
            if not free:
                break
            req = self._queue[0]
            n_real = int(req.prompt.size)
            worst = _pc.blocks_for(n_real + req.max_new_tokens, self._bs)
            init = _pc.blocks_for(n_real, self._bs)
            # worst-case reservation: admit only what can NEVER run the
            # pool dry mid-decode (FIFO — no head-of-line bypass, which
            # keeps "every request completes exactly once" trivial)
            if self._alloc.free_blocks - self._reserved < worst:
                break
            self._queue.popleft()
            i = free[0]
            blocks = self._alloc.alloc(init)
            self._reserved += worst - init
            self._tables[i, :] = 0
            self._tables[i, :init] = blocks
            self._tables_dev = None
            # observe BEFORE prefill so the histogram measures queue
            # wait, not prefill execution/compile time
            self._m_queue_wait.observe(
                1000.0 * (time.monotonic() - req.submit_time))
            self._results[req.request_id] = []
            tok = self._prefill(i, req, n_real)
            self._slots[i] = _Slot(req.request_id, blocks, worst,
                                   n_real, tok, req.max_new_tokens)
            self._emit(req.request_id, tok)
            emitted.append((req.request_id, tok))
            self._m_occupancy.set(self.num_active)
            if tok == self._eos or req.max_new_tokens <= 1:
                self._retire(i)
        return emitted

    def _prefill(self, i, req, n_real) -> int:
        """Run the bucketed prefill for slot ``i``: dense cached forward
        over the right-padded prompt, K/V scattered into the slot's
        blocks, first token selected at the prompt's true last
        position."""
        bucket = self._bucket(n_real)
        ids = np.full((1, bucket), self._pad, np.int32)
        ids[0, :n_real] = req.prompt
        sub = self._next_key()
        exec_ = self._prefill_execs.get(bucket)
        if exec_ is None:
            exec_ = self._compile_prefill(bucket, sub)
            self._prefill_execs[bucket] = exec_
        with _quiet_donation():
            tok, self._pools = exec_(
                self._params, jnp.asarray(ids),
                jnp.asarray(n_real, jnp.int32), self._pools,
                jnp.asarray(self._tables[i]), sub)
        return int(tok)

    def _ensure_blocks(self, active):
        """Grow any slot whose next write position crosses into an
        unallocated block (covered by the admission reservation)."""
        for i in active:
            slot = self._slots[i]
            bi = slot.cache_len // self._bs
            if bi >= len(slot.blocks):
                (blk,) = self._alloc.alloc(1)
                slot.blocks.append(blk)
                self._tables[i, bi] = blk
                self._tables_dev = None
                self._reserved -= 1

    def _retire(self, i):
        slot = self._slots[i]
        self._alloc.free(slot.blocks)
        self._reserved -= slot.worst_blocks - len(slot.blocks)
        self._tables[i, :] = 0
        self._tables_dev = None
        self._slots[i] = None
        self._done[slot.rid] = np.asarray(self._results.pop(slot.rid),
                                          np.int64)
        self._m_completed.inc()
        self._n_completed += 1
        self._m_occupancy.set(self.num_active)

    def _bucket(self, n) -> int:
        from ..generation import _prompt_bucket
        return _prompt_bucket(n, self.config.min_prefill_bucket)

    # -- compiled steps -----------------------------------------------

    def _compile_decode(self, lens, toks, key):
        """AOT-compile the fixed-shape batched decode step ONCE; every
        later tick reuses the executable (shape change is impossible —
        slots, tables and lengths are static width)."""
        def decode(params, pools, tables, lens, toks, key):
            logits, pools = self._model_step(
                params, toks[:, None], pools, None,
                block_tables=tables, cache_lens=lens)
            _, sub = jax.random.split(key)
            tok, _ = self._select(logits[:, -1, :], sub)
            return tok, pools

        jitted = jax.jit(decode, donate_argnums=(1,))
        with _quiet_donation():
            exec_ = jitted.lower(
                self._params, self._pools, jnp.asarray(self._tables),
                jnp.asarray(lens), jnp.asarray(toks), key).compile()
        self._m_decode_compiles.inc()
        self._n_decode_compiles += 1
        return exec_

    def _compile_prefill(self, bucket, key):
        def prefill(params, ids, n_real, pools, table_row, key):
            dense = self.model.init_caches(1, bucket)
            logits, dense = self._model_step(
                params, ids, dense, jnp.zeros((), jnp.int32))
            pools = [
                _pc.write_prefill(kp, vp, table_row[None], dk, dv,
                                  n_real=n_real)
                for (kp, vp), (dk, dv) in zip(pools, dense)]
            last = jax.lax.dynamic_slice_in_dim(
                logits, n_real - 1, 1, axis=1)[:, 0, :]
            _, sub = jax.random.split(key)
            tok, _ = self._select(last, sub)
            return tok[0], pools

        jitted = jax.jit(prefill, donate_argnums=(3,))
        with _quiet_donation():
            exec_ = jitted.lower(
                self._params, jnp.zeros((1, bucket), jnp.int32),
                jnp.zeros((), jnp.int32), self._pools,
                jnp.zeros((self._mb,), jnp.int32), key).compile()
        self._m_prefill_compiles.labels(bucket=bucket).inc()
        return exec_
