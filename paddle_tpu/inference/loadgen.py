"""Closed-loop SLO / goodput load generator for ``ServingEngine`` —
or ANY engine-shaped target: the harness only needs ``submit(prompt,
max_new_tokens)`` / ``step()`` / ``num_queued`` / ``num_active`` / a
chainable ``_stream`` callback slot, which ``EngineCluster``
(``inference/cluster.py``) implements too, so the same workload
measures one replica or a whole routed fleet unchanged.

The harness every serving feature proves itself against (ROADMAP "an
async serving front door ... closed-loop load-generator measuring
goodput under SLO"): drive an engine under a TIMED arrival process and
measure what a client would see —

- **open loop** (the default): requests arrive on a fixed schedule at a
  target QPS (seeded-Poisson or uniform gaps) whether or not the engine
  keeps up — the regime where queueing delay and tail latency actually
  appear (a closed loop self-throttles and can never overload the
  engine, which is exactly what hides SLO violations).
- **closed loop**: a fixed number of in-flight requests, each replaced
  on completion — measures capacity (max sustainable throughput), used
  here to calibrate the open-loop offered load.

Per-request metrics are CLIENT-side (wall-clock around ``submit()`` and
the streaming callback): TTFT = submit -> first streamed token, ITL =
gaps between consecutive streamed tokens, TPOT = mean ITL, e2e =
submit -> last token. A request **meets SLO** when ``ttft <=
slo.ttft_ms`` AND ``tpot <= slo.itl_ms``; **goodput** is the fraction
of SUBMITTED requests meeting SLO — a request that never completes
counts against it (the throughput the fleet can charge for, vs the
tok/s it merely emits). The engine's own always-on P²
digests measure the same quantities server-side; the two agree to
within digest error plus callback overhead.
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["SLO", "RequestRecord", "poisson_arrivals",
           "uniform_arrivals", "profile_arrivals", "run_load",
           "summarize", "conversation_workload", "write_records"]


@dataclass
class SLO:
    """Per-request latency budget: a request is 'good' when its TTFT
    and its mean inter-token latency (TPOT) both fit."""
    ttft_ms: float = 1000.0
    itl_ms: float = 200.0


@dataclass
class RequestRecord:
    """Client-side timeline of one request (monotonic seconds)."""
    rid: int
    arrival_s: float                    # scheduled arrival offset
    submit_t: float                     # actual submit() wall time
    token_t: List[float] = field(default_factory=list)
    priority: int = 0                   # scheduling class (preemptive
    #                                     engines; 0 = default class)
    # LoRA adapter the request was served under (submit(adapter_id=));
    # None = base model (ISSUE 18 satellite)
    adapter: Optional[int] = None
    # replica index the cluster router placed the request on (from
    # EngineCluster.owner_of at submit time); None for a plain engine
    replica: Optional[int] = None

    @property
    def completed(self) -> bool:
        return bool(self.token_t)

    @property
    def ttft_ms(self) -> float:
        return 1000.0 * (self.token_t[0] - self.submit_t)

    @property
    def itl_ms(self) -> List[float]:
        return [1000.0 * (b - a)
                for a, b in zip(self.token_t, self.token_t[1:])]

    @property
    def tpot_ms(self) -> float:
        """Mean time-per-output-token after the first."""
        gaps = self.itl_ms
        return float(np.mean(gaps)) if gaps else 0.0

    @property
    def e2e_ms(self) -> float:
        return 1000.0 * (self.token_t[-1] - self.submit_t)

    def meets(self, slo: SLO) -> bool:
        return self.completed and self.ttft_ms <= slo.ttft_ms \
            and self.tpot_ms <= slo.itl_ms


def poisson_arrivals(n: int, qps: float, seed: int = 0) -> np.ndarray:
    """Open-loop Poisson arrival offsets (seconds from start): i.i.d.
    exponential gaps at rate ``qps`` — the memoryless process real
    front-door traffic approximates."""
    rng = np.random.RandomState(seed)
    return np.cumsum(rng.exponential(1.0 / float(qps), size=n))


def uniform_arrivals(n: int, qps: float) -> np.ndarray:
    """Deterministic fixed-gap arrivals at ``qps`` (no burstiness —
    the lower bound on queueing delay at a given offered load)."""
    return (1.0 + np.arange(n)) / float(qps)


def _profile_rate(profile: dict, qps: float, t: float) -> float:
    """Instantaneous arrival rate of a shaped-load profile at offset
    ``t`` seconds — the λ(t) of the inhomogeneous Poisson process
    :func:`profile_arrivals` draws from. Floored at 5% of the base
    rate so the process always terminates."""
    kind = profile.get("kind")
    if kind == "sine":
        # diurnal-ish swing: qps * (1 ± depth) over period_s
        period = float(profile.get("period_s", 60.0))
        depth = float(profile.get("depth", 0.5))
        m = 1.0 + depth * np.sin(2.0 * np.pi * t / period)
    elif kind == "ramp":
        # linear warm-up from start_frac*qps to qps over ramp_s
        ramp = float(profile.get("ramp_s", 60.0))
        f0 = float(profile.get("start_frac", 0.1))
        m = f0 + (1.0 - f0) * min(t / ramp, 1.0)
    elif kind == "step":
        # square-wave burst: high*qps for the first half of each
        # period_s, low*qps for the second (the scale-up chaos shape)
        period = float(profile.get("period_s", 60.0))
        hi = float(profile.get("high", 2.0))
        lo = float(profile.get("low", 0.25))
        m = hi if (t % period) < period / 2.0 else lo
    else:
        raise ValueError(
            f"qps_profile kind must be sine|ramp|step, got {kind!r}")
    return float(qps) * max(float(m), 0.05)


def profile_arrivals(n: int, qps: float, profile: dict,
                     seed: int = 0) -> np.ndarray:
    """Open-loop SHAPED arrival offsets (seconds from start): an
    inhomogeneous Poisson process whose instantaneous rate follows
    ``profile`` around the base ``qps`` — the burst/ramp/diurnal
    workloads the elastic autoscaler is measured against (a constant
    rate never exercises scale-down). Seeded and sequential
    (``t += Exp(1/λ(t))``), so a given ``(n, qps, profile, seed)`` is
    reproducible byte-for-byte. Profiles::

        {"kind": "sine", "period_s": 60, "depth": 0.5}
        {"kind": "ramp", "ramp_s": 60, "start_frac": 0.1}
        {"kind": "step", "period_s": 60, "high": 2.0, "low": 0.25}
    """
    rng = np.random.RandomState(seed)
    out = np.empty(n, np.float64)
    t = 0.0
    for i in range(n):
        t += rng.exponential(1.0 / _profile_rate(profile, qps, t))
        out[i] = t
    return out


def conversation_workload(n_sessions: int, turns: int, *,
                          vocab: int = 1000, prefix_len: int = 32,
                          turn_len: int = 8, seed: int = 0):
    """Multi-session CONVERSATION workload: each session's turn ``t``
    prompt is its turn ``t-1`` prompt plus a fresh user chunk (same
    session id -> same growing prefix), interleaved round-robin across
    sessions (session 0 turn 0, session 1 turn 0, ..., session 0
    turn 1, ...) so later turns arrive after earlier ones had a chance
    to retire and publish their blocks.

    This is the workload that actually EXERCISES prefix caching and
    cluster session affinity under load: a later turn's leading blocks
    hash-hit the engine (or the routed replica) that served the
    previous turn, while round-robin interleaving keeps every replica
    busy. Returns ``(prompts, session_ids)`` — a flat prompt list in
    arrival order plus each prompt's session id (tests assert
    per-session replica stickiness with it)."""
    rng = np.random.RandomState(seed)
    convo = [rng.randint(1, vocab, (prefix_len,)).astype(np.int32)
             for _ in range(n_sessions)]
    prompts, session_ids = [], []
    for _t in range(turns):
        for s in range(n_sessions):
            convo[s] = np.concatenate(
                [convo[s],
                 rng.randint(1, vocab, (turn_len,)).astype(np.int32)])
            prompts.append(convo[s].copy())
            session_ids.append(s)
    return prompts, session_ids


def run_load(engine, prompts: Sequence[np.ndarray], *,
             qps: Optional[float] = None, mode: str = "open",
             concurrency: Optional[int] = None,
             max_new_tokens: Optional[int] = None,
             slo: Optional[SLO] = None, arrival: str = "poisson",
             priorities: Optional[Sequence[int]] = None,
             adapter_ids: Optional[Sequence[Optional[int]]] = None,
             record_path: Optional[str] = None,
             qps_profile: Optional[dict] = None,
             seed: int = 0) -> dict:
    """Serve ``prompts`` through ``engine`` — a ``ServingEngine`` OR
    any object with the same ``submit/step/num_queued/num_active/
    _stream`` surface (``EngineCluster``) — under a timed arrival
    process and return the goodput report (:func:`summarize`).

    ``mode="open"`` (requires ``qps``): requests are submitted when
    their scheduled arrival time passes, independent of engine
    progress. ``mode="closed"`` (``concurrency``, default the
    target's slot capacity — a cluster's aggregate decode slots): a
    fixed number in flight, each completion admits the next —
    reported ``achieved_qps`` is then the target's capacity at that
    concurrency.

    ``priorities`` (one int per prompt) forwards each request's
    scheduling class to ``submit(priority=)`` — the mixed-priority
    overload workloads the preemptive scheduler is measured on — and
    the report gains a ``by_priority`` breakdown (per-class goodput /
    TTFT / TPOT, each class its own SLO denominator).

    ``adapter_ids`` (one Optional[int] per prompt, ISSUE 18
    satellite) forwards each request's LoRA adapter to
    ``submit(adapter_id=)`` — the mixed-tenant multi-adapter
    workloads batched LoRA serving is measured on — and the report
    gains a ``by_adapter`` breakdown (per-adapter goodput / TTFT /
    TPOT; the base model appears under key ``"base"``). NDJSON rows
    carry the adapter in an ``adapter`` field.

    ``qps_profile`` (ISSUE 19 satellite) shapes the open-loop arrival
    RATE around the base ``qps``: a :func:`profile_arrivals` dict
    (``{"kind": "sine"|"ramp"|"step", ...}``) replaces the
    constant-rate schedule with a seeded inhomogeneous Poisson
    process — the burst/ramp/diurnal workloads elastic autoscaling is
    measured on. The profile is echoed in the report and in every
    NDJSON row; when absent, schedules and records are byte-identical
    to the fixed-QPS harness.

    ``record_path`` (ISSUE 15 satellite) additionally writes ONE
    NDJSON row per request (:func:`write_records`: submit /
    first-token / last-token monotonic timestamps, priority, outcome,
    routed replica) so offline analysis can join load-gen records
    against the cluster's merged trace — the trace's ``ts`` values
    are the same ``time.monotonic()`` base in integer microseconds.

    The target's ``stream_callback`` is chained, not replaced: an
    application callback installed at construction still fires.
    """
    if mode not in ("open", "closed"):
        raise ValueError(f"mode must be open|closed, got {mode!r}")
    if mode == "open" and not qps:
        raise ValueError("open-loop mode needs a target qps")
    if qps_profile is not None and mode != "open":
        raise ValueError(
            "qps_profile shapes the OPEN-loop arrival rate; a closed "
            "loop has no arrival schedule to shape")
    if priorities is not None and len(priorities) != len(prompts):
        raise ValueError(
            f"priorities ({len(priorities)}) must match prompts "
            f"({len(prompts)})")
    if adapter_ids is not None and len(adapter_ids) != len(prompts):
        raise ValueError(
            f"adapter_ids ({len(adapter_ids)}) must match prompts "
            f"({len(prompts)})")
    slo = slo or SLO()
    n = len(prompts)
    records: Dict[int, RequestRecord] = {}

    owner_of = getattr(engine, "owner_of", None)

    def _submit(idx, arrival_s):
        kw = {} if priorities is None \
            else {"priority": int(priorities[idx])}
        aid = None if adapter_ids is None else adapter_ids[idx]
        if aid is not None:
            kw["adapter_id"] = int(aid)
        rid = engine.submit(prompts[idx], max_new_tokens, **kw)
        owner = owner_of(rid) if owner_of is not None else None
        records[rid] = RequestRecord(
            rid, float(arrival_s), time.monotonic(),
            priority=0 if priorities is None
            else int(priorities[idx]),
            adapter=None if aid is None else int(aid),
            replica=owner[0] if owner is not None else None)
        return rid

    prev_cb = engine._stream

    def _record(rid, tok):
        rec = records.get(rid)
        if rec is not None:
            rec.token_t.append(time.monotonic())
        if prev_cb is not None:
            prev_cb(rid, tok)

    if mode == "open":
        if qps_profile is not None:
            offsets = profile_arrivals(n, qps, qps_profile, seed)
        elif arrival == "poisson":
            offsets = poisson_arrivals(n, qps, seed)
        else:
            offsets = uniform_arrivals(n, qps)
    else:
        offsets = np.zeros(n)
        # slot capacity of the target: a cluster exposes its aggregate
        # decode slots as a num_slots property; a plain engine carries
        # the count on its config (a ClusterConfig has neither — e.g.
        # a cluster whose decode tier fully failed reports 0 — so
        # fail with the actual problem, not an AttributeError)
        cap = (concurrency or getattr(engine, "num_slots", 0)
               or getattr(engine.config, "num_slots", 0))
        if not cap:
            raise ValueError(
                "closed-loop mode needs a concurrency: the target "
                "reports no slot capacity")
        concurrency = int(cap)

    engine._stream = _record
    t_start = time.monotonic()
    try:
        idx = 0
        while idx < n or engine.num_queued or engine.num_active:
            now = time.monotonic() - t_start
            if mode == "open":
                while idx < n and offsets[idx] <= now:
                    _submit(idx, offsets[idx])
                    idx += 1
            else:
                while idx < n and (engine.num_queued
                                   + engine.num_active) < concurrency:
                    _submit(idx, now)
                    idx += 1
            if engine.num_queued or engine.num_active:
                engine.step()
            elif idx < n:
                # idle until the next scheduled arrival (open loop)
                time.sleep(min(max(offsets[idx] - (
                    time.monotonic() - t_start), 0.0), 0.01))
        wall = time.monotonic() - t_start
    finally:
        engine._stream = prev_cb

    offered = float(qps) if mode == "open" else \
        (n / wall if wall > 0 else 0.0)
    report = summarize(list(records.values()), slo, wall,
                       offered_qps=offered, mode=mode)
    if qps_profile is not None:
        report["qps_profile"] = dict(qps_profile)
    if record_path is not None:
        # async tick pipeline (ISSUE 20): stamp the target's commit
        # lag onto every row — under async_depth=1 each token_t is
        # observed one tick after its device step, so SLO digests
        # computed offline need the lag to interpret the timestamps
        try:
            lag = int(engine.stats().get("async_depth", 0))
        except Exception:           # torn down before the snapshot
            lag = 0
        report["record_path"] = write_records(
            records.values(), record_path, slo=slo,
            qps_profile=qps_profile, commit_lag_ticks=lag)
    return report


def write_records(records, path: str, slo: Optional[SLO] = None,
                  qps_profile: Optional[dict] = None,
                  commit_lag_ticks: int = 0) -> str:
    """One NDJSON row per request (ISSUE 15 satellite): submit /
    first-token / last-token timestamps (``time.monotonic()``
    seconds — the SAME clock base the span tracer exports, whose
    Chrome ``ts`` is monotonic microseconds, so rows join against a
    merged trace by rid + time), priority, routed replica and
    outcome. With ``slo``, each row also carries ``slo_met``
    (ISSUE 17 satellite: TTFT+TPOT vs the configured SLO — the
    health engine's burn-rate inputs, validatable offline against
    the recorded trace). With ``qps_profile`` (ISSUE 19: shaped-load
    runs), every row carries the profile dict — offline analysis can
    reconstruct the offered λ(t) each request arrived under; rows of
    a fixed-QPS run are byte-identical to before the knob existed.
    ``commit_lag_ticks`` (ISSUE 20) records the serving target's
    ``async_depth`` at collection time: under the async tick pipeline
    the stream callback — and therefore every ``token_t`` stamp —
    fires at COMMIT, one tick after the device produced the token, so
    offline TTFT/ITL analysis knows the observation lag (0 = stamps
    are same-tick, the sync loop).
    Returns ``path``."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        for r in sorted(records, key=lambda r: r.rid):
            row = {
                "rid": r.rid,
                "priority": r.priority,
                "adapter": r.adapter,
                "replica": r.replica,
                "arrival_s": round(float(r.arrival_s), 6),
                "submit_t_s": r.submit_t,
                "first_token_t_s": r.token_t[0] if r.token_t
                else None,
                "last_token_t_s": r.token_t[-1] if r.token_t
                else None,
                "n_tokens": len(r.token_t),
                "ttft_ms": round(r.ttft_ms, 3) if r.completed
                else None,
                "tpot_ms": round(r.tpot_ms, 3) if r.completed
                else None,
                "e2e_ms": round(r.e2e_ms, 3) if r.completed
                else None,
                "outcome": "completed" if r.completed
                else "no_tokens",
                "commit_lag_ticks": int(commit_lag_ticks),
            }
            if slo is not None:
                row["slo_met"] = bool(r.meets(slo))
            if qps_profile is not None:
                row["qps_profile"] = dict(qps_profile)
            f.write(json.dumps(row) + "\n")
    return path


def summarize(records: List[RequestRecord], slo: SLO, wall_s: float,
              offered_qps: Optional[float] = None,
              mode: str = "open") -> dict:
    """Aggregate client-side records into the goodput report."""
    done = [r for r in records if r.completed]
    ttfts = np.asarray([r.ttft_ms for r in done]) \
        if done else np.zeros(0)
    itls = np.asarray([g for r in done for g in r.itl_ms])
    tpots = np.asarray([r.tpot_ms for r in done]) \
        if done else np.zeros(0)
    e2es = np.asarray([r.e2e_ms for r in done]) \
        if done else np.zeros(0)
    n_tokens = sum(len(r.token_t) for r in done)

    def pct(arr, q):
        return round(float(np.percentile(arr, q)), 3) if arr.size \
            else 0.0

    good = sum(r.meets(slo) for r in done)
    by_priority = None
    classes = sorted({r.priority for r in records})
    if len(classes) > 1:
        by_priority = {}
        for p in classes:
            sub = [r for r in records if r.priority == p]
            rep = summarize(sub, slo, wall_s, offered_qps=None,
                            mode=mode)
            rep.pop("by_priority", None)
            rep.pop("by_adapter", None)
            by_priority[str(p)] = rep
    # per-adapter sub-reports (ISSUE 18 satellite): only when the
    # workload actually mixed tenants; base-model requests key "base"
    by_adapter = None
    tenants = {r.adapter for r in records}
    if (tenants - {None}) and len(tenants) > 1:
        by_adapter = {}
        for a in sorted(tenants, key=lambda a: (a is None, a)):
            sub = [r for r in records if r.adapter == a]
            rep = summarize(sub, slo, wall_s, offered_qps=None,
                            mode=mode)
            rep.pop("by_priority", None)
            rep.pop("by_adapter", None)
            by_adapter["base" if a is None else str(a)] = rep
    return {
        "mode": mode,
        "requests": len(records),
        "completed": len(done),
        "goodput": round(good / len(records), 4) if records else 0.0,
        "slo": {"ttft_ms": slo.ttft_ms, "itl_ms": slo.itl_ms},
        "offered_qps": None if offered_qps is None
        else round(offered_qps, 3),
        "achieved_qps": round(len(done) / wall_s, 3)
        if wall_s > 0 else 0.0,
        "tokens_per_sec": round(n_tokens / wall_s, 1)
        if wall_s > 0 else 0.0,
        "ttft_p50_ms": pct(ttfts, 50), "ttft_p99_ms": pct(ttfts, 99),
        "itl_p50_ms": pct(itls, 50), "itl_p99_ms": pct(itls, 99),
        "tpot_p50_ms": pct(tpots, 50), "tpot_p99_ms": pct(tpots, 99),
        "e2e_p50_ms": pct(e2es, 50), "e2e_p99_ms": pct(e2es, 99),
        "wall_s": round(wall_s, 3),
        **({"by_priority": by_priority}
           if by_priority is not None else {}),
        **({"by_adapter": by_adapter}
           if by_adapter is not None else {}),
    }
