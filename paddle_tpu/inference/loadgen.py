"""Closed-loop SLO / goodput load generator for ``ServingEngine``.

The harness every serving feature proves itself against (ROADMAP "an
async serving front door ... closed-loop load-generator measuring
goodput under SLO"): drive an engine under a TIMED arrival process and
measure what a client would see —

- **open loop** (the default): requests arrive on a fixed schedule at a
  target QPS (seeded-Poisson or uniform gaps) whether or not the engine
  keeps up — the regime where queueing delay and tail latency actually
  appear (a closed loop self-throttles and can never overload the
  engine, which is exactly what hides SLO violations).
- **closed loop**: a fixed number of in-flight requests, each replaced
  on completion — measures capacity (max sustainable throughput), used
  here to calibrate the open-loop offered load.

Per-request metrics are CLIENT-side (wall-clock around ``submit()`` and
the streaming callback): TTFT = submit -> first streamed token, ITL =
gaps between consecutive streamed tokens, TPOT = mean ITL, e2e =
submit -> last token. A request **meets SLO** when ``ttft <=
slo.ttft_ms`` AND ``tpot <= slo.itl_ms``; **goodput** is the fraction
of SUBMITTED requests meeting SLO — a request that never completes
counts against it (the throughput the fleet can charge for, vs the
tok/s it merely emits). The engine's own always-on P²
digests measure the same quantities server-side; the two agree to
within digest error plus callback overhead.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["SLO", "RequestRecord", "poisson_arrivals",
           "uniform_arrivals", "run_load", "summarize"]


@dataclass
class SLO:
    """Per-request latency budget: a request is 'good' when its TTFT
    and its mean inter-token latency (TPOT) both fit."""
    ttft_ms: float = 1000.0
    itl_ms: float = 200.0


@dataclass
class RequestRecord:
    """Client-side timeline of one request (monotonic seconds)."""
    rid: int
    arrival_s: float                    # scheduled arrival offset
    submit_t: float                     # actual submit() wall time
    token_t: List[float] = field(default_factory=list)

    @property
    def completed(self) -> bool:
        return bool(self.token_t)

    @property
    def ttft_ms(self) -> float:
        return 1000.0 * (self.token_t[0] - self.submit_t)

    @property
    def itl_ms(self) -> List[float]:
        return [1000.0 * (b - a)
                for a, b in zip(self.token_t, self.token_t[1:])]

    @property
    def tpot_ms(self) -> float:
        """Mean time-per-output-token after the first."""
        gaps = self.itl_ms
        return float(np.mean(gaps)) if gaps else 0.0

    @property
    def e2e_ms(self) -> float:
        return 1000.0 * (self.token_t[-1] - self.submit_t)

    def meets(self, slo: SLO) -> bool:
        return self.completed and self.ttft_ms <= slo.ttft_ms \
            and self.tpot_ms <= slo.itl_ms


def poisson_arrivals(n: int, qps: float, seed: int = 0) -> np.ndarray:
    """Open-loop Poisson arrival offsets (seconds from start): i.i.d.
    exponential gaps at rate ``qps`` — the memoryless process real
    front-door traffic approximates."""
    rng = np.random.RandomState(seed)
    return np.cumsum(rng.exponential(1.0 / float(qps), size=n))


def uniform_arrivals(n: int, qps: float) -> np.ndarray:
    """Deterministic fixed-gap arrivals at ``qps`` (no burstiness —
    the lower bound on queueing delay at a given offered load)."""
    return (1.0 + np.arange(n)) / float(qps)


def run_load(engine, prompts: Sequence[np.ndarray], *,
             qps: Optional[float] = None, mode: str = "open",
             concurrency: Optional[int] = None,
             max_new_tokens: Optional[int] = None,
             slo: Optional[SLO] = None, arrival: str = "poisson",
             seed: int = 0) -> dict:
    """Serve ``prompts`` through ``engine`` under a timed arrival
    process and return the goodput report (:func:`summarize`).

    ``mode="open"`` (requires ``qps``): requests are submitted when
    their scheduled arrival time passes, independent of engine
    progress. ``mode="closed"`` (``concurrency``, default
    ``num_slots``): a fixed number in flight, each completion admits
    the next — reported ``achieved_qps`` is then the engine's capacity
    at that concurrency.

    The engine's ``stream_callback`` is chained, not replaced: an
    application callback installed at construction still fires.
    """
    if mode not in ("open", "closed"):
        raise ValueError(f"mode must be open|closed, got {mode!r}")
    if mode == "open" and not qps:
        raise ValueError("open-loop mode needs a target qps")
    slo = slo or SLO()
    n = len(prompts)
    records: Dict[int, RequestRecord] = {}

    prev_cb = engine._stream

    def _record(rid, tok):
        rec = records.get(rid)
        if rec is not None:
            rec.token_t.append(time.monotonic())
        if prev_cb is not None:
            prev_cb(rid, tok)

    if mode == "open":
        offsets = poisson_arrivals(n, qps, seed) \
            if arrival == "poisson" else uniform_arrivals(n, qps)
    else:
        offsets = np.zeros(n)
        concurrency = int(concurrency
                          or engine.config.num_slots)

    engine._stream = _record
    t_start = time.monotonic()
    try:
        idx = 0
        while idx < n or engine.num_queued or engine.num_active:
            now = time.monotonic() - t_start
            if mode == "open":
                while idx < n and offsets[idx] <= now:
                    rid = engine.submit(prompts[idx], max_new_tokens)
                    records[rid] = RequestRecord(
                        rid, float(offsets[idx]), time.monotonic())
                    idx += 1
            else:
                while idx < n and (engine.num_queued
                                   + engine.num_active) < concurrency:
                    rid = engine.submit(prompts[idx], max_new_tokens)
                    records[rid] = RequestRecord(
                        rid, now, time.monotonic())
                    idx += 1
            if engine.num_queued or engine.num_active:
                engine.step()
            elif idx < n:
                # idle until the next scheduled arrival (open loop)
                time.sleep(min(max(offsets[idx] - (
                    time.monotonic() - t_start), 0.0), 0.01))
        wall = time.monotonic() - t_start
    finally:
        engine._stream = prev_cb

    offered = float(qps) if mode == "open" else \
        (n / wall if wall > 0 else 0.0)
    return summarize(list(records.values()), slo, wall,
                     offered_qps=offered, mode=mode)


def summarize(records: List[RequestRecord], slo: SLO, wall_s: float,
              offered_qps: Optional[float] = None,
              mode: str = "open") -> dict:
    """Aggregate client-side records into the goodput report."""
    done = [r for r in records if r.completed]
    ttfts = np.asarray([r.ttft_ms for r in done]) \
        if done else np.zeros(0)
    itls = np.asarray([g for r in done for g in r.itl_ms])
    tpots = np.asarray([r.tpot_ms for r in done]) \
        if done else np.zeros(0)
    e2es = np.asarray([r.e2e_ms for r in done]) \
        if done else np.zeros(0)
    n_tokens = sum(len(r.token_t) for r in done)

    def pct(arr, q):
        return round(float(np.percentile(arr, q)), 3) if arr.size \
            else 0.0

    good = sum(r.meets(slo) for r in done)
    return {
        "mode": mode,
        "requests": len(records),
        "completed": len(done),
        "goodput": round(good / len(records), 4) if records else 0.0,
        "slo": {"ttft_ms": slo.ttft_ms, "itl_ms": slo.itl_ms},
        "offered_qps": None if offered_qps is None
        else round(offered_qps, 3),
        "achieved_qps": round(len(done) / wall_s, 3)
        if wall_s > 0 else 0.0,
        "tokens_per_sec": round(n_tokens / wall_s, 1)
        if wall_s > 0 else 0.0,
        "ttft_p50_ms": pct(ttfts, 50), "ttft_p99_ms": pct(ttfts, 99),
        "itl_p50_ms": pct(itls, 50), "itl_p99_ms": pct(itls, 99),
        "tpot_p50_ms": pct(tpots, 50), "tpot_p99_ms": pct(tpots, 99),
        "e2e_p50_ms": pct(e2es, 50), "e2e_p99_ms": pct(e2es, 99),
        "wall_s": round(wall_s, 3),
    }
