"""Data-parallel engine replication: N ``ServingEngine`` replicas
behind a session-affine router, with optional disaggregated
prefill -> decode KV streaming.

TP (``ServingConfig(tp_degree=N)``) scales ONE engine across an ``mp``
mesh group; this is the layer above it — the ROADMAP's "millions of
users" unlock: aggregate capacity past a single replica, TTFT isolated
from decode ticks, and a fault domain smaller than the fleet. Per
GSPMD (arxiv 2105.04663) the mesh side is solved; the router/affinity/
role-split layer here is host-side scheduling over engines that never
talk to each other's devices except through the block-transfer ops.

- **Session-affine routing.** ``submit()`` hashes the prompt's FULL
  blocks with the same chain-hash walk engine admission uses
  (``ops/paged_cache.prompt_block_hashes`` — factored out so router
  and engine can NEVER hash differently) and scores every live
  candidate replica by published-prefix overlap: the longest cached
  run wins, because that replica already holds the session's KV blocks
  and will prefill only the suffix. Ties (cold prompts included) break
  on queue depth (queued + active, the PR 2/11 telemetry), then on
  replica index — so multi-turn conversations stick to "their" replica
  while cold traffic load-balances. An overlap > 0 route counts as a
  ``serving_router_affinity_hits`` event; per-candidate depths land in
  the ``serving_router_queue_depth{replica=}`` gauge each route.
- **Disaggregated prefill -> decode** (``ClusterConfig(
  prefill_replicas=K)``): K role="prefill" engines run admission +
  chunked prefill ONLY (reserving only the prompt's blocks, so the
  prefill tier admits aggressively), then stream each finished
  prompt's KV blocks into a decode replica's pool —
  ``pop_prefilled()`` exports the blocks (one fixed-width gather
  executable; int8 pools travel as data + per-row scales, so a
  block's bytes are self-contained) and ``admit_prefilled()`` imports
  them (one fixed-width scatter, null-block padding, zero steady-state
  recompiles on either side). The decode replica seats the request at
  exactly the state a colocated engine holds after its own prefill,
  so greedy output is token-exact vs colocated by construction. The
  win is ISOLATION: decode ticks never share a launch with prefill
  rows (long prompts stop inflating every running request's ITL), and
  prefill chunks never wait behind decode batches (TTFT under
  concurrent long-prefill load). Routing in this mode targets the
  prefill tier (that is where the prefix caches fill — a handoff
  publishes the prompt's blocks before freeing them, so the session's
  next turn hits the same prefill engine's index).
- **Failure domain.** A replica whose ``step()`` raises (or an
  administrative ``fail_replica(i)``) drains its admission queue back
  through the router onto the surviving replicas — global request ids
  are preserved, the re-routed requests just prefill again elsewhere.
  In-flight slots on the failed replica terminate with the tokens
  already streamed (partial results, surfaced through ``run()``
  normally). A fully-failed prefill tier falls back to the decode
  replicas serving end-to-end (they are full engines); a fully-failed
  DECODE tier is fatal for new work (prefill engines cannot decode:
  new submits raise, in-flight requests terminate with what
  streamed). The cluster raises on submit only when no replica that
  could serve the request survives.
- **Kill switch** ``PADDLE_TPU_CLUSTER=0``: the cluster collapses to
  ONE colocated replica (``num_replicas=1, prefill_replicas=0``)
  regardless of config — the single engine underneath is bit-for-bit
  a plain ``ServingEngine`` (same executables, same outputs), the
  router degenerates to the identity, and no transfer executable is
  ever built. Rollback is one env var, like every switch in this
  repo.

Every replica is a full ``ServingEngine`` — prefix cache, COW,
speculative n-gram decoding, ragged batching, int8 pools and TP all
compose per replica unchanged (host state stays per-engine: one
allocator, one scheduler, one prefix index each). Greedy cluster
output is token-exact vs a single engine for every request (replicas
never interact mid-request), which is what makes N replicas a pure
capacity knob.

Telemetry: ``serving_router_affinity_hits`` /
``serving_router_queue_depth{replica=}`` here,
``serving_kv_blocks_transferred`` at the engine import site;
``stats()`` returns per-replica dicts plus rolled-up client-side
``ttft_ms`` / ``itl_ms`` / ``e2e_ms`` digests (P², observed at the
cluster's own stream callback — the view a client of the WHOLE
cluster sees, handoff gaps included) and the goodput-harness keys
(``tokens_total``, ``requests_completed``, queue/active depths).
See docs/OPS.md "Engine replication & disaggregated prefill".
"""
from __future__ import annotations

import json
import os
import re
import time
import warnings
from dataclasses import dataclass, replace as _dc_replace
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import monitor
from ..monitor import health as _health
from ..monitor import tracing as _tracing
from ..monitor.digest import LatencyDigest
from ..ops import paged_cache as _pc
from .autoscale import (AutoscaleConfig, AutoscalePolicy,
                        autoscale_enabled)
from .serving import (MigratedSession, PrefilledRequest,
                      QueueShedError, ServingConfig, ServingEngine)

__all__ = ["ClusterConfig", "Router", "EngineCluster"]


def cluster_enabled() -> bool:
    """False under the ``PADDLE_TPU_CLUSTER=0`` kill switch — the
    cluster then runs ONE colocated replica (a plain engine behind the
    cluster API), never N, never disaggregated."""
    return os.environ.get("PADDLE_TPU_CLUSTER", "1") != "0"


@dataclass
class ClusterConfig:
    # decode-capable replicas (role="both" colocated, role="decode"
    # when a prefill tier exists). Aggregate slot capacity is
    # num_replicas * ServingConfig.num_slots.
    num_replicas: int = 2
    # > 0: disaggregated mode — this many role="prefill" engines run
    # admission + chunked prefill only and stream finished KV blocks
    # into the decode replicas' pools (export_blocks/import_blocks).
    prefill_replicas: int = 0
    # elastic fleet (ISSUE 19): an AutoscaleConfig arms the control
    # loop — each cluster tick the policy reads queue depth /
    # occupancy / SLO burn / roofline busy-ness and drives scale_up()
    # / scale_down() (live-migrating drains) within its replica
    # bounds. None (default) = fixed-N fleet; the
    # PADDLE_TPU_AUTOSCALE=0 kill switch beats an explicit config.
    autoscale: Optional[AutoscaleConfig] = None

    def __post_init__(self):
        n = self.num_replicas
        if not isinstance(n, int) or isinstance(n, bool) or n < 1:
            raise ValueError(
                f"num_replicas must be a positive int, got {n!r}")
        k = self.prefill_replicas
        if not isinstance(k, int) or isinstance(k, bool) or k < 0:
            raise ValueError(
                f"prefill_replicas must be an int >= 0, got {k!r}")


class _MemoHashes:
    """Re-iterable memoizing view over the lazy ``prompt_block_hashes``
    walk: each replica probe re-iterates from the start, but a hash is
    computed at most once — so N cold replicas cost ONE block hash
    total (every probe stops at hash[0]), and the whole route costs
    ``max(overlap) + 1`` hashes, never O(prompt)."""

    __slots__ = ("_it", "_memo", "_done")

    def __init__(self, it):
        self._it = it
        self._memo = []
        self._done = False

    def __iter__(self):
        i = 0
        while True:
            if i == len(self._memo) and not self._done:
                try:
                    self._memo.append(next(self._it))
                except StopIteration:
                    self._done = True
            if i >= len(self._memo):
                return
            yield self._memo[i]
            i += 1


class Router:
    """Session-affine replica scoring. ``route()`` hashes the prompt
    with ``prompt_block_hashes`` — the exact walk engine admission
    runs, so a router hit IS an admission hit — lazily and memoized
    across the per-replica probes (a cache-cold fleet hashes ONE
    block, not the prompt), and asks every candidate engine for its
    published-prefix overlap; the longest cached run wins, ties break
    on queue depth then index. Pure scoring — metrics/bookkeeping
    live on the cluster."""

    def __init__(self, fingerprint: bytes, block_size: int):
        self._fp = bytes(fingerprint)
        self._bs = int(block_size)

    def route(self, prompt,
              engines: Dict[int, ServingEngine],
              priority: int = 0, adapter_id: Optional[int] = None
              ) -> Tuple[int, int, Dict[int, int]]:
        """Pick a replica for ``prompt`` among ``engines`` (index ->
        engine). Returns ``(index, overlap_blocks, depths)`` where
        ``depths`` is every candidate's queue depth at scoring time —
        PRIORITY-WEIGHTED when the replicas run the preemptive
        scheduler: work below ``priority`` is discounted (it can be
        preempted or bypassed, so it barely delays this arrival),
        which steers high-priority traffic toward replicas whose load
        is preemptible rather than merely toward short queues.
        ``adapter_id`` adds ADAPTER affinity below prefix affinity:
        among equal prefix overlaps, a replica whose device stacks
        already hold the adapter wins (seating there skips an LRU
        swap) — prefix overlap still dominates, because re-prefilling
        a lost prefix costs more than one adapter row upload."""
        if not engines:
            raise ValueError("route() needs at least one candidate")
        ids = np.asarray(prompt, np.int32).reshape(-1)
        hashes = _MemoHashes(
            _pc.prompt_block_hashes(self._fp, ids, self._bs))
        best = None
        depths = {}
        for idx, eng in engines.items():
            ov = eng.published_overlap(hashes)
            res = 0
            if adapter_id is not None:
                res = int(eng.adapter_resident(adapter_id))
            depth = eng.queue_depth(priority)
            depths[idx] = depth
            # longest run, then adapter-resident, then least loaded,
            # then lowest index
            key = (ov, res, -depth, -idx)
            if best is None or key > best[0]:
                best = (key, idx, ov)
        return best[1], best[2], depths


class EngineCluster:
    """N serving-engine replicas behind a session-affine router (+
    optional disaggregated prefill tier). The public surface mirrors
    ``ServingEngine`` — ``submit`` / ``step`` / ``run`` / ``serve`` /
    ``cancel`` / ``stats`` / ``shutdown`` / ``num_active`` /
    ``num_queued`` — so the loadgen harness, benches and applications
    drive either interchangeably. Request ids are CLUSTER-global;
    tokens stream through ``stream_callback(rid, tok)`` exactly like
    the engine's.

    Usage::

        cluster = EngineCluster(model, ClusterConfig(num_replicas=2),
                                ServingConfig(num_slots=8))
        rid = cluster.submit(prompt)
        results = cluster.run()         # {rid: np.ndarray of tokens}
    """

    def __init__(self, model, config: Optional[ClusterConfig] = None,
                 serving_config: Optional[ServingConfig] = None,
                 stream_callback: Optional[Callable] = None,
                 draft_model=None, spec_heads=None):
        ccfg = config or ClusterConfig()
        scfg = serving_config or ServingConfig()
        if not cluster_enabled():       # PADDLE_TPU_CLUSTER=0
            ccfg = ClusterConfig(num_replicas=1, prefill_replicas=0)
        self.config = ccfg
        self.serving_config = scfg
        self._disagg = ccfg.prefill_replicas > 0
        if self._disagg and draft_model is not None:
            # the SEPARATE-model case only: head-drafted tree
            # speculation (drafter="heads" + spec_tree) serves
            # disaggregated fine — the draft heads ride the target
            # params on every replica and re-draft from the imported
            # target pool, so nothing extra travels in the handoff
            raise NotImplementedError(
                "disaggregated mode cannot serve a SEPARATE draft "
                "model: the draft pool's prompt K/V is not part of "
                "the prefill->decode transfer payload (the target "
                "pool is) — use n-gram speculation, draft-head tree "
                "speculation (drafter='heads' + spec_tree), or "
                "colocated replicas")
        self._stream = stream_callback
        self._engines: List[ServingEngine] = []
        self._decode_idx: List[int] = []
        self._prefill_idx: List[int] = []
        # scale_up() spawns replicas from the SAME shared config, so
        # the construction inputs are kept (weights are shared jax
        # arrays — a new replica costs executables + pools, not a
        # second copy of the model)
        self._model = model
        self._draft_model = draft_model
        self._spec_heads = spec_heads
        decode_role = "decode" if self._disagg else "both"
        dkw = {"role": decode_role, "retain_results": True}
        # retain_results forced on: a replica's _done dict is the
        # cluster's completion signal (popped every tick, so a
        # long-lived cluster still never accumulates results)
        if self._disagg and scfg.ragged_prefill_rows is None:
            # a disaggregated decode replica never chunk-prefills (all
            # its admissions arrive via admit_prefilled), so the
            # default one-chunk prefill row budget would ride every
            # ragged launch as DEAD static width — shrink it to the
            # minimum unless the caller pinned a value
            dkw["ragged_prefill_rows"] = 1
        self._dkw = dict(dkw)
        for _ in range(ccfg.num_replicas):
            idx = len(self._engines)
            self._engines.append(ServingEngine(
                model, _dc_replace(scfg, **dkw),
                stream_callback=self._make_cb(idx),
                draft_model=draft_model, spec_heads=spec_heads))
            self._decode_idx.append(idx)
        for _ in range(ccfg.prefill_replicas):
            idx = len(self._engines)
            # speculation is a decode feature: the prefill tier runs
            # gamma=0 (n-gram spec composes on the decode replicas —
            # its history is the prompt + first token, both in the
            # handoff), and the transfer width is gamma-independent
            # (_mb_xfer) so the payloads still shape-match
            # speculation (linear OR tree) is a decode feature, so the
            # prefill tier also drops spec_tree and the heads drafter
            # alongside gamma — a decode replica's head re-draft needs
            # only the imported target pool + handoff history
            self._engines.append(ServingEngine(
                model, _dc_replace(scfg, role="prefill",
                                   retain_results=True,
                                   num_speculative_tokens=0,
                                   spec_tree=None,
                                   drafter="ngram"),
                stream_callback=self._make_cb(idx)))
            self._prefill_idx.append(idx)
        self._router = Router(_pc.model_fingerprint(model),
                              int(scfg.block_size))
        self._next_rid = 0              # cluster-global request ids
        self._l2g: Dict[tuple, int] = {}    # (engine, local) -> global
        self._owner: Dict[int, tuple] = {}  # global -> (engine, local)
        self._tokens: Dict[int, list] = {}
        # per-request sampling overrides, kept so a failure-drain
        # requeue re-submits with the SAME knobs
        self._req_samp: Dict[int, dict] = {}
        self._done: Dict[int, np.ndarray] = {}
        # handoffs exported from a prefill engine, waiting for decode
        # capacity: (src_engine_idx, PrefilledRequest)
        self._pending: List[Tuple[int, PrefilledRequest]] = []
        self._failed = set()
        # -- elastic fleet (ISSUE 19) ---------------------------------
        # replicas retired by scale_down(): drained empty (every
        # session live-migrated out), removed from their tier index so
        # the router/placement never see them, kept in _engines so
        # trace export and index stability survive — and so scale_up()
        # can REVIVE one with its executables already compiled (a
        # scale cycle compiles nothing in steady state)
        self._removed = set()
        # live sessions in transit: (global_rid, MigratedSession) —
        # placed onto the coldest live decode replica each tick
        self._pending_mig: List[Tuple[int, MigratedSession]] = []
        # adapter registry replay for replicas spawned/revived AFTER a
        # load_adapter broadcast (weights are shared refs, not copies)
        self._adapter_reg: Dict[int, object] = {}
        self._n_scale_ups = 0
        self._n_scale_downs = 0
        self._n_migrated = 0            # sessions live-migrated
        self._n_replica_ticks = 0       # sum over ticks of live
        #                                 replicas (the autoscale
        #                                 bench's capacity denominator)
        self._d_migration = LatencyDigest()      # export->seated ms
        self._m_replicas = monitor.gauge(
            "serving_replicas_live",
            "live replicas (decode + prefill tiers) in the cluster "
            "right now — scale_up/scale_down/fail_replica move it")
        self._m_migrated = monitor.counter(
            "serving_sessions_migrated",
            "live sessions moved between replicas with their KV "
            "(scale-down drains + rebalancing), token-exact and "
            "invisible to the client")
        self._m_replicas.set(len(self._decode_idx)
                             + len(self._prefill_idx))
        self._autoscale: Optional[AutoscalePolicy] = None
        if ccfg.autoscale is not None and autoscale_enabled():
            self._autoscale = AutoscalePolicy(ccfg.autoscale)
        # mean prompt length EMA — the prompt-mix signal the policy's
        # prefill:decode retune consumes (and dashboards plot)
        self._prompt_len_ema = 0.0
        self._tick_buf: List[tuple] = []
        self._n_routed = 0
        self._n_affinity = 0
        self._n_completed = 0
        # client-side rolled-up latency digests: observed at THE
        # cluster's own stream boundary, so a disaggregated handoff's
        # gap lands in the ITL digest like a client would see it
        self._submit_t: Dict[int, float] = {}
        self._last_emit: Dict[int, float] = {}
        self._d_ttft = LatencyDigest()
        self._d_itl = LatencyDigest()
        self._d_e2e = LatencyDigest()
        # -- fleet flight recorder (ISSUE 15) -------------------------
        # the cluster's OWN trace lane (router decisions, handoff
        # placements, cluster ticks) plus a (engine, local rid) ->
        # global rid history: the live _l2g map pops entries on
        # completion, but export_trace() must rewrite EVERY buffered
        # span — including retired requests' — to the cluster-global
        # id namespace. The history is populated only while tracing
        # (under PADDLE_TPU_TRACE=0 it would be dead weight) and is
        # FIFO-bounded: each ring holds at most `capacity` events, so
        # rids older than every ring's reach can never need rewriting
        # — one cap'd dict, not unbounded growth on a long-lived
        # fleet.
        self._l2g_hist: Dict[tuple, int] = {}
        self._trace = None
        if _tracing.tracing_enabled():
            tr = _tracing.Tracer("EngineCluster")
            tr.set_thread(0, "router")
            self._trace = tr
        self._hist_cap = (len(self._engines) + 1) \
            * _tracing.trace_buffer_capacity()
        # one bounded jax.profiler window around the next N CLUSTER
        # ticks (each replica's work runs inside the cluster tick, so
        # one process-wide capture covers the fleet)
        self._prof = _tracing.ProfilerWindow()
        self._m_affinity = monitor.counter(
            "serving_router_affinity_hits",
            "requests the cluster router placed on a replica already "
            "holding >= 1 of the prompt's prefix blocks (session "
            "affinity working)")
        self._m_depth = monitor.gauge(
            "serving_router_queue_depth",
            "per-replica queued + active depth at the router's last "
            "scoring pass", labels=("replica",))
        # -- fleet health engine (ISSUE 17) ---------------------------
        # the cluster's own watchdog sweep + incident sink: a replica
        # whose tick blows its deadline feeds the existing
        # fail_replica drain, and the cluster-level incident bundle
        # (merged trace, full fleet stats) captures the scene first.
        # Off exactly when the replicas' health engines are off.
        self._health_on = self._engines[0]._health is not None
        self._incident = (_health.IncidentCapture()
                          if self._health_on else None)

    # -- public API ---------------------------------------------------

    @property
    def engines(self) -> List[ServingEngine]:
        """All replicas, decode tier first (read-only introspection —
        tests, benches, dashboards)."""
        return list(self._engines)

    @property
    def num_active(self) -> int:
        return sum(self._engines[i].num_active
                   for i in self._live()) \
            + len(self._pending) + len(self._pending_mig)

    @property
    def num_queued(self) -> int:
        return sum(self._engines[i].num_queued for i in self._live())

    @property
    def num_slots(self) -> int:
        """Aggregate DECODE slot capacity (the loadgen closed-loop
        concurrency default)."""
        return sum(self._engines[i].config.num_slots
                   for i in self._decode_idx if i not in self._failed)

    def submit(self, prompt, max_new_tokens=None, temperature=None,
               top_k=None, top_p=None, priority=0,
               max_queue_wait_ms=None, adapter_id=None) -> int:
        """Route one request to a replica (prefill tier when
        disaggregated) and queue it there; returns the CLUSTER-global
        request id tokens stream under.
        ``temperature``/``top_k``/``top_p`` are this request's
        sampling overrides, forwarded to the owning replica's per-slot
        sampling tensors (and preserved across a failure-drain
        requeue; in disaggregated mode they travel with the KV handoff
        payload to the decode replica). ``priority`` is the request's
        scheduling class — it weights the router's queue-depth
        tiebreak, orders admission on the owning replica, may preempt
        strictly-lower work there, rides the disaggregated handoff,
        and survives a failure-drain requeue. ``max_queue_wait_ms``
        bounds the replica-side queue wait (outcome="timeout").
        ``adapter_id`` serves the request under a LoRA adapter
        registered via :meth:`load_adapter` — it weights the router's
        tiebreak toward replicas already holding the adapter
        resident, rides the disaggregated KV handoff (the prefill
        tier computes the prompt's KV under the adapter), and
        survives a failure-drain requeue like the sampling knobs."""
        ids = np.asarray(prompt, np.int32).reshape(-1)
        # prompt-length-mix EMA: the autoscaler's prefill:decode
        # retune signal (longer prompts shift pressure prefill-ward)
        n = float(ids.size)
        self._prompt_len_ema = (
            n if self._prompt_len_ema == 0.0
            else 0.9 * self._prompt_len_ema + 0.1 * n)
        if self._disagg:
            # mirror engine.submit()'s pool-fit rejection for the
            # DECODE side: the prefill tier reserves only prompt
            # blocks, so without this check a request whose decode
            # reservation can never fit any decode pool would prefill,
            # export, and then sit as a forever-pending handoff
            # (run() would never drain)
            live = [i for i in self._decode_idx
                    if i not in self._failed]
            if not live:
                raise RuntimeError(
                    "all decode replicas failed: a disaggregated "
                    "cluster's prefill tier cannot decode, so new "
                    "requests cannot be served (in-flight ones "
                    "terminate with the tokens already streamed)")
            de = self._engines[live[0]]
            max_new = int(de.config.max_new_tokens
                          if max_new_tokens is None
                          else max_new_tokens)
            worst = de._worst_for(ids.size, max_new)
            cap = max(self._engines[i]._alloc.num_blocks - 1
                      for i in live)
            if worst > cap:
                raise ValueError(
                    f"request needs {worst} blocks on a decode "
                    f"replica; the largest live decode pool has "
                    f"only {cap}")
        rid = self._next_rid
        samp = {k: v for k, v in (("temperature", temperature),
                                  ("top_k", top_k), ("top_p", top_p),
                                  ("max_queue_wait_ms",
                                   max_queue_wait_ms),
                                  ("adapter_id", adapter_id))
                if v is not None}
        if int(priority):
            samp["priority"] = int(priority)
        self._route_submit(rid, ids, max_new_tokens, samp)
        self._next_rid += 1
        if samp:
            self._req_samp[rid] = samp
        self._tokens[rid] = []
        self._submit_t[rid] = time.monotonic()
        return rid

    def load_adapter(self, adapter_id, weights) -> int:
        """Register LoRA adapter ``adapter_id`` on EVERY live replica
        (prefill tier included — disaggregated prompts must prefill
        under the adapter's deltas). Broadcasting the registry is what
        makes the router's adapter-affinity a soft optimization: any
        replica can serve any tenant, residency just decides who does
        it without an LRU swap."""
        aid = None
        for i in self._live():
            aid = self._engines[i].load_adapter(adapter_id, weights)
        if aid is None:
            raise RuntimeError(
                "no live replicas to register the adapter on")
        # registry replay source: a replica spawned or revived AFTER
        # this broadcast re-registers from here (shared array refs,
        # not copies) so migrated adapter sessions land anywhere
        self._adapter_reg[int(aid)] = weights
        return aid

    def cancel(self, request_id: int) -> bool:
        """Cancel a request anywhere in its cluster lifetime: queued
        or IN FLIGHT on its replica (forwarded to
        ``ServingEngine.cancel``, which retires the slot mid-decode
        and frees its blocks), or parked as a pending disaggregated
        handoff (the payload is dropped — its prefill-engine blocks
        were already freed at export). A request that already
        streamed tokens surfaces them as a partial result through
        ``run()``."""
        owner = self._owner.get(request_id)
        if owner is None:
            # an in-transit migration? (exported, not yet re-seated —
            # owner_of() is None for exactly that window)
            for k, (g, _rec) in enumerate(self._pending_mig):
                if g == request_id:
                    del self._pending_mig[k]
                    # a migrated session has streamed by definition:
                    # surface the partial tokens like an in-flight
                    # cancel would
                    self._finish(g)
                    return True
            return False
        idx, lrid = owner
        streamed = bool(self._tokens.get(request_id))
        if not self._engines[idx].cancel(lrid):
            # not queued / not in a slot there: a pending handoff?
            for k, (src, rec) in enumerate(self._pending):
                if (src, rec.request_id) == (idx, lrid):
                    del self._pending[k]
                    break
            else:
                return False
        # the replica may have parked a partial result under the local
        # rid — drop it; the cluster's own stream records are the
        # client-facing result
        self._engines[idx]._done.pop(lrid, None)
        self._l2g.pop((idx, lrid), None)
        self._owner.pop(request_id, None)
        self._req_samp.pop(request_id, None)
        if streamed:
            self._finish(request_id)        # partial tokens + e2e obs
        else:
            self._tokens.pop(request_id, None)
            self._submit_t.pop(request_id, None)
            self._last_emit.pop(request_id, None)
        return True

    def step(self) -> List[tuple]:
        """One cluster tick: advance every prefill engine and stream
        its finished prompts' KV blocks into decode replicas, then
        advance every decode replica. Returns this tick's
        ``[(request_id, token), ...]`` across the whole cluster. An
        armed profiling window (``profile(n_ticks)``) brackets the
        whole cluster tick."""
        with self._prof.tick():
            return self._step_impl()

    def _step_impl(self) -> List[tuple]:
        t0 = time.monotonic()
        self._tick_buf = []
        # capacity denominator for goodput-per-replica-tick: one unit
        # per LIVE replica per cluster tick (the autoscale bench's
        # "what did this capacity cost" axis)
        self._n_replica_ticks += sum(
            1 for i in self._decode_idx + self._prefill_idx
            if i not in self._failed)
        for i in list(self._prefill_idx):
            if i in self._failed:
                continue
            eng = self._engines[i]
            if eng.num_queued or eng.num_active:
                self._safe_step(i)
            if i not in self._failed:
                for rec in eng.pop_prefilled():
                    self._pending.append((i, rec))
        self._place_handoffs()
        self._place_migrations()
        # decode replicas tick dispatch-all-then-commit-all: every
        # async replica's executable is IN FLIGHT before any replica
        # blocks on its token fetch, so N launches run concurrently
        # instead of serially. A sync replica (async_depth=0 /
        # PADDLE_TPU_ASYNC_TICK=0) runs its whole step inside the
        # dispatch phase and no-ops the commit phase — the loop then
        # degrades to today's serial ticking bit-for-bit.
        stepped = []
        for i in list(self._decode_idx):
            if i in self._failed:
                continue
            eng = self._engines[i]
            if eng.num_queued or eng.num_active:
                self._safe_phase(i, dispatch=True)
                stepped.append(i)
        for i in stepped:
            if i in self._failed:
                continue
            self._safe_phase(i, dispatch=False)
        self._collect_done()
        if self._health_on:
            self._watchdog_sweep()
        if self._autoscale is not None:
            self._autoscale_tick()
        if self._trace is not None:
            self._trace.emit(
                "cluster tick", tid=0, t0=t0,
                args={"pending_handoffs": len(self._pending),
                      "pending_migrations": len(self._pending_mig),
                      "emitted": len(self._tick_buf),
                      "failed": len(self._failed)})
        return self._tick_buf

    def run(self) -> Dict[int, np.ndarray]:
        """Drive ``step()`` until every replica drains; returns (and
        clears) the tokens of every request completed since the last
        ``run()``, keyed by cluster-global request id."""
        while self.num_queued or self.num_active:
            self.step()
        done, self._done = self._done, {}
        return done

    def serve(self, prompts, max_new_tokens=None) -> List[np.ndarray]:
        """Batch convenience: submit all, run to completion, return
        token arrays in submission order."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        done = self.run()
        return [done[r] for r in rids]

    def fail_replica(self, index: int):
        """Administratively fail one replica (also invoked when its
        ``step()`` raises): its admission queue drains back through
        the router onto the surviving replicas — global request ids
        are preserved, the requests simply prefill again elsewhere —
        and its in-flight requests terminate with the tokens already
        streamed (partial results, returned by ``run()`` normally).
        Raises RuntimeError when no replica survives to take the
        drained queue."""
        if index in self._failed:
            return
        self._failed.add(index)
        eng = self._engines[index]
        requeue = []
        for req in list(eng._queue):
            g = self._l2g.pop((index, req.request_id), None)
            eng.cancel(req.request_id)      # terminal queue-wait obs
            if g is None:
                continue
            if req.resume is not None:
                # a PREEMPTED request waiting to resume: its KV lives
                # only on the failed replica (host-tier payload +
                # published blocks), so it cannot continue elsewhere —
                # terminate with the tokens already streamed, like an
                # in-flight slot
                self._finish(g)
                continue
            requeue.append((g, req.prompt, req.max_new_tokens))
        for slot in eng._slots:
            if slot is None:
                continue
            # pop the mapping: the failed engine never emits again
            # (already-exported handoffs are NOT in _slots — their
            # payloads survive and still place into decode replicas)
            g = self._l2g.pop((index, slot.rid), None)
            if g is not None:
                self._finish(g)             # partial result
        for g, prompt, max_new in requeue:
            try:
                self._route_submit(g, prompt, max_new)
            except QueueShedError:
                # a surviving replica shed the drained request: the
                # fault-tolerance path must not crash mid-drain (the
                # remaining requeues' mappings are already popped) —
                # terminate it with whatever streamed, like an
                # in-flight casualty
                warnings.warn(
                    f"request {g} shed during the failure drain; "
                    "terminating with the tokens already streamed")
                self._finish(g)
        # wipe the dead replica's affinity surface: the candidate
        # filter already hides it from the router, but its content
        # index + host-tier published spills would otherwise linger as
        # dead weight for the fleet's lifetime — and any path that
        # ever probes the engine again (diagnostics, a future revival)
        # must see overlap 0, not hashes for KV nobody serves.
        # Best-effort: the replica may be torn down mid-call.
        try:
            eng.purge_published()
        except Exception:       # pragma: no cover - torn down
            pass
        self._set_replica_gauge()

    # -- elastic fleet (ISSUE 19) -------------------------------------

    def scale_up(self, role: str = "decode", warm: bool = True) -> int:
        """Add one replica to ``role``'s tier ("decode" / "prefill")
        from the SAME shared construction inputs (weights are shared
        jax arrays — a replica costs executables + pools, never a
        second model copy) and return its index. A replica previously
        retired by :meth:`scale_down` is REVIVED in preference to
        building a new one: its executables are already compiled, so a
        steady-state scale cycle compiles NOTHING. Fresh or revived,
        the replica replays the cluster's adapter registry (so
        migrated LoRA sessions can land on it) and — with ``warm=True``
        — pre-builds its executables off the request path: the
        migration export/import pair plus one throwaway 1-token
        request driven to completion before the router ever sees the
        replica."""
        if role not in ("decode", "prefill"):
            raise ValueError(f"role must be 'decode' or 'prefill', "
                             f"got {role!r}")
        if role == "prefill" and not self._disagg:
            raise ValueError(
                "cannot scale the prefill tier of a colocated "
                "cluster (prefill_replicas=0)")
        tier = (self._decode_idx if role == "decode"
                else self._prefill_idx)
        want = (("decode" if self._disagg else "both")
                if role == "decode" else "prefill")
        idx = None
        for i in sorted(self._removed):
            if self._engines[i]._role == want:
                idx = i
                break
        revived = idx is not None
        if revived:
            self._removed.discard(idx)
            eng = self._engines[idx]
        else:
            idx = len(self._engines)
            if role == "decode":
                eng = ServingEngine(
                    self._model,
                    _dc_replace(self.serving_config, **self._dkw),
                    stream_callback=self._make_cb(idx),
                    draft_model=self._draft_model,
                    spec_heads=self._spec_heads)
            else:
                # mirror __init__'s prefill-tier construction:
                # speculation is a decode feature
                eng = ServingEngine(
                    self._model,
                    _dc_replace(self.serving_config, role="prefill",
                                retain_results=True,
                                num_speculative_tokens=0,
                                spec_tree=None, drafter="ngram"),
                    stream_callback=self._make_cb(idx))
            self._engines.append(eng)
            self._hist_cap = (len(self._engines) + 1) \
                * _tracing.trace_buffer_capacity()
        for aid, w in self._adapter_reg.items():
            # replay registrations the replica missed (revived
            # replicas keep their registry; known() makes this
            # idempotent either way)
            if eng._lora_pool is not None \
                    and not eng._lora_pool.known(aid):
                eng.load_adapter(aid, w)
        if warm and not revived:
            # a revived replica's executables are already compiled —
            # only a FRESH engine needs the off-path warm pass
            try:
                eng.warm_migration()
            except Exception:   # pragma: no cover - defensive
                warnings.warn(
                    f"replica {idx} failed its migration warm-up; "
                    "the first real transfer will compile inline")
            # one throwaway request end-to-end: prefill + decode (or
            # prefill + export on the prefill tier) executables build
            # NOW, not under the first routed request. A 1-token
            # prompt publishes nothing (cache_len < block_size), so
            # the affinity surface stays clean.
            lrid = eng.submit([1], 1)
            guard = 0
            while (eng.num_queued or eng.num_active) and guard < 64:
                eng.step()
                eng.pop_prefilled()     # prefill role: drop handoff
                guard += 1
            eng._done.pop(lrid, None)
        # joining the tier index LAST: the router and placement loops
        # only ever see a fully-warmed replica
        tier.append(idx)
        self._n_scale_ups += 1
        self._set_replica_gauge()
        if self._trace is not None:
            self._trace.instant(
                "scale up", tid=0,
                args={"replica": idx, "role": role,
                      "revived": revived})
        return idx

    def scale_down(self, index: Optional[int] = None) -> int:
        """Retire one replica with a LIVE-MIGRATING drain: every
        resident session leaves through the compiled export path and
        re-seats on a surviving replica at its exact continuation
        state (cache_len, last token, emit count, sampling row,
        priority, adapter pin) — clients just see their streams
        continue; greedy output is token-exact vs never-migrated.
        Queued-but-unserved work re-routes as fresh submissions.
        ``index`` defaults to the COLDEST live decode replica. The
        replica leaves its tier index immediately (no new routes, no
        placements), its published-prefix surface is purged (affinity
        follows the migrated KV), and the engine object is KEPT for a
        later :meth:`scale_up` revival — executables stay compiled.
        Raises when ``index`` is the last live decode replica (a
        drain needs somewhere to put the sessions)."""
        if index is None:
            cands = [i for i in self._decode_idx
                     if i not in self._failed]
            if len(cands) < 2:
                raise RuntimeError(
                    "scale_down needs >= 2 live decode replicas "
                    "(the drain live-migrates onto the survivors)")
            index = min(cands, key=lambda j:
                        self._engines[j].num_active
                        + self._engines[j].num_queued)
        if index in self._failed or index in self._removed:
            raise ValueError(
                f"replica {index} is already failed/removed")
        if index in self._decode_idx:
            if sum(1 for i in self._decode_idx
                   if i not in self._failed) < 2:
                raise RuntimeError(
                    "cannot drain the last live decode replica")
            self._decode_idx.remove(index)
        elif index in self._prefill_idx:
            self._prefill_idx.remove(index)
        else:
            raise ValueError(f"no replica {index}")
        self._removed.add(index)
        eng = self._engines[index]
        # already-exported handoffs first: their payloads are
        # self-contained, they just place like any pending handoff
        for rec in eng.pop_prefilled():
            self._pending.append((index, rec))
        migrations, fresh = eng.drain_sessions()
        for rec in migrations:
            g = self._l2g.pop((index, rec.request_id), None)
            if g is None:       # cancelled upstream: drop
                continue
            # in transit: owner_of() is None until re-seated
            self._owner.pop(g, None)
            self._pending_mig.append((g, rec))
        for req in fresh:
            g = self._l2g.pop((index, req.request_id), None)
            if g is None:
                continue
            try:
                self._route_submit(g, req.prompt, req.max_new_tokens)
            except QueueShedError:
                warnings.warn(
                    f"request {g} shed during the scale-down drain; "
                    "terminating with the tokens already streamed")
                self._finish(g)
        # affinity follows the KV: the drained replica's index must
        # stop scoring overlaps the survivors now serve
        eng.purge_published()
        self._n_scale_downs += 1
        self._set_replica_gauge()
        if self._trace is not None:
            self._trace.instant(
                "scale down", tid=0,
                args={"replica": index,
                      "migrations": len(migrations),
                      "requeued": len(fresh)})
        self._place_migrations()
        return index

    def rebalance(self, max_moves: int = 1) -> int:
        """Cluster-level load shedding: while the hottest live decode
        replica is >= 2 sessions deeper than the coldest, export its
        best victim (lowest priority class, newest admit — the PR 14
        victim policy, minus the mid-prefill preference since those
        have nothing worth moving) and live-migrate it to the coldest
        replica. Returns the number of sessions moved (bounded by
        ``max_moves``). A no-op below 2 live replicas or under
        balanced load — safe to call every tick."""
        moved = 0
        for _ in range(int(max_moves)):
            live = [i for i in self._decode_idx
                    if i not in self._failed]
            if len(live) < 2:
                break

            def _load(j):
                return (self._engines[j].num_active
                        + self._engines[j].num_queued)

            hot = max(live, key=_load)
            cold = min(live, key=_load)
            if _load(hot) - _load(cold) < 2:
                break
            eng = self._engines[hot]
            cands = [i for i, s in enumerate(eng._slots)
                     if s is not None and not s.handoff
                     and not (s.pend_pos is not None
                              and s.resume is None)]
            if not cands:
                break
            victim = min(cands, key=lambda i: (
                eng._slots[i].priority, -eng._slots[i].admit_t))
            lrid = eng._slots[victim].rid
            g = self._l2g.pop((hot, lrid), None)
            rec = eng.export_session(victim)
            if g is None:       # pragma: no cover - cancelled race
                continue
            self._owner.pop(g, None)
            self._pending_mig.append((g, rec))
            if self._trace is not None:
                self._trace.instant(
                    "rebalance", tid=0,
                    args={"rid": g, "src": hot, "dst_hint": cold})
            moved += 1
        if moved:
            self._place_migrations()
        return moved

    def _place_migrations(self):
        """Re-seat in-transit migrated sessions, coldest live decode
        replica first. A session that finds no capacity this tick
        stays pending (``num_active`` counts it, so ``run()`` keeps
        ticking); a replica that RAISES during admission is treated
        as failed mid-migration — it drains through ``fail_replica``
        and the session retries the next candidate, degrading to the
        recompute path if its payload import died with the target."""
        if not self._pending_mig:
            return
        still = []
        for g, rec in self._pending_mig:
            placed = False
            while not placed:
                live = [i for i in self._decode_idx
                        if i not in self._failed]
                if not live:
                    warnings.warn(
                        "no live decode replica to seat migrated "
                        f"session {g}; terminating with the tokens "
                        "already streamed")
                    self._finish(g)
                    placed = True       # terminal, not re-queued
                    break
                for i in sorted(live, key=lambda j:
                                self._engines[j].num_active
                                + self._engines[j].num_queued):
                    try:
                        lrid = self._engines[i].admit_migrated(rec)
                    except Exception as exc:    # noqa: BLE001
                        warnings.warn(
                            f"replica {i} failed admitting migrated "
                            f"session {g} ({exc!r}); failing it and "
                            "retrying elsewhere")
                        self.fail_replica(i)
                        break       # re-derive the live set
                    if lrid is None:
                        continue    # no capacity there right now
                    self._l2g[(i, lrid)] = g
                    self._owner[g] = (i, lrid)
                    self._hist_put((i, lrid), g)
                    self._d_migration.observe(
                        1000.0 * (time.monotonic() - rec.export_t))
                    self._n_migrated += 1
                    self._m_migrated.inc()
                    if self._trace is not None:
                        self._trace.instant(
                            "migration placed", tid=0,
                            args={"rid": g, "dst": i,
                                  "blocks": rec.n_blocks,
                                  "recompute": rec.payload is None})
                    placed = True
                    break
                else:
                    # every live candidate said "not right now":
                    # park for the next tick
                    still.append((g, rec))
                    placed = True
        self._pending_mig = still

    def _shed_backlog(self, new_idx):
        """After a scale-up, spread the EXISTING backlog: each
        survivor's queued-but-unserved requests beyond the fleet's
        fair share re-route through the router, which places them on
        the emptiest replica — the one that just joined. Without this
        the new capacity only absorbs future arrivals while the burst
        that triggered it keeps queueing on the old replicas.
        Preempted resume-carrying waiters stay put (their KV lives
        where they queued). Colocated tiers only: a disaggregated
        cluster's router queue lives on the prefill tier."""
        live = [i for i in self._decode_idx if i not in self._failed]
        if len(live) < 2:
            return
        total = sum(self._engines[i].num_queued for i in live)
        fair = -(-total // len(live))               # ceil
        for i in live:
            if i == new_idx:
                continue
            eng = self._engines[i]
            extra = eng.num_queued - fair
            if extra <= 0:
                continue
            for req in eng.shed_queued(extra):
                g = self._l2g.pop((i, req.request_id), None)
                if g is None:
                    continue
                try:
                    self._route_submit(g, req.prompt,
                                       req.max_new_tokens)
                except QueueShedError:
                    warnings.warn(
                        f"request {g} shed during the scale-up "
                        "backlog spread; terminating with the tokens "
                        "already streamed")
                    self._finish(g)

    def _autoscale_tick(self):
        """One control-loop step: gather the tick's signals (queue
        depth per slot, occupancy, worst fast SLO burn rate, busiest
        roofline) and execute the policy's decision. At most ONE
        replica changes per tick — decode tier first; the prefill
        ratio retune only runs on decode-hold ticks."""
        pol = self._autoscale
        dec = [i for i in self._decode_idx if i not in self._failed]
        if not dec:
            return
        burn = 0.0
        busy = 0.0
        for i in dec:
            eng = self._engines[i]
            if eng._health is not None:
                burn = max(burn,
                           eng._health.burn_rates().get("fast", 0.0))
            r = eng._roofline()
            busy = max(busy, r["step_mfu"], r["step_hbm_bw_util"])
        sig = {
            "replicas": len(dec),
            "slots": sum(self._engines[i].config.num_slots
                         for i in dec),
            "active": sum(self._engines[i].num_active for i in dec)
            + len(self._pending_mig),
            "queued": sum(self._engines[i].num_queued for i in dec),
            "burn_fast": burn,
            "busy": busy,
            "mean_prompt_len": self._prompt_len_ema,
        }
        d = pol.decide(sig)
        if d == "up":
            try:
                idx = self.scale_up("decode")
            except Exception as exc:    # pragma: no cover - defensive
                warnings.warn(f"autoscale scale_up failed: {exc!r}")
                return
            if not self._disagg:
                self._shed_backlog(idx)
            return
        if d == "down":
            try:
                self.scale_down()
            except RuntimeError:
                pass        # last live decode replica: hold instead
            return
        if not self._disagg:
            return
        pf = [i for i in self._prefill_idx if i not in self._failed]
        sig.update({
            "prefill_replicas": len(pf),
            "prefill_slots": sum(self._engines[i].config.num_slots
                                 for i in pf),
            "prefill_active": sum(self._engines[i].num_active
                                  for i in pf),
            "prefill_queued": sum(self._engines[i].num_queued
                                  for i in pf),
        })
        d = pol.decide_prefill(sig)
        if d == "up":
            try:
                self.scale_up("prefill")
            except Exception as exc:    # pragma: no cover - defensive
                warnings.warn(
                    f"autoscale prefill scale_up failed: {exc!r}")
        elif d == "down" and pf:
            cold = min(pf, key=lambda j:
                       self._engines[j].num_active
                       + self._engines[j].num_queued)
            try:
                self.scale_down(cold)
            except (RuntimeError, ValueError):
                pass

    def _watchdog_sweep(self):
        """Per-tick stuck-replica check: a replica whose watchdog
        trips gets the scene captured (cluster-level incident bundle:
        merged trace + full fleet stats) and is then drained through
        the existing ``fail_replica`` path — a wedged replica degrades
        the fleet instead of freezing it."""
        for i in list(self._live()):
            eng = self._engines[i]
            try:
                stuck = eng.watchdog_stuck()
            except Exception:       # pragma: no cover - defensive
                stuck = True
            if not stuck:
                continue
            warnings.warn(
                f"replica {i} failed its stuck-tick watchdog "
                "deadline; draining it through fail_replica()")
            if self._incident is not None:
                h = eng.health()
                try:
                    self._incident.maybe_capture(
                        "stuck_tick", "page", stats_cb=self.stats,
                        trace_cb=self.export_trace,
                        journal=(h or {}).get("journal", []))
                except Exception:
                    pass            # capture never takes the fleet down
            self.fail_replica(i)

    def health(self) -> Optional[dict]:
        """Fleet health roll-up: the minimum replica score, the union
        of firing alerts, the failed set, and every replica's own
        snapshot. None when the health engine is off."""
        if not self._health_on:
            return None
        reps = []
        for i, eng in enumerate(self._engines):
            if i in self._failed:
                reps.append(None)
                continue
            try:
                reps.append(eng.health())
            except Exception:       # pragma: no cover - torn down
                reps.append(None)
        live = [r for r in reps if r is not None]
        return {
            "health_score": min((r["health_score"] for r in live),
                                default=0.0),
            "alerts_firing": sorted(
                {a for r in live for a in r["alerts_firing"]}),
            "failed_replicas": sorted(self._failed),
            "replicas": reps,
        }

    def owner_of(self, request_id: int) -> Optional[Tuple[int, int]]:
        """Current ``(replica_index, local_rid)`` of a LIVE request,
        or None once it finished — the loadgen record export stamps
        its NDJSON rows with this so offline analysis can join them
        against the merged trace's per-replica pids."""
        return self._owner.get(request_id)

    def profile(self, n_ticks: int, path: Optional[str] = None):
        """Arm ONE bounded ``jax.profiler`` capture around the next
        ``n_ticks`` CLUSTER ticks — every replica's executables run
        inside the cluster tick, so one process-wide capture covers
        the fleet (jax allows a single live profiler session; this is
        the cluster-forwarded form of ``ServingEngine.profile``).
        ``path`` defaults to ``$PADDLE_TPU_PROFILE_DIR``; returns the
        capture dir, or None under ``PADDLE_TPU_TRACE=0``."""
        return self._prof.arm(n_ticks, path)

    def _hist_put(self, key, g):
        """Record one (replica, local rid) -> global rid mapping for
        the trace rewrite. No-op when tracing is disabled (nothing
        will ever be exported); FIFO-pruned past ``_hist_cap`` (an
        rid older than every ring buffer's reach cannot appear in any
        buffered span, so its mapping is dead)."""
        if self._trace is None:
            return
        h = self._l2g_hist
        h[key] = g
        if len(h) > self._hist_cap:
            # dicts iterate in insertion order: drop the oldest (one
            # insert can only overflow by one)
            h.pop(next(iter(h)))

    # request-span names the trace rewrite maps into the global id
    # namespace: "req<rid>" and "req<rid> queued"
    _REQ_NAME = re.compile(r"^req(\d+)(\s.*)?$")

    def export_trace(self, path: Optional[str] = None):
        """Merge the router's and EVERY replica's span ring buffers
        into ONE Chrome/Perfetto trace: each replica keeps its own
        pid lane (process names rewritten to ``replica<i>:<role>``),
        the cluster's router lane rides alongside, and every request
        id — span names like ``req3`` AND ``rid`` args — is rewritten
        to the CLUSTER-global id, so a disaggregated request's route
        decision, prefill chunks, handoff flow arrow, decode ticks
        and preempt/resume marks line up under one rid end-to-end.
        Returns the trace dict when ``path`` is None, else writes the
        JSON and returns ``path``; None when tracing is disabled
        (``PADDLE_TPU_TRACE=0`` — the recorder is inert)."""
        if self._trace is None:
            return None
        events = list(self._trace.chrome_events())
        for idx, eng in enumerate(self._engines):
            tr = eng.tracer
            if tr is None:          # pragma: no cover - mixed switch
                continue
            events.extend(
                self._rewrite_events(idx, eng, tr.chrome_events()))
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path is None:
            return doc
        os.makedirs(os.path.dirname(os.path.abspath(path)),
                    exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f, default=str)
        return path

    def _rewrite_events(self, idx, eng, evs):
        """One replica's Chrome events, mapped into the cluster
        namespace: request ids -> global ids (span names and args),
        process name -> ``replica<i>:<role>``. Events whose local rid
        never passed through this cluster (none, in practice) keep
        their local id rather than guessing."""
        out = []
        for ev in evs:
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    ev = dict(ev, args={
                        "name": f"replica{idx}:{eng._role}"})
                out.append(ev)
                continue
            name = ev.get("name", "")
            args = ev.get("args")
            new = None
            m = self._REQ_NAME.match(name)
            if m is not None:
                g = self._l2g_hist.get((idx, int(m.group(1))))
                if g is not None:
                    new = dict(ev,
                               name=f"req{g}{m.group(2) or ''}")
            if args and "rid" in args:
                g = self._l2g_hist.get((idx, args["rid"]))
                if g is not None:
                    new = dict(new if new is not None else ev)
                    new["args"] = dict(args, rid=g)
            out.append(new if new is not None else ev)
        return out

    def stats(self) -> dict:
        """Cluster-aggregate snapshot: per-replica ``stats()`` dicts
        under ``replicas`` plus rolled-up routing / transfer /
        throughput / latency keys (the client-side view across the
        whole cluster — the goodput harness's denominators). Failed or
        torn-down replicas are SKIPPED in the roll-ups (annotated in
        ``failed_replicas``, None in ``replicas``) instead of raising
        — the fleet snapshot must survive its own casualties."""
        reps_all: List[Optional[dict]] = []
        skipped = set(self._failed)
        for i, e in enumerate(self._engines):
            if i in self._failed:
                reps_all.append(None)
                continue
            try:
                reps_all.append(e.stats())
            except Exception:       # torn down mid-snapshot
                skipped.add(i)
                reps_all.append(None)
        live_idx = [i for i, r in enumerate(reps_all) if r is not None]
        reps = [reps_all[i] for i in live_idx]
        # headline roofline roll-up: the busiest replica's numbers as
        # a PAIR from that ONE replica — a per-metric max could
        # combine an MFU and a bandwidth figure no single replica
        # exhibits, which is useless for bound classification
        if reps:
            busy = max(range(len(reps)), key=lambda i: (
                reps[i]["roofline"]["step_mfu"],
                reps[i]["roofline"]["step_hbm_bw_util"]))
            roofline = {
                "cpu_proxy": any(r["roofline"]["cpu_proxy"]
                                 for r in reps),
                "busiest_replica": live_idx[busy],
                "step_mfu": reps[busy]["roofline"]["step_mfu"],
                "step_hbm_bw_util":
                    reps[busy]["roofline"]["step_hbm_bw_util"],
            }
        else:                       # every replica down
            roofline = {"cpu_proxy": False, "busiest_replica": None,
                        "step_mfu": 0.0, "step_hbm_bw_util": 0.0}
        return {
            "num_replicas": len(self._decode_idx),
            "prefill_replicas": len(self._prefill_idx),
            "disaggregated": self._disagg,
            "cluster_enabled": cluster_enabled(),
            "failed_replicas": sorted(skipped),
            "active": self.num_active,
            "queued": self.num_queued,
            "pending_handoffs": len(self._pending),
            # elastic fleet (ISSUE 19): ALWAYS present — a fixed-N
            # fleet (no policy / kill switch) reports its static size
            # and zeros, so dashboards never KeyError across configs
            "replicas_live": sum(
                1 for i in self._decode_idx + self._prefill_idx
                if i not in self._failed),
            "removed_replicas": sorted(self._removed),
            "scale_ups": self._n_scale_ups,
            "scale_downs": self._n_scale_downs,
            "sessions_migrated": self._n_migrated,
            "pending_migrations": len(self._pending_mig),
            "migration_ms": self._d_migration.summary(),
            "replica_ticks": self._n_replica_ticks,
            "mean_prompt_len": round(self._prompt_len_ema, 2),
            "autoscale": (self._autoscale.state()
                          if self._autoscale is not None else None),
            "router_requests": self._n_routed,
            "router_affinity_hits": self._n_affinity,
            "router_affinity_hit_rate":
                self._n_affinity / self._n_routed
                if self._n_routed else 0.0,
            "kv_blocks_transferred":
                sum(r["kv_blocks_imported"] for r in reps),
            "preemptions": sum(r["preemptions"] for r in reps),
            "kv_blocks_spilled":
                sum(r["kv_blocks_spilled"] for r in reps),
            "kv_blocks_restored":
                sum(r["kv_blocks_restored"] for r in reps),
            "host_tier_bytes":
                sum(r["host_tier_bytes"] for r in reps),
            # multi-LoRA roll-ups: ALWAYS present (False/0 on
            # base-model fleets) — sums over live replicas, matching
            # the host-tier pattern above
            "lora_enabled": any(r["lora_enabled"] for r in reps),
            "lora_adapters_resident":
                sum(r["lora_adapters_resident"] for r in reps),
            "lora_adapter_swaps":
                sum(r["lora_adapter_swaps"] for r in reps),
            "lora_host_tier_bytes":
                sum(r["lora_host_tier_bytes"] for r in reps),
            "prefix_tokens_reused":
                sum(r["prefix_tokens_reused"] for r in reps),
            "tokens_total": sum(r["tokens_total"] for r in reps),
            "requests_completed": self._n_completed,
            "decode_steps": sum(r["decode_steps"] for r in reps),
            "executables_compiled":
                sum(r["executables_compiled"] for r in reps),
            "ttft_ms": self._d_ttft.summary(),
            "itl_ms": self._d_itl.summary(),
            "e2e_ms": self._d_e2e.summary(),
            # fleet flight recorder (ISSUE 15): ALWAYS present —
            # killed/idle clusters report False/0 so dashboards never
            # KeyError across a rolled-back fleet
            "tracing": self._trace is not None,
            "trace_events_dropped":
                (self._trace.dropped
                 if self._trace is not None else 0)
                + sum(r["trace_events_dropped"] for r in reps),
            "profile_captures": self._prof.captures,
            # fleet health (ISSUE 17): ALWAYS present — min score over
            # live replicas, sums for the counters; a killed fleet
            # reports 1.0 / zeros
            "health_score": min((r["health_score"] for r in reps),
                                default=0.0 if skipped else 1.0),
            "alerts_firing": sum(r["alerts_firing"] for r in reps),
            "alerts_fired_total":
                sum(r["alerts_fired_total"] for r in reps),
            "incidents_captured":
                sum(r["incidents_captured"] for r in reps)
                + (self._incident.captured
                   if self._incident is not None else 0),
            "nonfinite_logits_ticks":
                sum(r["nonfinite_logits_ticks"] for r in reps),
            # async tick pipeline (ISSUE 20): ALWAYS present — max
            # depth across live replicas (the fleet's commit lag is
            # the deepest replica's) and a flush-count sum; a sync or
            # killed fleet reports 0/0
            "async_depth": max((r["async_depth"] for r in reps),
                               default=0),
            "pipeline_flushes":
                sum(r["pipeline_flushes"] for r in reps),
            "roofline": roofline,
            "replicas": reps_all,
        }

    def shutdown(self, check_leaks: bool = True) -> bool:
        """Drain every replica's queue (terminal queue-wait
        observations) and sweep every allocator's free/cached/
        referenced partition — the per-replica leak check, fleet-wide.
        Failed replicas are swept too (their blocks were never freed
        by the drain, so live-slot blocks are passed as expected)."""
        for eng in self._engines:
            eng.shutdown(check_leaks=check_leaks)
        return True

    # -- internals ----------------------------------------------------

    def _live(self):
        return [i for i in range(len(self._engines))
                if i not in self._failed and i not in self._removed]

    def _set_replica_gauge(self):
        self._m_replicas.set(sum(
            1 for i in self._decode_idx + self._prefill_idx
            if i not in self._failed))

    def _make_cb(self, idx):
        def cb(lrid, tok):
            g = self._l2g.get((idx, lrid))
            if g is not None:
                self._on_token(g, tok)
        return cb

    def _on_token(self, g, tok):
        now = time.monotonic()
        prev = self._last_emit.get(g)
        if prev is None:
            t0 = self._submit_t.get(g)
            if t0 is not None:
                self._d_ttft.observe(1000.0 * (now - t0))
        else:
            self._d_itl.observe(1000.0 * (now - prev))
        self._last_emit[g] = now
        rec = self._tokens.get(g)
        if rec is not None:
            rec.append(int(tok))
        self._tick_buf.append((g, int(tok)))
        if self._stream is not None:
            self._stream(g, int(tok))

    def _route_submit(self, g, prompt, max_new_tokens, samp=None):
        """Score candidates, submit to the winner, and map its local
        rid to the global one — shared by ``submit()`` and the
        failure-drain requeue (which must preserve ``g`` AND the
        request's per-slot sampling overrides)."""
        if samp is None:
            samp = self._req_samp.get(g, {})
        tier = self._prefill_idx if self._disagg else self._decode_idx
        cands = {i: self._engines[i] for i in tier
                 if i not in self._failed}
        if not cands and self._disagg:
            # the whole prefill tier failed: decode replicas are full
            # engines (they prefill their own admissions), so a
            # healthy decode tier keeps serving end-to-end — the
            # cluster only dies when NO replica survives
            cands = {i: self._engines[i] for i in self._decode_idx
                     if i not in self._failed}
        if not cands:
            raise RuntimeError(
                "no live replicas to route to "
                f"({len(self._failed)} of {len(self._engines)} "
                "failed)")
        if len(cands) == 1:
            # identity route (kill switch / N=1 / last survivor):
            # skip the per-block prompt hashing — there is nothing to
            # choose between, so affinity is meaningless here
            idx, overlap, depths = next(iter(cands)), 0, {}
        else:
            idx, overlap, depths = self._router.route(
                prompt, cands, priority=int(samp.get("priority", 0)),
                adapter_id=samp.get("adapter_id"))
        # submit FIRST: a validation rejection must not skew the
        # router counters (the hit rate is an acceptance metric)
        lrid = self._engines[idx].submit(prompt, max_new_tokens,
                                         **samp)
        for i, d in depths.items():
            self._m_depth.labels(replica=str(i)).set(d)
        self._n_routed += 1
        if overlap > 0:
            self._n_affinity += 1
            self._m_affinity.inc()
        self._l2g[(idx, lrid)] = g
        self._owner[g] = (idx, lrid)
        self._hist_put((idx, lrid), g)
        if self._trace is not None:
            # router-decision span: which replica won, on how much
            # published-prefix overlap, against which queue depths
            self._trace.instant(
                "route", tid=0,
                args={"rid": g, "replica": idx,
                      "overlap": int(overlap),
                      "depths": {str(i): float(d)
                                 for i, d in depths.items()}})

    def _place_handoffs(self):
        """Import pending prefilled requests into decode replicas,
        least-loaded first; a handoff that finds no capacity stays
        pending for the next tick (its blocks are already freed on the
        prefill engine — the payload carries the bytes)."""
        still = []
        for src, rec in self._pending:
            live = [i for i in self._decode_idx
                    if i not in self._failed]
            if not live:
                # the whole decode tier failed: a prefill engine
                # cannot decode, so nothing can continue this request
                # — terminate it with the tokens already streamed
                # (the first token) instead of stranding run() or
                # raising past a healthy prefill tier; submit()
                # rejects new disaggregated requests in this state
                warnings.warn(
                    "all decode replicas failed; terminating "
                    f"prefilled request {rec.request_id} with the "
                    "tokens already streamed")
                g = self._l2g.pop((src, rec.request_id), None)
                if g is not None:
                    self._finish(g)
                continue
            g = self._l2g.get((src, rec.request_id))
            if g is None:       # cancelled/failed upstream: drop
                continue
            placed = False
            for i in sorted(live, key=lambda j:
                            self._engines[j].num_active
                            + self._engines[j].num_queued):
                drid = self._engines[i].admit_prefilled(rec)
                if drid is not None:
                    self._l2g.pop((src, rec.request_id), None)
                    self._l2g[(i, drid)] = g
                    self._owner[g] = (i, drid)
                    self._hist_put((i, drid), g)
                    if self._trace is not None:
                        self._trace.instant(
                            "handoff placed", tid=0,
                            args={"rid": g, "src": src, "dst": i,
                                  "blocks": rec.n_blocks})
                    placed = True
                    break
            if not placed:
                still.append((src, rec))
        self._pending = still

    def _safe_step(self, idx):
        try:
            self._engines[idx].step()
        except Exception as exc:        # noqa: BLE001 — fault domain
            warnings.warn(
                f"cluster replica {idx} failed mid-step ({exc!r}); "
                "draining its queue back to the router")
            self.fail_replica(idx)
            if not self._live():
                raise

    def _safe_phase(self, idx, dispatch: bool):
        """One phase of an overlapped decode tick (same fault domain
        as ``_safe_step``): dispatch launches the replica's next tick,
        commit drains its lagging host bookkeeping. Sync replicas run
        their whole step in the dispatch phase."""
        try:
            eng = self._engines[idx]
            if dispatch:
                eng.tick_dispatch()
            else:
                eng.tick_commit()
        except Exception as exc:        # noqa: BLE001 — fault domain
            warnings.warn(
                f"cluster replica {idx} failed mid-step ({exc!r}); "
                "draining its queue back to the router")
            self.fail_replica(idx)
            if not self._live():
                raise

    def _collect_done(self):
        """Completion signal: a request is done when the replica that
        owns its tail retires it (``_done`` populated under
        ``retain_results``). Token content comes from the CLUSTER's
        own stream records, so a disaggregated request's first token
        (prefill engine) and continuation (decode replica) splice into
        one result."""
        for idx, eng in enumerate(self._engines):
            if not eng._done:
                continue
            for lrid in list(eng._done):
                eng._done.pop(lrid)
                g = self._l2g.pop((idx, lrid), None)
                if g is not None:
                    self._finish(g)

    def _finish(self, g):
        now = time.monotonic()
        t0 = self._submit_t.pop(g, None)
        if t0 is not None:
            self._d_e2e.observe(1000.0 * (now - t0))
        self._last_emit.pop(g, None)
        self._owner.pop(g, None)
        self._req_samp.pop(g, None)
        self._done[g] = np.asarray(self._tokens.pop(g, []), np.int64)
        self._n_completed += 1
