"""Elastic fleet autoscaling policy (ISSUE 19): capacity that tracks
load.

``AutoscalePolicy`` is a PURE decision function over the cluster's
existing telemetry — no engine imports, no side effects beyond its own
hysteresis counters — so the control loop is unit-testable on
synthetic signal streams and the cluster stays the only actor that
spawns or drains replicas. Each cluster tick the policy sees one
``signals`` dict:

- ``replicas`` / ``slots`` / ``active`` / ``queued`` — the decode
  tier's live size, aggregate slot capacity, resident sessions and
  queued work (queue depth per slot is the primary pressure signal);
- ``burn_fast`` — the worst live replica's fast SLO burn rate
  (``HealthMonitor.burn_rates()``, the PR 17 goodput signal): traffic
  can burn error budget while occupancy still looks moderate, so a
  burning fleet scales up even below the occupancy trigger;
- ``busy`` — the busiest replica's roofline utilization
  (``max(step_mfu, step_hbm_bw_util)``): a compute-saturated fleet
  with an empty queue is still a fleet about to queue;
- ``prefill_replicas`` / ``prefill_slots`` / ``prefill_active`` /
  ``prefill_queued`` — the prefill tier's pressure in disaggregated
  mode. A shifting prompt-length mix shows up HERE first: longer
  prompts raise prefill queue-per-slot while decode occupancy lags,
  and ``decide_prefill`` retunes the prefill:decode ratio from that
  skew (``mean_prompt_len`` rides along for dashboards/tests).

Decisions are rate-limited twice: a trigger must hold for
``hysteresis_ticks`` CONSECUTIVE ticks before it acts (one bursty tick
never flaps the fleet), and any action starts a ``cooldown_ticks``
hold-down (scale effects take ticks to show; reacting to a
mid-transient snapshot double-scales). Scale-down additionally
requires ALL down-triggers at once — draining a replica live-migrates
every resident session, which is invisible to clients but not free.

Kill switch ``PADDLE_TPU_AUTOSCALE=0``: the cluster never constructs a
policy, so a configured cluster is bit-for-bit a fixed-N fleet —
rollback is one env var, like every switch in this repo. See
docs/OPS.md "Elastic autoscaling & live migration".
"""
from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = ["AutoscaleConfig", "AutoscalePolicy", "autoscale_enabled"]


def autoscale_enabled() -> bool:
    """False under the ``PADDLE_TPU_AUTOSCALE=0`` kill switch — the
    cluster then ignores its ``ClusterConfig.autoscale`` policy and
    runs as a fixed-N fleet (manual ``scale_up``/``scale_down`` keep
    working; only the automatic control loop is inert)."""
    return os.environ.get("PADDLE_TPU_AUTOSCALE", "1") != "0"


@dataclass
class AutoscaleConfig:
    """Knobs for :class:`AutoscalePolicy`. Thresholds are per-slot
    ratios so one config serves any replica size."""
    # decode-tier fleet bounds (live replicas; failed ones don't count)
    min_replicas: int = 1
    max_replicas: int = 4
    # scale-up triggers (ANY fires): queued work per decode slot, slot
    # occupancy, fast SLO burn rate (the page threshold from the
    # health engine's burn-rate monitors), roofline busy-ness
    up_queue_per_slot: float = 0.5
    up_occupancy: float = 0.95
    up_burn_fast: float = 14.0
    up_busy: float = 0.95
    # scale-down triggers (ALL must hold): occupancy AND queue both
    # under their floors — a drain is client-invisible but not free
    down_occupancy: float = 0.35
    down_queue_per_slot: float = 0.05
    # consecutive breaching ticks before acting / hold-down after any
    # action (either tier)
    hysteresis_ticks: int = 3
    cooldown_ticks: int = 20
    # disaggregated prefill:decode ratio retune (both 0 = never touch
    # the prefill tier); same per-slot queue thresholds, prefill side
    min_prefill_replicas: int = 0
    max_prefill_replicas: int = 0
    prefill_up_queue_per_slot: float = 0.5
    prefill_down_queue_per_slot: float = 0.05

    def __post_init__(self):
        if not (isinstance(self.min_replicas, int)
                and not isinstance(self.min_replicas, bool)
                and self.min_replicas >= 1):
            raise ValueError(
                f"min_replicas must be an int >= 1, got "
                f"{self.min_replicas!r}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) < min_replicas "
                f"({self.min_replicas})")
        if self.min_prefill_replicas < 0 \
                or self.max_prefill_replicas < self.min_prefill_replicas:
            raise ValueError(
                "prefill replica bounds must satisfy 0 <= min <= max,"
                f" got [{self.min_prefill_replicas}, "
                f"{self.max_prefill_replicas}]")
        if self.hysteresis_ticks < 1 or self.cooldown_ticks < 0:
            raise ValueError(
                "hysteresis_ticks must be >= 1 and cooldown_ticks "
                f">= 0, got {self.hysteresis_ticks}/"
                f"{self.cooldown_ticks}")


class AutoscalePolicy:
    """Hysteresis + cooldown control loop over cluster signals. Call
    :meth:`decide` once per cluster tick with the decode tier's
    signals (``"up"`` / ``"down"`` / ``"hold"``), and — in
    disaggregated mode — :meth:`decide_prefill` on ticks where the
    decode tier held. The policy assumes the caller EXECUTES every
    non-hold decision (the cooldown starts either way — an
    inexecutable decision, e.g. no cold replica to drain, must not
    retrigger every tick)."""

    def __init__(self, config: AutoscaleConfig | None = None):
        self.config = config or AutoscaleConfig()
        self._up = 0            # consecutive up-trigger ticks
        self._down = 0
        self._p_up = 0
        self._p_down = 0
        self._cooldown = 0
        self.decisions = {"up": 0, "down": 0, "hold": 0,
                          "prefill_up": 0, "prefill_down": 0}

    def _act(self, name):
        self._up = self._down = self._p_up = self._p_down = 0
        self._cooldown = self.config.cooldown_ticks
        self.decisions[name] += 1
        return name.split("_")[-1]

    def decide(self, signals: dict) -> str:
        """One decode-tier decision from one tick's signals."""
        cfg = self.config
        n = max(1, int(signals.get("replicas", 1)))
        slots = max(1, int(signals.get("slots", 1)))
        occ = float(signals.get("active", 0)) / slots
        qps = float(signals.get("queued", 0)) / slots
        burn = float(signals.get("burn_fast", 0.0))
        busy = float(signals.get("busy", 0.0))
        want_up = (qps >= cfg.up_queue_per_slot
                   or occ >= cfg.up_occupancy
                   or burn >= cfg.up_burn_fast
                   or busy >= cfg.up_busy)
        want_down = (occ <= cfg.down_occupancy
                     and qps <= cfg.down_queue_per_slot)
        self._up = self._up + 1 if want_up else 0
        self._down = self._down + 1 if want_down else 0
        if self._cooldown > 0:
            self._cooldown -= 1
        elif self._up >= cfg.hysteresis_ticks \
                and n < cfg.max_replicas:
            return self._act("up")
        elif self._down >= cfg.hysteresis_ticks \
                and n > cfg.min_replicas:
            return self._act("down")
        self.decisions["hold"] += 1
        return "hold"

    def decide_prefill(self, signals: dict) -> str:
        """One prefill-tier decision (disaggregated ratio retune) —
        call only on ticks where the decode tier held, so the fleet
        changes at most one replica per tick."""
        cfg = self.config
        if cfg.max_prefill_replicas <= 0:
            return "hold"
        n = int(signals.get("prefill_replicas", 0))
        slots = max(1, int(signals.get("prefill_slots", 1)))
        occ = float(signals.get("prefill_active", 0)) / slots
        qps = float(signals.get("prefill_queued", 0)) / slots
        want_up = qps >= cfg.prefill_up_queue_per_slot or occ >= 1.0
        want_down = (qps <= cfg.prefill_down_queue_per_slot
                     and occ <= cfg.down_occupancy)
        self._p_up = self._p_up + 1 if want_up else 0
        self._p_down = self._p_down + 1 if want_down else 0
        if self._cooldown > 0:
            pass        # decide() already consumed this tick's decay
        elif self._p_up >= cfg.hysteresis_ticks \
                and n < cfg.max_prefill_replicas:
            return self._act("prefill_up")
        elif self._p_down >= cfg.hysteresis_ticks \
                and n > cfg.min_prefill_replicas:
            return self._act("prefill_down")
        return "hold"

    def state(self) -> dict:
        """Introspection snapshot (stats / tests): streak counters,
        cooldown remaining, decision tallies."""
        return {"up_streak": self._up, "down_streak": self._down,
                "prefill_up_streak": self._p_up,
                "prefill_down_streak": self._p_down,
                "cooldown_remaining": self._cooldown,
                "decisions": dict(self.decisions)}
