"""``paddle.inference`` — deployment predictor
(``paddle/fluid/inference/api/analysis_predictor.cc`` +
``python/paddle/inference/`` parity).

TPU-first: the reference loads a ``*.pdmodel`` program, runs analysis/
fusion passes, and executes zero-copy through ``AnalysisPredictor``. Here
the artifact produced by ``paddle.jit.save`` already IS the compiled
program (a serialized jax.export StableHLO module — XLA did the fusion
at export time), so ``Predictor`` deserializes it once and ``run()``
executes the AOT module on device. The named-handle API
(``get_input_handle``/``copy_from_cpu``/``copy_to_cpu``) is preserved so
reference deployment scripts port unchanged.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "GenerationPredictor", "create_generation_predictor",
           "ServingConfig", "ServingEngine", "ServingRequest",
           "QueueShedError",
           "ClusterConfig", "EngineCluster", "Router",
           "SLO", "run_load",
           "PrecisionType", "PlaceType", "get_version"]


def __getattr__(name):
    # lazy: the serving engine pulls in jax/model machinery that plain
    # Predictor users never need
    if name in ("ServingConfig", "ServingEngine", "ServingRequest",
                "PrefilledRequest", "QueueShedError"):
        from . import serving
        return getattr(serving, name)
    if name in ("ClusterConfig", "EngineCluster", "Router"):
        from . import cluster
        return getattr(cluster, name)
    if name in ("SLO", "RequestRecord", "run_load", "summarize",
                "poisson_arrivals", "uniform_arrivals",
                "conversation_workload"):
        from . import loadgen
        return getattr(loadgen, name)
    raise AttributeError(name)


def get_version() -> str:
    from .. import __version__
    return __version__


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3  # TPU rides the custom-device slot in the reference


class Config:
    """``paddle.inference.Config`` parity. GPU/TRT/MKLDNN toggles are
    accepted for script compatibility; on TPU the program is already an
    XLA-compiled module, so they record intent only."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # paddle convention: Config("path/model") with implicit suffixes
        if prog_file and not prog_file.endswith(".pdmodel"):
            self._prefix = prog_file
        elif prog_file:
            self._prefix = prog_file[:-len(".pdmodel")]
        else:
            self._prefix = None
        self._params_file = params_file
        self._precision = PrecisionType.Float32
        self._memory_pool_mb = 0
        self._enable_profile = False
        self._glog_info = False
        self._optim = True

    def set_model(self, prog_file: str, params_file: str = None):
        self.__init__(prog_file, params_file)

    def model_dir(self) -> str:
        return os.path.dirname(self._prefix or "")

    def prog_file(self) -> str:
        return (self._prefix or "") + ".pdmodel"

    def params_file(self) -> str:
        return self._params_file or (self._prefix or "") + ".pdparams"

    # accelerator knobs (recorded; XLA owns placement/fusion on TPU)
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        self._memory_pool_mb = memory_pool_init_size_mb
        self._precision = precision

    def disable_gpu(self):
        pass

    def enable_xpu(self, *a, **k):
        pass

    def enable_custom_device(self, device_type="tpu", device_id=0):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        pass

    def enable_mkldnn(self):
        pass

    def switch_ir_optim(self, flag=True):
        self._optim = flag

    def enable_memory_optim(self):
        pass

    def enable_profile(self):
        self._enable_profile = True

    def disable_glog_info(self):
        self._glog_info = False

    def set_cpu_math_library_num_threads(self, n):
        pass


class Tensor:
    """Named I/O handle (``paddle_infer::Tensor`` parity)."""

    def __init__(self, name: str, predictor: "Predictor", is_input: bool):
        self.name = name
        self._pred = predictor
        self._is_input = is_input

    def copy_from_cpu(self, data: np.ndarray):
        if not self._is_input:
            raise RuntimeError("copy_from_cpu on an output handle")
        self._pred._inputs[self.name] = np.ascontiguousarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        if self._is_input:
            return np.asarray(self._pred._inputs.get(self.name))
        if self._pred._outputs is None:
            raise RuntimeError("run() has not been called")
        return np.asarray(self._pred._outputs[self.name])

    def shape(self):
        if self._is_input:
            arr = self._pred._inputs.get(self.name)
            if arr is not None:
                return list(arr.shape)
            return self._pred._input_meta[self.name]["shape"]
        if self._pred._outputs is not None:
            return list(np.asarray(
                self._pred._outputs[self.name]).shape)
        return None

    def reshape(self, shape):
        pass  # shapes are fixed at export on TPU


class Predictor:
    def __init__(self, config: Config):
        from ..jit import load as jit_load
        self.config = config
        self._translated = jit_load(config._prefix)
        if self._translated._exported is None:
            raise ValueError(
                f"{config.prog_file()} has no exported program — save "
                f"the model with paddle.jit.save(layer, path, "
                f"input_spec=[...])")
        spec = self._translated.input_spec
        self._input_names = [
            s.get("name") or f"x{i}" for i, s in enumerate(spec)]
        self._input_meta = {
            n: s for n, s in zip(self._input_names, spec)}
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs = None
        self._output_names: List[str] = []

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        if name not in self._input_names:
            raise KeyError(name)
        return Tensor(name, self, is_input=True)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is not None:
            if len(inputs) != len(self._input_names):
                from ..framework.errors import InvalidArgumentError
                raise InvalidArgumentError(
                    f"run() got {len(inputs)} inputs; the exported "
                    f"program expects {len(self._input_names)} "
                    f"({self._input_names})")
            for n, a in zip(self._input_names, inputs):
                self._inputs[n] = np.ascontiguousarray(a)
        missing = [n for n in self._input_names if n not in self._inputs]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        args = [self._inputs[n] for n in self._input_names]
        out = self._translated(*args)
        if not isinstance(out, (list, tuple)):
            out = [out]
        from ..framework.core import Tensor as _T
        arrays = [np.asarray(o.numpy() if isinstance(o, _T) else o)
                  for o in out]
        self._output_names = [f"out{i}" for i in range(len(arrays))]
        self._outputs = dict(zip(self._output_names, arrays))
        if inputs is not None:
            return arrays
        return True

    def get_output_names(self) -> List[str]:
        return list(self._output_names) or ["out0"]

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self, is_input=False)

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class GenerationPredictor:
    """LLM serving predictor (the role PaddleNLP's
    ``llm/predict/predictor.py`` plays over AnalysisPredictor): wraps a
    causal-LM Layer's KV-cache ``generate()`` decode loop. The loop is
    one jitted XLA program per (batch, prompt-len, max-new) shape —
    compiled on first call, cached after."""

    def __init__(self, model, generation_config=None):
        from ..generation import GenerationConfig, GenerationMixin
        if not isinstance(model, GenerationMixin):
            raise TypeError(
                f"{type(model).__name__} does not support generation "
                "(needs the KV-cache protocol: init_caches + caches/"
                "offset forward kwargs)")
        self.model = model
        self.generation_config = generation_config or GenerationConfig()
        model.eval()

    def generate(self, input_ids, **overrides) -> np.ndarray:
        """input_ids: [B, L] numpy/array of token ids. Returns the
        generated ids [B, max_new_tokens] as numpy (pad after EOS)."""
        from ..framework.core import Tensor as _T
        ids = np.ascontiguousarray(np.asarray(input_ids))
        out, _scores = self.model.generate(
            _T(ids), generation_config=self.generation_config,
            **overrides)
        return np.asarray(out.numpy())


def create_generation_predictor(model,
                                generation_config=None
                                ) -> GenerationPredictor:
    return GenerationPredictor(model, generation_config)
