"""Metrics (``python/paddle/metric/metrics.py`` parity)."""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor, as_jax

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(as_jax(x)) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__.lower()

    def name(self):
        return self._name

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-computation hook run on device outputs; default
        passes predictions/labels straight through."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        top = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        correct = (top == label_np[..., None])
        return correct.astype(np.float32)

    def update(self, correct, *args):
        correct = _np(correct)
        num = correct.shape[0] if correct.ndim else 1
        for i, k in enumerate(self.topk):
            acc_k = correct[..., :k].sum(-1).mean() if correct.ndim else \
                float(correct)
            self.total[i] += float(acc_k) * num
            self.count[i] += num
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        bins = np.round(pos_prob * self.num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self.num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoid over thresholds, descending
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    from .. import ops
    pred = _np(input)
    lab = _np(label).reshape(-1)
    topk_idx = np.argsort(-pred, axis=-1)[:, :k]
    hit = (topk_idx == lab[:, None]).any(axis=1)
    return Tensor(np.asarray(hit.mean(), np.float32))
