"""``paddle.text`` (reference: ``python/paddle/text/``) — ViterbiDecoder
plus the text datasets (offline synthetic fallbacks, same pattern as
``paddle_tpu.vision.datasets``)."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ..nn.layer.layers import Layer
from ..io import Dataset

__all__ = ["ViterbiDecoder", "viterbi_decode", "Imdb", "UCIHousing"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode (``paddle.text.viterbi_decode`` /
    ``paddle/phi/kernels/cpu/viterbi_decode_kernel.cc`` parity).

    potentials: [B, L, T] unary emissions; transition_params: [T, T];
    lengths: [B] int64. Returns (scores [B], paths [B, L]).
    TPU-first: the per-step max-product recursion is a ``lax.scan``
    carrying (best score per tag, backpointers)."""

    def f(pot, trans, lens):
        b, seq, t = pot.shape
        start = pot[:, 0, :]
        if include_bos_eos_tag:
            # BOS = tag t-2: transitions out of BOS added at step 0
            start = start + trans[t - 2][None, :]

        def step(carry, xs):
            score = carry                        # [B, T]
            emit, idx = xs                       # [B, T], scalar
            cand = score[:, :, None] + trans[None]  # [B, T_from, T_to]
            best = jnp.max(cand, axis=1) + emit
            bp = jnp.argmax(cand, axis=1)
            live = (idx < lens)[:, None]
            score2 = jnp.where(live, best, score)
            return score2, jnp.where(live, bp,
                                     jnp.arange(t)[None, :])

        idxs = jnp.arange(1, seq)
        final, bps = jax.lax.scan(step, start,
                                  (jnp.transpose(pot[:, 1:],
                                                 (1, 0, 2)), idxs))
        if include_bos_eos_tag:
            final = final + trans[:, t - 1][None, :]  # into EOS
        last_tag = jnp.argmax(final, axis=-1)
        scores = jnp.max(final, axis=-1)

        def backtrack(carry, bp):
            tag = carry
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, prev     # emit the tag at THIS position

        _, path_rev = jax.lax.scan(backtrack, last_tag, bps,
                                   reverse=True)
        paths = jnp.concatenate(
            [jnp.transpose(path_rev, (1, 0)), last_tag[:, None]],
            axis=1)                              # [B, L]
        # positions beyond each length keep the final tag (reference
        # semantics: caller slices by length)
        return scores, paths.astype(jnp.int64)

    return apply_jax("viterbi_decode", f, potentials, transition_params,
                     lengths, n_outputs=2)


class ViterbiDecoder(Layer):
    """``paddle.text.ViterbiDecoder`` parity."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(np.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


class Imdb(Dataset):
    """IMDB sentiment dataset surface. Offline synthetic fallback:
    token-id sequences whose label correlates with a marker token (same
    split-stable pattern as the synthetic vision datasets)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 vocab_size=5000, seq_len=64, n=512):
        seed = 1234 if mode == "train" else 4321
        rng = np.random.RandomState(seed)
        self.docs = rng.randint(2, vocab_size, (n, seq_len)) \
            .astype(np.int64)
        self.labels = rng.randint(0, 2, (n,)).astype(np.int64)
        self.docs[:, 0] = self.labels          # separable marker
        self.word_idx = {i: i for i in range(vocab_size)}

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], int(self.labels[i])


class UCIHousing(Dataset):
    """UCI housing regression surface (13 features -> price), synthetic
    offline fallback with a fixed linear ground truth + noise."""

    def __init__(self, data_file=None, mode="train", n=404):
        seed = 1234 if mode == "train" else 4321
        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, 13).astype(np.float32)
        w = np.linspace(-1.0, 1.0, 13).astype(np.float32)
        self.y = (self.x @ w + 0.05 * rng.randn(n)) \
            .astype(np.float32)[:, None]

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]
