"""Profiler (``python/paddle/profiler/`` parity) over ``jax.profiler``.

CUPTI-based GPU tracing (``paddle/fluid/platform/profiler/cuda_tracer.cc``)
maps to the XLA/TPU profiler: traces land in TensorBoard format, RecordEvent
maps to ``jax.profiler.TraceAnnotation`` (SURVEY.md §5.1).
"""
from __future__ import annotations

import contextlib
import enum
import os
import time

import jax

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "SortedKeys", "make_scheduler", "export_chrome_tracing",
           "load_profiler_result"]


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    def schedule(step):
        step -= skip_first
        if step < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if repeat and step >= period * repeat:
            return ProfilerState.CLOSED
        pos = step % period if period else 0
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return schedule


class RecordEvent:
    """Host-side trace annotation (``platform::RecordEvent`` parity).

    Besides the ``jax.profiler.TraceAnnotation`` (visible in the
    TensorBoard/Chrome trace), every span also lands in the metrics
    registry as a ``record_event_ms{name=...}`` histogram — so span
    counts and wall time are observable without a trace capture (spans
    inside a jit trace measure TRACE time, not device time)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ctx = None
        self._t0 = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        self._t0 = time.perf_counter()
        try:
            self._ctx = jax.profiler.TraceAnnotation(self.name)
            self._ctx.__enter__()
        except Exception:
            self._ctx = None

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None
        if self._t0 is not None:
            dt_ms = (time.perf_counter() - self._t0) * 1000.0
            self._t0 = None
            try:
                from ..monitor import get_registry
                get_registry().histogram(
                    "record_event_ms",
                    "RecordEvent span wall time (host side)",
                    labels=("name",)).labels(name=self.name) \
                    .observe(dt_ms)
            except Exception:
                pass


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self.timer_only = timer_only
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self._log_dir = None
        self._trace_dir = None      # survives stop() for summary/export
        self._running = False
        self._step = 0
        self._step_times = []
        self._last_step_t = None

    def start(self):
        self._running = True
        self._last_step_t = time.perf_counter()
        if not self.timer_only:
            self._log_dir = os.environ.get(
                "PADDLE_PROFILER_LOG_DIR", "./profiler_log")
            try:
                jax.profiler.start_trace(self._log_dir)
                self._trace_dir = self._log_dir
            except Exception:
                self._log_dir = None

    def stop(self):
        if self._log_dir is not None:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._log_dir = None
        self._running = False
        if self.on_trace_ready:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        ts = np.asarray(self._step_times)
        return (f"avg {ts.mean()*1000:.2f} ms/step, "
                f"min {ts.min()*1000:.2f}, max {ts.max()*1000:.2f}")

    # -- statistics (python/paddle/profiler/profiler_statistic.py) ----

    def _op_records(self):
        """Aggregate device-op durations from the captured xplane trace:
        [(name, category, calls, total_ms)] sorted by total time."""
        if self._trace_dir is None:
            return []
        return _parse_xplane_ops(self._trace_dir)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Formatted statistic tables (``profiler_statistic`` parity):
        step-time summary, device op summary (from the XLA trace), and
        device memory summary."""
        lines = []
        # --- step summary
        lines.append(_table(
            "Step Summary",
            ["stat", "value"],
            [["steps", str(len(self._step_times))],
             ["", self.step_info()]]))
        # --- device op summary
        ops = self._op_records() if op_detail else []
        if ops:
            total = sum(r[3] for r in ops) or 1.0
            if sorted_by in (None, "total", SortedKeys.OpTotal):
                ops = sorted(ops, key=lambda r: -r[3])
            elif sorted_by in ("calls", SortedKeys.OpCalls):
                ops = sorted(ops, key=lambda r: -r[2])
            rows = [[name[:48], cat[:20], str(calls),
                     f"{ms:.3f}", f"{ms / calls:.4f}",
                     f"{100 * ms / total:.1f}%"]
                    for name, cat, calls, ms in ops[:40]]
            lines.append(_table(
                "Device Op Summary (from XLA trace)",
                ["name", "category", "calls", "total_ms", "avg_ms",
                 "pct"], rows))
        # --- memory summary
        mem = _memory_stats()
        if mem:
            lines.append(_table(
                "Device Memory Summary",
                ["stat", "bytes"],
                [[k, str(v)] for k, v in sorted(mem.items())]))
        return "\n".join(lines)

    def export(self, path, format="json"):
        """Write the captured trace: ``format="json"`` emits a Chrome
        trace (decompressed from the profiler's trace.json.gz);
        ``format="summary"`` writes the summary tables; anything else
        copies the raw TensorBoard trace directory path reference."""
        if format == "summary":
            with open(path, "w") as f:
                f.write(self.summary())
            return path
        if self._trace_dir is None:
            raise RuntimeError(
                "no trace captured (timer_only profiler or start() "
                "not called)")
        src = _find_chrome_trace(self._trace_dir)
        if src is None:
            raise RuntimeError(
                f"no chrome trace found under {self._trace_dir}")
        import gzip
        import shutil
        if path.endswith(".gz"):
            shutil.copyfile(src, path)
        else:
            with gzip.open(src, "rb") as fin, open(path, "wb") as fout:
                shutil.copyfileobj(fin, fout)
        return path

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class SortedKeys(enum.Enum):
    """``paddle.profiler.SortedKeys`` parity (subset)."""
    OpTotal = 0
    OpCalls = 1


def _table(title, headers, rows):
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else
              len(h) for i, h in enumerate(headers)]
    sep = "-+-".join("-" * w for w in widths)
    out = [title, sep,
           " | ".join(h.ljust(w) for h, w in zip(headers, widths)),
           sep]
    for r in rows:
        out.append(" | ".join(c.ljust(w) for c, w in zip(r, widths)))
    out.append(sep)
    return "\n".join(out)


def _memory_stats():
    try:
        stats = jax.local_devices()[0].memory_stats()
        if not stats:
            return {}
        keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size")
        return {k: v for k, v in stats.items() if k in keep}
    except Exception:
        return {}


def _find_chrome_trace(log_dir):
    import glob
    hits = sorted(glob.glob(os.path.join(
        log_dir, "plugins", "profile", "*", "*.trace.json.gz")))
    return hits[-1] if hits else None


def _op_base_category(name):
    """Shared normalization: strip the SSA %-prefix / numeric suffixes
    off an op name to get its category."""
    import re
    base = re.sub(r"\.\d+$", "", name.split(" ")[0].lstrip("%"))
    return re.sub(r"\.\d+$", "", base.split("=")[0]).strip()


def _parse_xplane_ops(log_dir):
    """Aggregate the trace's device-op events into
    [(name, category, calls, total_ms)]. Primary source is the xplane
    proto bundled with tensorflow's tsl; on TPU images without TF the
    decompressed Chrome ``trace.json.gz`` serves the same table (its
    thread names mirror the xplane lines), so ``summary()`` is never
    empty for lack of the proto."""
    ops = _parse_xplane_proto(log_dir)
    if ops:
        return ops
    return _parse_chrome_trace_ops(log_dir)


def _parse_chrome_trace_ops(log_dir):
    """Device-op table from the Chrome trace: complete ("X") events on
    device-process threads, aggregated by op name. Durations are in
    microseconds in the Chrome format."""
    src = _find_chrome_trace(log_dir)
    if src is None:
        return []
    import gzip
    import json as _json
    try:
        with gzip.open(src, "rt") as f:
            data = _json.load(f)
    except Exception:
        return []
    events = data.get("traceEvents", []) or []
    pnames, tnames = {}, {}
    for ev in events:
        if ev.get("ph") != "M":
            continue
        if ev.get("name") == "process_name":
            pnames[ev.get("pid")] = ev.get("args", {}).get("name", "")
        elif ev.get("name") == "thread_name":
            tnames[(ev.get("pid"), ev.get("tid"))] = \
                ev.get("args", {}).get("name", "")
    agg = {}

    def _consume(pred):
        found = False
        for ev in events:
            if ev.get("ph") != "X" or not pred(ev):
                continue
            name = ev.get("name", "?")
            cat = _op_base_category(name)
            calls, ms = agg.get((name, cat), (0, 0.0))
            agg[(name, cat)] = (calls + 1,
                                ms + float(ev.get("dur", 0)) / 1e3)
            found = True
        return found

    def _device(ev):
        pn = pnames.get(ev.get("pid"), "")
        tn = tnames.get((ev.get("pid"), ev.get("tid")), "")
        return (("TPU" in pn or "GPU" in pn)
                and (not tn or "XLA Ops" in tn or "Steps" not in tn))

    got = _consume(_device)
    if not got:                      # CPU backend: take host events
        _consume(lambda ev: True)
    return [(name, cat, calls, ms)
            for (name, cat), (calls, ms) in agg.items()]


def _parse_xplane_proto(log_dir):
    import glob
    import re
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception:
        return []
    paths = sorted(glob.glob(os.path.join(
        log_dir, "plugins", "profile", "*", "*.xplane.pb")))
    if not paths:
        return []
    sp = xplane_pb2.XSpace()
    try:
        with open(paths[-1], "rb") as f:
            sp.ParseFromString(f.read())
    except Exception:
        return []
    agg = {}

    def _consume(plane, line_filter):
        em = plane.event_metadata
        found = False
        for line in plane.lines:
            if line_filter and line.name != line_filter:
                continue
            for ev in line.events:
                meta = em.get(ev.metadata_id)
                name = meta.name if meta is not None else "?"
                base = re.sub(r"\.\d+$", "",
                              name.split(" ")[0].lstrip("%"))
                cat = re.sub(r"\.\d+$", "", base.split("=")[0]).strip()
                calls, ms = agg.get((name, cat), (0, 0.0))
                agg[(name, cat)] = (calls + 1,
                                    ms + ev.duration_ps / 1e9)
                found = True
        return found

    got = False
    for plane in sp.planes:
        if "TPU" in plane.name or "GPU" in plane.name:
            got |= _consume(plane, "XLA Ops")
    if not got:                      # CPU backend: take host events
        for plane in sp.planes:
            if "CPU" in plane.name or "Host" in plane.name:
                _consume(plane, None)
    return [(name, cat, calls, ms)
            for (name, cat), (calls, ms) in agg.items()]


def export_chrome_tracing(dir_name, worker_name=None):
    """Trace-ready handler (``export_chrome_tracing`` parity): traces
    land under ``dir_name`` and a decompressed Chrome trace json is
    written there when the profiler stops."""
    os.environ["PADDLE_PROFILER_LOG_DIR"] = dir_name

    def handler(prof):
        try:
            os.makedirs(dir_name, exist_ok=True)
            name = worker_name or "worker"
            prof.export(os.path.join(dir_name, f"{name}.json"))
        except Exception:
            pass
    return handler


def load_profiler_result(path):
    """Load an exported Chrome trace json back as a dict."""
    import gzip
    import json as _json
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return _json.load(f)
