"""Profiler (``python/paddle/profiler/`` parity) over ``jax.profiler``.

CUPTI-based GPU tracing (``paddle/fluid/platform/profiler/cuda_tracer.cc``)
maps to the XLA/TPU profiler: traces land in TensorBoard format, RecordEvent
maps to ``jax.profiler.TraceAnnotation`` (SURVEY.md §5.1).
"""
from __future__ import annotations

import contextlib
import enum
import os
import time

import jax

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result"]


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    def schedule(step):
        step -= skip_first
        if step < 0:
            return ProfilerState.CLOSED
        period = closed + ready + record
        if repeat and step >= period * repeat:
            return ProfilerState.CLOSED
        pos = step % period if period else 0
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return schedule


class RecordEvent:
    """Host-side trace annotation (``platform::RecordEvent`` parity)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ctx = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        try:
            self._ctx = jax.profiler.TraceAnnotation(self.name)
            self._ctx.__enter__()
        except Exception:
            self._ctx = None

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self.timer_only = timer_only
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self._log_dir = None
        self._running = False
        self._step = 0
        self._step_times = []
        self._last_step_t = None

    def start(self):
        self._running = True
        self._last_step_t = time.perf_counter()
        if not self.timer_only:
            self._log_dir = os.environ.get(
                "PADDLE_PROFILER_LOG_DIR", "./profiler_log")
            try:
                jax.profiler.start_trace(self._log_dir)
            except Exception:
                self._log_dir = None

    def stop(self):
        if self._log_dir is not None:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._log_dir = None
        self._running = False
        if self.on_trace_ready:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        ts = np.asarray(self._step_times)
        return (f"avg {ts.mean()*1000:.2f} ms/step, "
                f"min {ts.min()*1000:.2f}, max {ts.max()*1000:.2f}")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        return self.step_info()

    def export(self, path, format="json"):
        pass

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        pass
    os.environ["PADDLE_PROFILER_LOG_DIR"] = dir_name
    return handler


def load_profiler_result(path):
    raise NotImplementedError("use TensorBoard to view TPU traces")
