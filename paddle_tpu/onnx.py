"""``paddle.onnx`` (reference: ``python/paddle/onnx/export.py`` over
paddle2onnx).

TPU-first: the deployable graph artifact of this framework is serialized
StableHLO (``paddle.jit.save``), which XLA-based runtimes consume
directly. ``export`` always produces that artifact and says so loudly — a true
``.onnx`` conversion is not implemented, and the warning tells the user
exactly what was written and how to serve it."""
from __future__ import annotations

import warnings

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export ``layer``: writes the StableHLO deployable
    (``<path>.pdmodel`` + params, loadable with ``paddle.jit.load`` /
    the inference Predictor) and returns its path, warning that the
    format is StableHLO rather than ONNX."""
    from .jit import save as jit_save
    base = path[:-5] if path.endswith(".onnx") else path
    jit_save(layer, base, input_spec=input_spec)
    warnings.warn(
        "paddle.onnx.export: wrote the StableHLO deployable to "
        f"{base}.pdmodel (load with paddle.jit.load or the inference "
        "Predictor). StableHLO->ONNX conversion is not implemented — "
        "serve the artifact with the XLA runtime.")
    return base + ".pdmodel"
