"""Dense Qwen2 family (reference: PaddleNLP
``paddlenlp/transformers/qwen2/modeling.py`` — ``Qwen2Config``,
``Qwen2Model``, ``Qwen2ForCausalLM``).

Architecturally Qwen2 is the Llama decoder with three deltas: bias on
the q/k/v projections (``qkv_bias=True``), a larger default rope theta
(1e6), and tied embeddings on the small checkpoints. The TPU-first
build shares the Llama module bodies (same GQA attention over the
Pallas flash kernel, same RMSNorm/SwiGLU) and expresses the deltas as
config, so the whole 4D-parallel + generation + serving surface (pp
pipe class, paged-KV continuous-batching decode via
``init_paged_caches``/``block_tables`` — see ``inference/serving.py``)
carries over without re-implementation."""
from __future__ import annotations

from dataclasses import dataclass

from .llama import (LlamaConfig, LlamaForCausalLM, LlamaForCausalLMPipe,
                    LlamaModel, LlamaPretrainingCriterion)

__all__ = ["Qwen2Config", "Qwen2Model", "Qwen2ForCausalLM",
           "Qwen2ForCausalLMPipe", "Qwen2PretrainingCriterion"]


@dataclass
class Qwen2Config(LlamaConfig):
    # Qwen2-7B-shaped defaults (PaddleNLP qwen2 config defaults)
    vocab_size: int = 151936
    hidden_size: int = 3584
    intermediate_size: int = 18944
    num_hidden_layers: int = 28
    num_attention_heads: int = 28
    num_key_value_heads: int = 4
    max_position_embeddings: int = 32768
    rope_theta: float = 1e6
    qkv_bias: bool = True            # THE Qwen2 signature delta

    @staticmethod
    def tiny(vocab=1024, hidden=256, layers=2, heads=8, kv_heads=4,
             ffn=512):
        return Qwen2Config(
            vocab_size=vocab, hidden_size=hidden, intermediate_size=ffn,
            num_hidden_layers=layers, num_attention_heads=heads,
            num_key_value_heads=kv_heads, max_position_embeddings=2048)


class Qwen2Model(LlamaModel):
    """Decoder stack; `qwen2.embed_tokens` etc. via the shared body."""


Qwen2PretrainingCriterion = LlamaPretrainingCriterion


class Qwen2ForCausalLM(LlamaForCausalLM):
    """Causal-LM head over the shared body (config carries the deltas)."""


class Qwen2ForCausalLMPipe(LlamaForCausalLMPipe):
    """Pipeline-parallel Qwen2 (modeling_pp parity via the shared
    shard_map+ppermute engine)."""
