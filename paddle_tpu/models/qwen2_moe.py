"""Qwen2-MoE model family (PaddleNLP ``paddlenlp/transformers/qwen2_moe/
modeling.py`` parity) — BASELINE config 5.

TPU-first expert parallelism: each sparse block holds its experts as
STACKED arrays ``[e, d, m]`` annotated with a PartitionSpec on the expert
mesh axis. Dispatch/combine are the GShard einsums from
``distributed/moe.py``; when the expert dim is mesh-sharded, GSPMD lowers
the dispatch einsum into the all-to-all the reference implements by hand
over its expert ProcessGroup. All shapes static (capacity padding) so the
whole step stays inside one jit.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ..nn.initializer import Normal
from ..nn.layer.layers import Layer
from ..nn.layer.norm import RMSNorm
from ..distributed.fleet.mp_layers import (ColumnParallelLinear,
                                           RowParallelLinear,
                                           VocabParallelEmbedding)
from ..distributed.shard_utils import batch_shard
from ..generation import GenerationMixin
from ..incubate.nn.functional import swiglu
from .llama import (LlamaAttention, LlamaPretrainingCriterion,
                    _rope_tables)

__all__ = ["Qwen2MoeConfig", "Qwen2MoeModel", "Qwen2MoeForCausalLM",
           "StackedExpertsMLP"]


@dataclass
class Qwen2MoeConfig:
    vocab_size: int = 151936
    hidden_size: int = 3584
    intermediate_size: int = 18944          # dense-layer MLP width
    moe_intermediate_size: int = 2560       # per-expert MLP width
    shared_expert_intermediate_size: int = 20480
    num_hidden_layers: int = 28
    num_attention_heads: int = 28
    num_key_value_heads: int = 4
    num_experts: int = 64
    num_experts_per_tok: int = 8
    decoder_sparse_step: int = 1            # every k-th layer is sparse
    norm_topk_prob: bool = False
    router_aux_loss_coef: float = 0.001
    capacity_factor: float = 1.25
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-6
    rope_theta: float = 1000000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    qkv_bias: bool = True                   # the Qwen2 signature detail
    recompute: bool = False
    expert_axis: str = "dp"                 # mesh axis experts shard over
    # dropless routing: no capacity factor, no dropped tokens — experts
    # run as grouped matmuls (megablox on TPU / lax.ragged_dot), inside
    # shard_map with explicit all-to-alls when expert_axis is sharded
    dropless: bool = False
    # EP exchange-slot bound, in multiples of the balanced per-shard
    # load (see moe._dropless_ep); >= the EP degree is exactly dropless
    ep_buffer_factor: float = 2.0
    # fused-dispatch grouped matmuls (ops/pallas/moe_gmm.py): the sort
    # gather rides the first expert matmul's load, swiglu its epilogue,
    # the combine unsort the second's scatter store. False (or
    # PADDLE_TPU_MOE_FUSED_GMM=0) pins the sort->pack->gmm path.
    moe_fused_gmm: bool = True
    dtype: str = "float32"

    @staticmethod
    def tiny(vocab=1024, hidden=128, layers=2, heads=4, kv_heads=2,
             moe_ffn=96, shared_ffn=192, experts=8, topk=2):
        return Qwen2MoeConfig(
            vocab_size=vocab, hidden_size=hidden, intermediate_size=shared_ffn,
            moe_intermediate_size=moe_ffn,
            shared_expert_intermediate_size=shared_ffn,
            num_hidden_layers=layers, num_attention_heads=heads,
            num_key_value_heads=kv_heads, num_experts=experts,
            num_experts_per_tok=topk, max_position_embeddings=512)


class StackedExpertsMLP(Layer):
    """E SwiGLU experts held as stacked ``[e, ...]`` parameters.

    The reference keeps a python list of per-expert Linears and loops /
    all-to-alls between them; on TPU a stacked layout turns the expert
    compute into three batched einsums (one MXU call each) and makes the
    expert dim an ordinary shardable array axis.
    """

    def __init__(self, num_experts, d_model, d_ffn, expert_axis="dp",
                 initializer_range=0.02):
        super().__init__()
        init = Normal(0.0, initializer_range)
        self.num_experts = num_experts
        self.gate_up_proj = self.create_parameter(
            [num_experts, d_model, 2 * d_ffn], default_initializer=init)
        self.down_proj = self.create_parameter(
            [num_experts, d_ffn, d_model], default_initializer=init)
        from ..distributed.shard_utils import annotate_param
        annotate_param(self.gate_up_proj, (expert_axis, None, "mp"))
        annotate_param(self.down_proj, (expert_axis, "mp", None))
        # NOTE: the expert computation itself lives in
        # distributed/moe.py (_expert_swiglu_grouped and the padded
        # fallback's efn) — this Layer only owns the stacked params.


class Qwen2MoeSparseBlock(Layer):
    """Router + stacked routed experts + always-on shared expert."""

    def __init__(self, config: Qwen2MoeConfig):
        super().__init__()
        from ..nn.layer.common import Linear
        self.config = config
        self.gate = Linear(config.hidden_size, config.num_experts,
                           bias_attr=False)
        self.experts = StackedExpertsMLP(
            config.num_experts, config.hidden_size,
            config.moe_intermediate_size, config.expert_axis,
            config.initializer_range)
        self.shared_expert = _DenseMLP(
            config.hidden_size, config.shared_expert_intermediate_size,
            config.initializer_range)
        self.shared_expert_gate = Linear(config.hidden_size, 1,
                                         bias_attr=False)

    def forward(self, x):
        """Returns ``(out, aux_loss)`` — aux travels by value so it
        survives ``jax.checkpoint`` retracing (a value stored on ``self``
        inside the remat trace would leak the inner tracer)."""
        cfg = self.config
        b, l, d = x.shape
        from ..ops.manipulation import reshape
        x2 = reshape(x, [-1, d])
        logits = self.gate(x2)

        collect = getattr(self, "_collect_stats", False)

        def f(x_arr, logit_arr, gate_up, down):
            if getattr(cfg, "dropless", False):
                from ..distributed.moe import \
                    moe_dispatch_combine_dropless
                out = moe_dispatch_combine_dropless(
                    x_arr, logit_arr, cfg.num_experts,
                    cfg.num_experts_per_tok, gate_up, down,
                    normalize_gates=cfg.norm_topk_prob,
                    expert_axis=cfg.expert_axis,
                    ep_buffer_factor=getattr(cfg, "ep_buffer_factor",
                                             2.0),
                    fused=getattr(cfg, "moe_fused_gmm", None),
                    return_stats=collect)
            else:
                # capacity semantics on the grouped-matmul engine
                # (stacked SwiGLU experts; falls back to the padded
                # einsum under an expert-sharded mesh)
                from ..distributed.moe import \
                    moe_dispatch_combine_grouped
                out = moe_dispatch_combine_grouped(
                    x_arr, logit_arr, cfg.num_experts,
                    cfg.num_experts_per_tok, gate_up, down,
                    capacity_factor=cfg.capacity_factor,
                    expert_axis=cfg.expert_axis,
                    normalize_gates=cfg.norm_topk_prob,
                    fused=getattr(cfg, "moe_fused_gmm", None),
                    return_stats=collect)
            if collect:
                y, aux, stats = out
                return y, aux, stats["drop_rate"]
            return out

        if collect:
            y, aux, drop = apply_jax("qwen2_moe_block", f, x2, logits,
                                     self.experts.gate_up_proj,
                                     self.experts.down_proj, n_outputs=3)
            # eager-only diagnostic (a traced value here would be a
            # leaked tracer — use collect_drop_rates(), which runs eager)
            self.drop_rate = drop
        else:
            y, aux = apply_jax("qwen2_moe_block", f, x2, logits,
                               self.experts.gate_up_proj,
                               self.experts.down_proj, n_outputs=2)

        shared = self.shared_expert(x2)
        from ..ops.math import multiply, add
        from ..nn.functional import sigmoid
        sg = sigmoid(self.shared_expert_gate(x2))
        out = add(y, multiply(shared, sg))
        return reshape(out, [b, l, d]), aux


class _DenseMLP(Layer):
    def __init__(self, d_model, d_ffn, initializer_range=0.02):
        super().__init__()
        self.gate_proj = ColumnParallelLinear(
            d_model, d_ffn, has_bias=False, gather_output=False)
        self.up_proj = ColumnParallelLinear(
            d_model, d_ffn, has_bias=False, gather_output=False)
        self.down_proj = RowParallelLinear(
            d_ffn, d_model, has_bias=False, input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(swiglu(self.gate_proj(x), self.up_proj(x)))


# Same GQA attention as Llama; config.qkv_bias=True is the only delta.
Qwen2MoeAttention = LlamaAttention


class Qwen2MoeDecoderLayer(Layer):
    def __init__(self, config: Qwen2MoeConfig, layer_idx: int):
        super().__init__()
        self.self_attn = Qwen2MoeAttention(config)
        sparse = (config.num_experts > 0 and
                  (layer_idx + 1) % config.decoder_sparse_step == 0)
        if sparse:
            self.mlp = Qwen2MoeSparseBlock(config)
        else:
            self.mlp = _DenseMLP(config.hidden_size,
                                 config.intermediate_size,
                                 config.initializer_range)
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)

    def forward(self, hidden_states, rope_cos, rope_sin,
                attention_mask=None, kv_cache=None, offset=None,
                position_ids=None, block_tables=None, cache_lens=None,
                ragged_meta=None):
        """Returns ``(h, aux_loss)`` uniformly (zero aux for dense
        layers) so the remat and non-remat paths carry the router loss
        identically; with ``kv_cache``, ``(h, aux_loss, new_cache)``.
        ``block_tables``/``cache_lens``/``ragged_meta`` select the
        paged / ragged mixed-batch serving attention (vanilla GQA — the
        Llama kernels run unmodified; only the MLP differs, and MoE
        dispatch is per-row, so packed serving rows route exactly like
        a dense batch)."""
        h = self.input_layernorm(hidden_states)
        new_cache = None
        if kv_cache is not None:
            a, new_cache = self.self_attn(h, rope_cos, rope_sin,
                                          attention_mask, kv_cache,
                                          offset,
                                          position_ids=position_ids,
                                          block_tables=block_tables,
                                          cache_lens=cache_lens,
                                          ragged_meta=ragged_meta)
        else:
            a = self.self_attn(h, rope_cos, rope_sin, attention_mask)
        h = hidden_states + a
        h2 = self.post_attention_layernorm(h)
        m = self.mlp(h2)
        if isinstance(m, tuple):
            m, aux = m
        else:
            aux = _wrap_out(jnp.zeros((), jnp.float32))
        if kv_cache is not None:
            return h + m, aux, new_cache
        return h + m, aux


class Qwen2MoeModel(Layer):
    def __init__(self, config: Qwen2MoeConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size)
        from ..nn.layer.container import LayerList
        self.layers = LayerList(
            [Qwen2MoeDecoderLayer(config, i)
             for i in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = _rope_tables(config.max_position_embeddings, head_dim,
                                config.rope_theta)
        self._rope_cos = Tensor(cos)
        self._rope_sin = Tensor(sin)

    def forward(self, input_ids, attention_mask=None, caches=None,
                offset=None, position_ids=None, block_tables=None,
                cache_lens=None, ragged_meta=None):
        """Returns ``(h, total_aux_loss)``; with ``caches``,
        ``(h, total_aux_loss, new_caches)``."""
        input_ids = batch_shard(input_ids)
        h = self.embed_tokens(input_ids)
        if caches is not None:
            new_caches = []
            for layer, kv in zip(self.layers, caches):
                h, _aux, kv2 = layer(h, self._rope_cos, self._rope_sin,
                                     attention_mask, kv_cache=kv,
                                     offset=offset,
                                     position_ids=position_ids,
                                     block_tables=block_tables,
                                     cache_lens=cache_lens,
                                     ragged_meta=ragged_meta)
                new_caches.append(kv2)
            return self.norm(h), None, new_caches
        l = h.shape[1]
        cos = _wrap_out(as_jax(self._rope_cos)[:l])
        sin = _wrap_out(as_jax(self._rope_sin)[:l])
        from ..distributed.recompute import recompute
        from ..ops.math import add
        aux_total = None
        for layer in self.layers:
            if self.config.recompute and self.training:
                h, aux = recompute(layer, h, cos, sin, attention_mask)
            else:
                h, aux = layer(h, cos, sin, attention_mask)
            aux_total = aux if aux_total is None else add(aux_total, aux)
        return self.norm(h), aux_total


class Qwen2MoeForCausalLM(Layer, GenerationMixin):
    def __init__(self, config: Qwen2MoeConfig):
        super().__init__()
        self.config = config
        self.qwen2_moe = Qwen2MoeModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)
        self.criterion = LlamaPretrainingCriterion()

    def _logits(self, h):
        if self.config.tie_word_embeddings:
            from ..ops.linalg import matmul
            return matmul(h, self.qwen2_moe.embed_tokens.weight,
                          transpose_y=True)
        return self.lm_head(h)

    def init_caches(self, batch_size: int, max_length: int):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        dtype = jnp.dtype(getattr(cfg, "dtype", "float32"))
        return [
            (jnp.zeros((batch_size, max_length, cfg.num_key_value_heads,
                        head_dim), dtype),
             jnp.zeros((batch_size, max_length, cfg.num_key_value_heads,
                        head_dim), dtype))
            for _ in range(cfg.num_hidden_layers)
        ]

    def init_paged_caches(self, num_blocks: int, block_size: int,
                          sharding=None, kv_cache_dtype=None):
        """Zeroed per-layer paged (k_pool, v_pool) — the shared serving
        cache (see ``ops/paged_cache.py``); same layout/protocol as
        Llama's, so the serving engine and ``generate(
        cache_impl="paged")`` run MoE unmodified on the attention
        side. ``kv_cache_dtype="int8"``: quantized ``QuantKV``
        pools."""
        from ..ops.paged_cache import init_pool
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        dtype = jnp.dtype(getattr(cfg, "dtype", "float32")) \
            if kv_cache_dtype is None else kv_cache_dtype
        return [
            init_pool(num_blocks, block_size, cfg.num_key_value_heads,
                      head_dim, dtype, sharding=sharding)
            for _ in range(cfg.num_hidden_layers)
        ]

    def forward(self, input_ids, labels=None, attention_mask=None,
                caches=None, offset=None, position_ids=None,
                block_tables=None, cache_lens=None, ragged_meta=None):
        if caches is not None:
            h, _, new_caches = self.qwen2_moe(input_ids, attention_mask,
                                              caches=caches, offset=offset,
                                              position_ids=position_ids,
                                              block_tables=block_tables,
                                              cache_lens=cache_lens,
                                              ragged_meta=ragged_meta)
            return self._logits(h), new_caches
        h, aux_total = self.qwen2_moe(input_ids, attention_mask)
        logits = self._logits(h)
        if labels is None:
            return logits
        loss = self.criterion(logits, labels)
        if aux_total is not None and self.config.router_aux_loss_coef:
            from ..ops.math import add, scale
            loss = add(loss, scale(
                aux_total, self.config.router_aux_loss_coef))
        return loss

    def collect_drop_rates(self, input_ids):
        """Per-sparse-block expert-capacity drop rates for one EAGER
        forward (reference: the MoE stack's capacity-drop telemetry).
        Returns a list of floats, one per sparse block."""
        blocks = [lay.mlp for lay in self.qwen2_moe.layers
                  if isinstance(lay.mlp, Qwen2MoeSparseBlock)]
        for b in blocks:
            b._collect_stats = True
        was_training = self.training
        self.eval()
        try:
            from ..framework.core import no_grad
            with no_grad():                 # diagnostic: no tape
                self(input_ids)
        finally:
            if was_training:
                self.train()
            for b in blocks:
                b._collect_stats = False
        import numpy as np
        out = []
        for b in blocks:
            out.append(float(np.asarray(as_jax(b.drop_rate))))
            b.drop_rate = None              # release the graph/activations
        return out
