"""GPT model family (PaddleNLP ``paddlenlp/transformers/gpt/modeling.py``
parity) with TP annotations."""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ..nn import functional as F
from ..nn.layer.common import Dropout, Embedding
from ..nn.layer.container import LayerList
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm
from ..distributed.fleet.mp_layers import (ColumnParallelLinear,
                                           RowParallelLinear,
                                           VocabParallelEmbedding)
from ..distributed.shard_utils import batch_shard, constraint
from ..generation import GenerationMixin

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPretrainingCriterion"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02

    @staticmethod
    def tiny(vocab=1024, hidden=128, layers=2, heads=4):
        return GPTConfig(vocab_size=vocab, hidden_size=hidden,
                         num_hidden_layers=layers, num_attention_heads=heads,
                         intermediate_size=hidden * 4,
                         max_position_embeddings=512)


class GPTAttention(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_attention_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        self.qkv_proj = ColumnParallelLinear(
            config.hidden_size, 3 * config.hidden_size,
            gather_output=False)
        self.out_proj = RowParallelLinear(
            config.hidden_size, config.hidden_size,
            input_is_parallel=True)
        self.dropout_p = config.attention_probs_dropout_prob

    def forward(self, x, kv_cache=None, offset=None, block_tables=None,
                cache_lens=None, ragged_meta=None):
        b, l, d = x.shape
        qkv = self.qkv_proj(x)

        if kv_cache is not None and block_tables is not None:
            ctx, kv2 = self._attend_serving(qkv, kv_cache,
                                            block_tables, cache_lens,
                                            ragged_meta, b, l, d)
            ctx = constraint(ctx, None, None, "mp")
            return self.out_proj(ctx), kv2

        if kv_cache is not None:
            def attn_c(a, kc, vc, off):
                from .llama import cached_attention
                q, k, v = jnp.split(a, 3, axis=-1)
                qh = q.reshape(b, l, self.num_heads, self.head_dim)
                kh = k.reshape(b, l, self.num_heads, self.head_dim)
                vh = v.reshape(b, l, self.num_heads, self.head_dim)
                out, kc2, vc2 = cached_attention(qh, kh, vh, kc, vc,
                                                 off, self.head_dim)
                return out.reshape(b, l, d), kc2, vc2

            ctx, kc2, vc2 = apply_jax("gpt_attention_cached", attn_c,
                                      qkv, kv_cache[0], kv_cache[1],
                                      offset, n_outputs=3)
            ctx = constraint(ctx, None, None, "mp")
            return self.out_proj(ctx), (kc2, vc2)

        def attn(a):
            q, k, v = jnp.split(a, 3, axis=-1)
            qh = q.reshape(b, l, self.num_heads, self.head_dim)
            kh = k.reshape(b, l, self.num_heads, self.head_dim)
            vh = v.reshape(b, l, self.num_heads, self.head_dim)
            from ..ops.pallas.flash_attention import flash_attention_core
            out = flash_attention_core(qh, kh, vh, is_causal=True)
            return out.reshape(b, l, d)
        ctx = apply_jax("gpt_attention", attn, qkv)
        ctx = constraint(ctx, None, None, "mp")
        return self.out_proj(ctx)

    def _attend_serving(self, qkv, kv_cache, block_tables, cache_lens,
                        ragged_meta, b, l, d):
        """Paged/ragged split + write + attend WITHOUT the output
        projection — the shared core of the serving branches of
        ``forward`` and the fused decode path (which runs the output
        projection inside the fused residual-add epilogue). Returns
        ``(ctx [B, L, D], (k_pool, v_pool))``."""
        if ragged_meta is not None:
            # ragged mixed batch: [1, R] packed rows over the pool
            (q_lens, row_starts, row_slot, row_pos, narrow_iota,
             win_iota) = ragged_meta

            def attn_r(a, kp, vp, tables, lens, ql, rs, sl, pos_r,
                       nwin, win):
                from .llama import ragged_paged_attention_decode
                q, k, v = jnp.split(a, 3, axis=-1)
                r = b * l                        # packed rows (b == 1)
                qh = q.reshape(r, self.num_heads, self.head_dim)
                kh = k.reshape(r, self.num_heads, self.head_dim)
                vh = v.reshape(r, self.num_heads, self.head_dim)
                out, kp2, vp2 = ragged_paged_attention_decode(
                    qh, kh, vh, kp, vp, tables, lens, ql, rs, sl,
                    pos_r, nwin, win, self.head_dim)
                return out.reshape(b, l, d), kp2, vp2

            ctx, kp2, vp2 = apply_jax(
                "gpt_attention_ragged", attn_r, qkv, kv_cache[0],
                kv_cache[1], block_tables, cache_lens, q_lens,
                row_starts, row_slot, row_pos, narrow_iota, win_iota,
                n_outputs=3)
            return ctx, (kp2, vp2)

        # paged decode: kv_cache is the shared (k_pool, v_pool)
        def attn_p(a, kp, vp, tables, lens):
            from .llama import paged_attention_decode
            q, k, v = jnp.split(a, 3, axis=-1)
            qh = q.reshape(b, l, self.num_heads, self.head_dim)
            kh = k.reshape(b, l, self.num_heads, self.head_dim)
            vh = v.reshape(b, l, self.num_heads, self.head_dim)
            out, kp2, vp2 = paged_attention_decode(
                qh, kh, vh, kp, vp, tables, lens, self.head_dim)
            return out.reshape(b, l, d), kp2, vp2

        ctx, kp2, vp2 = apply_jax("gpt_attention_paged", attn_p,
                                  qkv, kv_cache[0], kv_cache[1],
                                  block_tables, cache_lens,
                                  n_outputs=3)
        return ctx, (kp2, vp2)


class GPTDecoderLayer(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size,
                              config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size,
                              config.layer_norm_epsilon)
        self.linear1 = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size,
            gather_output=False)
        self.linear2 = RowParallelLinear(
            config.intermediate_size, config.hidden_size,
            input_is_parallel=True)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def _fused_decode_eligible(self):
        """Fused decode-tick gate (the Llama twin, LayerNorm flavor):
        a serving trace armed the fused scope, the layer is in eval
        mode (the fused epilogues skip the — inert — eval dropout),
        and every weight is a plain float tensor."""
        from ..ops.pallas import decode_fused as _df
        if self.training or _df.fused_decode_mode() is None:
            return False
        return _df.fused_params_ok(
            self.ln_1.weight, self.ln_2.weight,
            getattr(self.attn.qkv_proj, "weight", None),
            getattr(self.attn.out_proj, "weight", None),
            getattr(self.linear1, "weight", None),
            getattr(self.linear2, "weight", None))

    def _forward_decode_fused(self, x, kv_cache, block_tables,
                              cache_lens, ragged_meta):
        """Mega-kernelized GPT decode tick (ISSUE 13): LayerNorm fused
        into the (already single) QKV projection, attention epilogue
        into the output projection + residual add, the second
        LayerNorm into the first MLP linear, and tanh-gelu into the
        second MLP linear + residual add. The XLA fallback is bitwise
        this layer's unfused eval-mode ops."""
        from ..ops.pallas import decode_fused as _df
        from ..ops import lora as _lora
        b, l, d = x.shape
        (qkv,) = _df.norm_matmul(
            x, self.ln_1.weight, self.ln_1.bias,
            [self.attn.qkv_proj.weight], [self.attn.qkv_proj.bias],
            eps=self.ln_1._epsilon, kind="ln")
        if _lora.armed(self.attn.qkv_proj):
            # multi-LoRA serving composes per MODULE (the Llama twin):
            # fused prologue kept, the armed projection adds its
            # ragged grouped-matmul delta off the recomputed norm —
            # bitwise the unfused module path's input, so fused
            # ON==OFF stays token-exact under adapters too
            qkv = _lora.apply(self.attn.qkv_proj, self.ln_1(x), qkv)
        ctx, new_cache = self.attn._attend_serving(
            qkv, kv_cache, block_tables, cache_lens, ragged_meta,
            b, l, d)
        if _lora.armed(self.attn.out_proj):
            # armed epilogue: module call + residual add (the unfused
            # ordering; eval-mode dropout is inert)
            x2 = x + self.attn.out_proj(ctx)
        else:
            x2 = _df.matmul_residual([ctx], self.attn.out_proj.weight,
                                     self.attn.out_proj.bias, x)
        (g,) = _df.norm_matmul(
            x2, self.ln_2.weight, self.ln_2.bias,
            [self.linear1.weight], [self.linear1.bias],
            eps=self.ln_2._epsilon, kind="ln")
        if _lora.armed(self.linear1):
            g = _lora.apply(self.linear1, self.ln_2(x2), g)
        if _lora.armed(self.linear2):
            out = x2 + self.linear2(F.gelu(g, approximate=True))
        else:
            out = _df.matmul_residual([g], self.linear2.weight,
                                      self.linear2.bias, x2,
                                      act="gelu_tanh")
        return out, new_cache

    def forward(self, x, kv_cache=None, offset=None, block_tables=None,
                cache_lens=None, ragged_meta=None):
        if kv_cache is not None and block_tables is not None \
                and self._fused_decode_eligible():
            return self._forward_decode_fused(x, kv_cache,
                                              block_tables, cache_lens,
                                              ragged_meta)
        new_cache = None
        if kv_cache is not None:
            a, new_cache = self.attn(self.ln_1(x), kv_cache, offset,
                                     block_tables=block_tables,
                                     cache_lens=cache_lens,
                                     ragged_meta=ragged_meta)
        else:
            a = self.attn(self.ln_1(x))
        x = x + self.dropout(a)
        h = self.linear2(F.gelu(self.linear1(self.ln_2(x)),
                                approximate=True))
        out = x + self.dropout(h)
        if kv_cache is not None:
            return out, new_cache
        return out


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = VocabParallelEmbedding(config.vocab_size,
                                                 config.hidden_size)
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.h = LayerList([GPTDecoderLayer(config)
                            for _ in range(config.num_hidden_layers)])
        self.ln_f = LayerNorm(config.hidden_size,
                              config.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None, caches=None,
                offset=None, block_tables=None, cache_lens=None,
                ragged_meta=None):
        input_ids = batch_shard(input_ids)
        l = input_ids.shape[1]
        if position_ids is None:
            if ragged_meta is not None:
                # ragged mixed batch: each packed row carries its own
                # absolute position (pad rows clamp to the last learned
                # position — their output is discarded and their write
                # null-routed)
                from ..framework.core import _wrap_out as _w
                from ..framework.core import as_jax as _aj
                position_ids = _w(jnp.clip(
                    _aj(ragged_meta[3]).astype(jnp.int32), 0,
                    self.config.max_position_embeddings - 1)[None, :])
            elif cache_lens is not None:
                # paged decode: each slot sits at its own position
                # (window token t of a speculative verify chunk at
                # cache_lens + t)
                from ..framework.core import _wrap_out as _w
                from ..framework.core import as_jax as _aj
                position_ids = _w(
                    _aj(cache_lens).astype(jnp.int32)[:, None]
                    + jnp.arange(l, dtype=jnp.int32)[None, :])
            else:
                from ..ops.creation import arange
                position_ids = arange(l, dtype="int64")
                if offset is not None:
                    position_ids = position_ids + offset
        h = self.embeddings(input_ids) + \
            self.position_embeddings(position_ids)
        h = self.dropout(h)
        if caches is not None:
            new_caches = []
            for layer, kv in zip(self.h, caches):
                h, kv2 = layer(h, kv_cache=kv, offset=offset,
                               block_tables=block_tables,
                               cache_lens=cache_lens,
                               ragged_meta=ragged_meta)
                new_caches.append(kv2)
            return self.ln_f(h), new_caches
        for layer in self.h:
            h = layer(h)
        return self.ln_f(h)


class GPTPretrainingCriterion(Layer):
    """CE over pre-shifted labels (PaddleNLP parity: the dataset shifts;
    ``labels[t]`` targets ``logits[t]``)."""

    def forward(self, logits, labels):
        def f(lg, lb):
            lb = lb.astype(jnp.int32)
            logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
            picked = jnp.take_along_axis(logp, lb[..., None],
                                         axis=-1)[..., 0]
            return -jnp.mean(picked)
        return apply_jax("gpt_ce", f, logits, labels)


class GPTForCausalLM(Layer, GenerationMixin):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        self.criterion = GPTPretrainingCriterion()

    def init_caches(self, batch_size: int, max_length: int):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        return [
            (jnp.zeros((batch_size, max_length, cfg.num_attention_heads,
                        head_dim), jnp.float32),
             jnp.zeros((batch_size, max_length, cfg.num_attention_heads,
                        head_dim), jnp.float32))
            for _ in range(cfg.num_hidden_layers)
        ]

    def init_paged_caches(self, num_blocks: int, block_size: int,
                          sharding=None, kv_cache_dtype=None):
        """Per-layer paged (k_pool, v_pool) for serving (MHA: kv head
        count equals the query head count). ``sharding``: the
        tensor-parallel kv_head split (``pool_sharding(mesh)``);
        ``kv_cache_dtype="int8"``: quantized ``QuantKV`` pools."""
        from ..ops.paged_cache import init_pool
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        dtype = jnp.float32 if kv_cache_dtype is None \
            else kv_cache_dtype
        return [
            init_pool(num_blocks, block_size, cfg.num_attention_heads,
                      head_dim, dtype, sharding=sharding)
            for _ in range(cfg.num_hidden_layers)
        ]

    def forward(self, input_ids, labels=None, caches=None, offset=None,
                block_tables=None, cache_lens=None, ragged_meta=None,
                return_hidden=False):
        from ..ops.linalg import matmul
        if caches is not None:
            h, new_caches = self.gpt(input_ids, caches=caches,
                                     offset=offset,
                                     block_tables=block_tables,
                                     cache_lens=cache_lens,
                                     ragged_meta=ragged_meta)
            logits = matmul(h, self.gpt.embeddings.weight,
                            transpose_y=True)
            if return_hidden:
                return (logits, h), new_caches
            return logits, new_caches
        h = self.gpt(input_ids)
        logits = matmul(h, self.gpt.embeddings.weight, transpose_y=True)
        if labels is not None:
            return self.criterion(logits, labels)
        return logits
