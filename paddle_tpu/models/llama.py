"""Llama model family (PaddleNLP ``paddlenlp/transformers/llama/
modeling.py`` parity) — BASELINE config 4 flagship.

TPU-first 4D parallel layout:
  - TP: q/k/v/gate/up projections are ColumnParallel, o/down are
    RowParallel, embeddings VocabParallel — all via PartitionSpec
    annotations on the ``mp`` mesh axis (GSPMD inserts the collectives).
  - SP (Megatron): activation constraints on the seq dim when
    ``sequence_parallel=True``.
  - SEP: when the ``sep`` axis is >1, attention runs Ulysses all-to-all
    head<->seq reshuffles (``distributed/sep_parallel.py``, the default)
    or the ppermute ring (``distributed/ring_attention.py``), selected
    by ``hybrid_configs["sep_mechanism"]``.
  - DP/sharding: batch dim constraint + fsdp param specs (stage 3).
  - PP: homogeneous decoder layers — pipelined via
    ``distributed/pipeline.py`` through ``LlamaForCausalLMPipe``.
  - remat: per-decoder-layer jax.checkpoint when config.recompute.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer.layers import Layer
from ..nn.layer.norm import RMSNorm
from ..distributed.fleet.mp_layers import (ColumnParallelLinear,
                                           RowParallelLinear,
                                           VocabParallelEmbedding)
from ..distributed.shard_utils import batch_shard, constraint, \
    mesh_axis_size
from ..generation import GenerationMixin
from ..incubate.nn.functional import (fused_rotary_position_embedding,
                                      swiglu)

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "LlamaForCausalLMPipe", "LlamaPretrainingCriterion"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    sequence_parallel: bool = False
    recompute: bool = False
    # remat every k-th decoder layer (reference fleet
    # ``recompute_interval``): k=1 remats all layers; k=2 halves the
    # recompute FLOPs at ~2x the activation memory — the knob that keeps
    # deep stacks above 0.65 MFU
    recompute_interval: int = 1
    use_flash_attention: bool = True
    dtype: str = "float32"

    @staticmethod
    def llama3_8b():
        return LlamaConfig(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32,
            num_key_value_heads=8, max_position_embeddings=8192,
            rope_theta=500000.0)

    @staticmethod
    def tiny(vocab=1024, hidden=256, layers=2, heads=8, kv_heads=4,
             ffn=512):
        return LlamaConfig(
            vocab_size=vocab, hidden_size=hidden, intermediate_size=ffn,
            num_hidden_layers=layers, num_attention_heads=heads,
            num_key_value_heads=kv_heads, max_position_embeddings=2048)


def _rope_tables(seq_len, head_dim, theta):
    pos = np.arange(seq_len, dtype=np.float32)
    inv = theta ** (-np.arange(0, head_dim, 2,
                               dtype=np.float32) / head_dim)
    freqs = np.outer(pos, inv)
    return np.cos(freqs), np.sin(freqs)


def cached_attention(qh, kh, vh, kc, vc, off, head_dim,
                     extra_bias=None):
    """Shared KV-cache attention step (Llama/GPT families): write this
    chunk's heads [B, L, H', D] into the static cache at ``off``, attend
    q against the full cache under a causal-with-offset mask (plus an
    optional additive ``extra_bias`` broadcastable to [B, H, L, S] —
    e.g. a decode src_mask). Returns (out [B, L, H, D], new_k_cache,
    new_v_cache). GQA: cache holds KV heads; repeat to the query head
    count here."""
    b, l = qh.shape[0], qh.shape[1]
    h = qh.shape[2]
    hkv = kc.shape[2]
    rep = h // hkv
    d = qh.shape[3]
    off = off.astype(jnp.int32) if hasattr(off, "astype") else off
    zero = jnp.zeros((), jnp.int32)
    kc2 = jax.lax.dynamic_update_slice(
        kc, kh.astype(kc.dtype), (zero, off, zero, zero))
    vc2 = jax.lax.dynamic_update_slice(
        vc, vh.astype(vc.dtype), (zero, off, zero, zero))
    S = kc.shape[1]
    rows = off + jnp.arange(l)[:, None]
    cols = jnp.arange(S)[None, :]
    bias = jnp.where(cols <= rows, 0.0, -1e9)[None, None]  # [1,1,L,S]
    if extra_bias is not None:
        pad = S - extra_bias.shape[-1]
        if pad > 0:  # mask covers the live prefix; mask out the tail
            extra_bias = jnp.pad(extra_bias,
                                 [(0, 0)] * (extra_bias.ndim - 1)
                                 + [(0, pad)],
                                 constant_values=-1e9)
        bias = bias + extra_bias                   # [B,H,L,S]
    # GQA WITHOUT materializing the expanded cache: jnp.repeat here
    # would write+read rep x the whole KV cache per decode step (the
    # dominant HBM traffic at small batch); grouping the query heads
    # keeps the cache read once
    q5 = qh.reshape(b, l, hkv, rep, d)
    scores = jnp.einsum(
        "blgrd,bsgd->bgrls", q5, kc2.astype(qh.dtype),
        preferred_element_type=jnp.float32) / math.sqrt(head_dim)
    if bias.shape[1] == h:                # per-head bias (any batch dim)
        bias5 = bias.reshape(bias.shape[0], hkv, rep, l, S)
    else:                                 # broadcast causal mask (H=1)
        bias5 = bias[:, :, None]          # [B|1,1,1,L,S]
    scores = scores + bias5
    w = jax.nn.softmax(scores, axis=-1).astype(qh.dtype)
    out = jnp.einsum("bgrls,bsgd->blgrd", w, vc2.astype(qh.dtype))
    return out.reshape(b, l, h, d), kc2, vc2


def paged_attention_decode(qh, kh, vh, k_pool, v_pool, block_tables,
                           cache_lens, head_dim):
    """Shared paged-KV decode step (Llama/GPT families): write this
    chunk's K/V heads [S, T, H_kv, D] into the shared block pool at
    positions ``cache_lens[s] + t``, then attend q against each slot's
    length-bounded block list through the ragged paged kernel
    (``ops/pallas/paged_attention.py``; gather fallback off-TPU).
    ``T = 1`` is the continuous-batching decode step; ``T > 1`` is
    both the speculative verify window AND the serving engine's
    chunked prefill (``T = prefill_chunk``) — causal within the
    window: token ``t`` sees ``cache_lens[s] + t + 1`` positions,
    which over a prompt chunk starting at ``cache_lens`` IS exact
    causal prefill against the already-cached blocks (including
    blocks mapped from the prefix cache). Only ``generate()``'s
    one-program paged loop still prefills through the dense cached
    path + ``ops.paged_cache.write_prefill``.
    Tensor-parallel serving: inside a TP engine's trace
    (``serving_tp_scope``, a mesh with a live ``mp`` axis, divisible
    head counts) the SAME body runs inside ``shard_map``
    — each shard writes/attends its contiguous kv_head slice of the
    pool, block tables and lengths replicated, no collective inside
    (``ops/pallas/paged_attention.sharded_paged_attention_step``).
    Returns (out [S, T, H, D], new_k_pool, new_v_pool)."""
    from ..ops.pallas.paged_attention import (paged_attention_step,
                                              sharded_paged_attention_step,
                                              tp_shard_degree)
    sm = 1.0 / math.sqrt(head_dim)
    if tp_shard_degree(qh.shape[2], kh.shape[2]) > 1:
        return sharded_paged_attention_step(qh, kh, vh, k_pool, v_pool,
                                            block_tables, cache_lens,
                                            sm_scale=sm)
    return paged_attention_step(qh, kh, vh, k_pool, v_pool,
                                block_tables, cache_lens, sm_scale=sm)


def ragged_paged_attention_decode(qh, kh, vh, k_pool, v_pool,
                                  block_tables, cache_lens, q_lens,
                                  row_starts, row_slot, row_pos,
                                  narrow_iota, win_iota, head_dim):
    """Shared RAGGED mixed-batch step (Llama/GPT families): one packed
    row buffer ``[R, H, D]`` carries every live query row of a serving
    tick — decoding slots (1 row), speculative verify windows
    (gamma+1 rows) and prefill chunks — partitioned by per-slot
    ``q_lens``/``row_starts``; row ``r`` writes and attends at cache
    position ``row_pos[r]`` of slot ``row_slot[r]``. The per-width
    ``paged_attention_decode`` above is the uniform-width special case
    of this step; the serving engine's ONE ragged executable is its
    only caller. Tensor-parallel serving routes the same body through
    ``shard_map`` exactly like the per-width wrapper. Returns
    ``(out [R, H, D], new_k_pool, new_v_pool)``."""
    from ..ops.pallas.paged_attention import (
        ragged_attention_step, sharded_ragged_attention_step,
        tp_shard_degree)
    sm = 1.0 / math.sqrt(head_dim)
    if tp_shard_degree(qh.shape[1], kh.shape[1]) > 1:
        return sharded_ragged_attention_step(
            qh, kh, vh, k_pool, v_pool, block_tables, cache_lens,
            q_lens, row_starts, row_slot, row_pos, narrow_iota,
            win_iota, sm_scale=sm)
    return ragged_attention_step(
        qh, kh, vh, k_pool, v_pool, block_tables, cache_lens, q_lens,
        row_starts, row_slot, row_pos, narrow_iota, win_iota,
        sm_scale=sm)


def _rope_rotate(x, c, s):
    """Shared neox-halves rotation; c/s arrive pre-broadcast against
    [B, L, H, D/2]. Tables stay fp32 for precision; output is cast back
    so bf16 activations remain bf16."""
    d = x.shape[-1]
    xf = x.astype(jnp.float32)
    x1 = xf[..., : d // 2]
    x2 = xf[..., d // 2:]
    c = c.astype(jnp.float32)
    s = s.astype(jnp.float32)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def _apply_rope(x, cos, sin):
    # x: [B, L, H, D]; cos/sin: [L, D/2] (shared positions)
    return _rope_rotate(x, cos[None, :, None, :], sin[None, :, None, :])


def _apply_rope_rows(x, cos, sin):
    """Rope with PER-ROW position tables (left-padded batches: each row
    starts counting positions at its first real token). x: [B, L, H, D];
    cos/sin: [B, L, D/2]."""
    return _rope_rotate(x, cos[:, :, None, :], sin[:, :, None, :])


class LlamaAttention(Layer):
    """GQA attention, shared by the Llama/Qwen2-MoE/DeepSeek families —
    ``config.qkv_bias`` (default False) is the only signature difference
    between them (Qwen2 adds bias to q/k/v)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.hidden_size = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        init = Normal(0.0, config.initializer_range)
        qkv_bias = getattr(config, "qkv_bias", False)
        self.q_proj = ColumnParallelLinear(
            self.hidden_size, self.num_heads * self.head_dim,
            weight_attr=None, has_bias=qkv_bias, gather_output=False)
        self.k_proj = ColumnParallelLinear(
            self.hidden_size, self.num_kv_heads * self.head_dim,
            has_bias=qkv_bias, gather_output=False)
        self.v_proj = ColumnParallelLinear(
            self.hidden_size, self.num_kv_heads * self.head_dim,
            has_bias=qkv_bias, gather_output=False)
        self.o_proj = RowParallelLinear(
            self.num_heads * self.head_dim, self.hidden_size,
            has_bias=False, input_is_parallel=True)

    def forward(self, hidden_states, rope_cos, rope_sin,
                attention_mask=None, kv_cache=None, offset=None,
                position_ids=None, block_tables=None, cache_lens=None,
                ragged_meta=None):
        b, l, _ = hidden_states.shape
        q = self.q_proj(hidden_states)
        k = self.k_proj(hidden_states)
        v = self.v_proj(hidden_states)

        if kv_cache is not None and block_tables is not None \
                and ragged_meta is not None:
            # ragged mixed batch: [1, R] packed rows over the pool
            return self._forward_ragged(q, k, v, rope_cos, rope_sin,
                                        kv_cache, block_tables,
                                        cache_lens, ragged_meta, b, l)
        if kv_cache is not None and block_tables is not None:
            # paged decode: kv_cache is the shared (k_pool, v_pool)
            return self._forward_paged(q, k, v, rope_cos, rope_sin,
                                       kv_cache, block_tables,
                                       cache_lens, b, l)
        if kv_cache is not None:
            # attention_mask here is the [B, S] cache-length pad mask
            # (left-padded batches); position_ids [B, L] give each row
            # its own rope positions
            return self._forward_cached(q, k, v, rope_cos, rope_sin,
                                        kv_cache, offset, b, l,
                                        attention_mask=attention_mask,
                                        position_ids=position_ids)

        def attn(q_a, k_a, v_a, cos, sin):
            qh = q_a.reshape(b, l, self.num_heads, self.head_dim)
            kh = k_a.reshape(b, l, self.num_kv_heads, self.head_dim)
            vh = v_a.reshape(b, l, self.num_kv_heads, self.head_dim)
            qh = _apply_rope(qh, cos, sin)
            kh = _apply_rope(kh, cos, sin)
            from ..distributed.shard_utils import in_manual_region
            if mesh_axis_size("sep") > 1 and not in_manual_region():
                from ..distributed.sep_parallel import sep_attention
                rep = self.num_heads // self.num_kv_heads
                kh = jnp.repeat(kh, rep, axis=2)
                vh = jnp.repeat(vh, rep, axis=2)
                out = sep_attention(qh, kh, vh, causal=True)
            else:
                from ..ops.pallas.flash_attention import \
                    flash_attention_core
                # grouped kv heads pass through unexpanded — the Pallas
                # kernel routes each query head to its kv group via the
                # BlockSpec index map (the XLA fallback repeats inside)
                out = flash_attention_core(qh, kh, vh, is_causal=True)
            return out.reshape(b, l, self.num_heads * self.head_dim)

        ctx = apply_jax("llama_attention", attn, q, k, v, rope_cos,
                        rope_sin)
        ctx = constraint(ctx, None, None, "mp")
        return self.o_proj(ctx)

    def _forward_paged(self, q, k, v, rope_cos, rope_sin, kv_cache,
                       block_tables, cache_lens, b, l):
        """Continuous-batching decode attention over the paged block
        pool: per-slot rope positions come from ``cache_lens`` (each
        slot sits at its own sequence position; window token ``t`` of a
        speculative verify chunk at ``cache_lens + t``), the K/V write
        and the ragged attention run through
        ``paged_attention_decode``."""
        ctx, kp2, vp2 = self._attend_paged(q, k, v, rope_cos, rope_sin,
                                           kv_cache, block_tables,
                                           cache_lens, b, l)
        ctx = constraint(ctx, None, None, "mp")
        return self.o_proj(ctx), (kp2, vp2)

    def _attend_paged(self, q, k, v, rope_cos, rope_sin, kv_cache,
                      block_tables, cache_lens, b, l):
        """Rope + pool write + ragged paged attention WITHOUT the
        O-projection — the shared core of ``_forward_paged`` and the
        fused decode path (which runs the O-projection inside the
        fused residual-add epilogue). Returns ``(ctx [B, L, H*D],
        k_pool, v_pool)``."""

        def attn_p(q_a, k_a, v_a, cos_t, sin_t, kp, vp, tables, lens):
            qh = q_a.reshape(b, l, self.num_heads, self.head_dim)
            kh = k_a.reshape(b, l, self.num_kv_heads, self.head_dim)
            vh = v_a.reshape(b, l, self.num_kv_heads, self.head_dim)
            pos = lens.astype(jnp.int32)[:, None] \
                + jnp.arange(l, dtype=jnp.int32)[None, :]   # [S, L]
            cos = cos_t[pos]                             # [S, L, D/2]
            sin = sin_t[pos]
            qh = _apply_rope_rows(qh, cos, sin)
            kh = _apply_rope_rows(kh, cos, sin)
            out, kp2, vp2 = paged_attention_decode(
                qh, kh, vh, kp, vp, tables, lens, self.head_dim)
            return (out.reshape(b, l, self.num_heads * self.head_dim),
                    kp2, vp2)

        return apply_jax(
            "llama_attention_paged", attn_p, q, k, v, rope_cos, rope_sin,
            kv_cache[0], kv_cache[1], block_tables, cache_lens,
            n_outputs=3)

    def _forward_ragged(self, q, k, v, rope_cos, rope_sin, kv_cache,
                        block_tables, cache_lens, ragged_meta, b, l):
        """Ragged mixed-batch attention: the hidden states arrive as
        ONE packed row buffer ``[1, R, hidden]`` (decode rows, verify
        windows and prefill chunks of every slot, concatenated); rope
        positions come per ROW (``row_pos`` — pad rows carry an
        overflow position whose clamped rope garbage never survives
        the null-routed write), and the write+attend runs through
        ``ragged_paged_attention_decode``."""
        ctx, kp2, vp2 = self._attend_ragged(q, k, v, rope_cos,
                                            rope_sin, kv_cache,
                                            block_tables, cache_lens,
                                            ragged_meta, b, l)
        ctx = constraint(ctx, None, None, "mp")
        return self.o_proj(ctx), (kp2, vp2)

    def _attend_ragged(self, q, k, v, rope_cos, rope_sin, kv_cache,
                       block_tables, cache_lens, ragged_meta, b, l):
        """Per-row rope + scatter + ragged attention WITHOUT the
        O-projection — the shared core of ``_forward_ragged`` and the
        fused decode path. Returns ``(ctx [B, L, H*D], k_pool,
        v_pool)``."""
        (q_lens, row_starts, row_slot, row_pos, narrow_iota,
         win_iota) = ragged_meta

        def attn_r(q_a, k_a, v_a, cos_t, sin_t, kp, vp, tables, lens,
                   ql, rs, sl, pos_r, nwin, win):
            r = b * l                       # packed rows (b == 1)
            qh = q_a.reshape(r, self.num_heads, self.head_dim)
            kh = k_a.reshape(r, self.num_kv_heads, self.head_dim)
            vh = v_a.reshape(r, self.num_kv_heads, self.head_dim)
            pos = jnp.clip(pos_r.astype(jnp.int32), 0,
                           cos_t.shape[0] - 1)            # [R]
            cos = cos_t[pos]                              # [R, D/2]
            sin = sin_t[pos]
            qh = _rope_rotate(qh, cos[:, None, :], sin[:, None, :])
            kh = _rope_rotate(kh, cos[:, None, :], sin[:, None, :])
            out, kp2, vp2 = ragged_paged_attention_decode(
                qh, kh, vh, kp, vp, tables, lens, ql, rs, sl, pos_r,
                nwin, win, self.head_dim)
            return (out.reshape(b, l, self.num_heads * self.head_dim),
                    kp2, vp2)

        return apply_jax(
            "llama_attention_ragged", attn_r, q, k, v, rope_cos,
            rope_sin, kv_cache[0], kv_cache[1], block_tables,
            cache_lens, q_lens, row_starts, row_slot, row_pos,
            narrow_iota, win_iota, n_outputs=3)

    def _forward_cached(self, q, k, v, rope_cos, rope_sin, kv_cache,
                        offset, b, l, attention_mask=None,
                        position_ids=None):
        """Incremental-decode attention: write this chunk's K/V into the
        static-shape cache at ``offset`` and attend against the full
        cache under a causal-with-offset mask (KV-cache decode path —
        reference: PaddleNLP generation with ``cache_kvs``). rope tables
        arrive un-sliced; ``offset`` is a traced int32 scalar so one
        compiled program serves every decode step. Left-padded batches:
        ``attention_mask`` [B, S] masks pad cache slots and
        ``position_ids`` [B, L] give per-row rope positions."""
        with_rows = position_ids is not None
        with_mask = attention_mask is not None

        def attn_c(q_a, k_a, v_a, cos_t, sin_t, kc, vc, off, *rest):
            qh = q_a.reshape(b, l, self.num_heads, self.head_dim)
            kh = k_a.reshape(b, l, self.num_kv_heads, self.head_dim)
            vh = v_a.reshape(b, l, self.num_kv_heads, self.head_dim)
            off32 = off.astype(jnp.int32) if hasattr(off, "astype") \
                else off
            rest = list(rest)
            if with_rows:
                pos = rest.pop(0).astype(jnp.int32)     # [B, L]
                cos = cos_t[pos]                        # [B, L, D/2]
                sin = sin_t[pos]
                qh = _apply_rope_rows(qh, cos, sin)
                kh = _apply_rope_rows(kh, cos, sin)
            else:
                cos = jax.lax.dynamic_slice_in_dim(cos_t, off32, l, 0)
                sin = jax.lax.dynamic_slice_in_dim(sin_t, off32, l, 0)
                qh = _apply_rope(qh, cos, sin)
                kh = _apply_rope(kh, cos, sin)
            extra = None
            if with_mask:
                m = rest.pop(0)                         # [B, S]
                extra = jnp.where(m > 0, 0.0, -1e9)[:, None, None, :]
            out, kc2, vc2 = cached_attention(qh, kh, vh, kc, vc, off32,
                                             self.head_dim,
                                             extra_bias=extra)
            return (out.reshape(b, l, self.num_heads * self.head_dim),
                    kc2, vc2)

        extras = []
        if with_rows:
            extras.append(position_ids)
        if with_mask:
            extras.append(attention_mask)
        ctx, kc2, vc2 = apply_jax(
            "llama_attention_cached", attn_c, q, k, v, rope_cos, rope_sin,
            kv_cache[0], kv_cache[1], offset, *extras, n_outputs=3)
        ctx = constraint(ctx, None, None, "mp")
        return self.o_proj(ctx), (kc2, vc2)


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.gate_proj = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size, has_bias=False,
            gather_output=False)
        self.up_proj = ColumnParallelLinear(
            config.hidden_size, config.intermediate_size, has_bias=False,
            gather_output=False)
        self.down_proj = RowParallelLinear(
            config.intermediate_size, config.hidden_size, has_bias=False,
            input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)

    def _fused_decode_eligible(self):
        """Fused decode-tick path gate: a serving trace armed the
        fused scope (``ops/pallas/decode_fused`` — engine kill switch,
        config flag, GSPMD-TP exclusion all fold into the mode) and
        every weight the fused kernels would consume is a plain float
        tensor (weight-only int8 layers keep the module path)."""
        from ..ops.pallas import decode_fused as _df
        if _df.fused_decode_mode() is None:
            return False
        attn, mlp = self.self_attn, self.mlp
        return _df.fused_params_ok(
            self.input_layernorm.weight,
            self.post_attention_layernorm.weight,
            getattr(attn.q_proj, "weight", None),
            getattr(attn.k_proj, "weight", None),
            getattr(attn.v_proj, "weight", None),
            getattr(attn.o_proj, "weight", None),
            getattr(mlp.gate_proj, "weight", None),
            getattr(mlp.up_proj, "weight", None),
            getattr(mlp.down_proj, "weight", None))

    def _forward_decode_fused(self, hidden_states, rope_cos, rope_sin,
                              kv_cache, block_tables, cache_lens,
                              ragged_meta):
        """Mega-kernelized decode tick (ISSUE 13): the four per-layer
        fusion boundaries closed — RMSNorm fused into the QKV
        projection prologue, the attention epilogue into the
        O-projection + residual add, the post-attention RMSNorm into
        the gate/up prologue, and swiglu into the down-projection +
        residual add — via ``ops/pallas/decode_fused``. Per-layer
        activations stay in VMEM across every boundary on TPU; the
        XLA fallback is bitwise this layer's unfused ops, so CPU
        engines with fusion ON compile today's graph unchanged."""
        from ..ops.pallas import decode_fused as _df
        from ..ops import lora as _lora
        attn = self.self_attn
        b, l, _ = hidden_states.shape
        eps = self.input_layernorm._epsilon
        q, k, v = _df.norm_matmul(
            hidden_states, self.input_layernorm.weight, None,
            [attn.q_proj.weight, attn.k_proj.weight,
             attn.v_proj.weight],
            [attn.q_proj.bias, attn.k_proj.bias, attn.v_proj.bias],
            eps=eps, kind="rms")
        if _lora.armed(attn.q_proj) or _lora.armed(attn.k_proj) \
                or _lora.armed(attn.v_proj):
            # multi-LoRA serving composes per MODULE: the fused
            # prologue stays; the armed projections add their ragged
            # grouped-matmul delta off the recomputed norm (bitwise
            # the norm the unfused module path feeds them, so fused
            # ON==OFF stays token-exact under adapters too)
            hn = self.input_layernorm(hidden_states)
            q = _lora.apply(attn.q_proj, hn, q)
            k = _lora.apply(attn.k_proj, hn, k)
            v = _lora.apply(attn.v_proj, hn, v)
        if ragged_meta is not None:
            ctx, kp2, vp2 = attn._attend_ragged(
                q, k, v, rope_cos, rope_sin, kv_cache, block_tables,
                cache_lens, ragged_meta, b, l)
        else:
            ctx, kp2, vp2 = attn._attend_paged(
                q, k, v, rope_cos, rope_sin, kv_cache, block_tables,
                cache_lens, b, l)
        if _lora.armed(attn.o_proj):
            # an armed epilogue falls back to module call + residual
            # add (the unfused ordering — module forward applies the
            # delta), keeping the prologue fusions above
            h = hidden_states + attn.o_proj(ctx)
        else:
            h = _df.matmul_residual([ctx], attn.o_proj.weight,
                                    attn.o_proj.bias, hidden_states)
        mlp = self.mlp
        g, u = _df.norm_matmul(
            h, self.post_attention_layernorm.weight, None,
            [mlp.gate_proj.weight, mlp.up_proj.weight], [None, None],
            eps=self.post_attention_layernorm._epsilon, kind="rms")
        if _lora.armed(mlp.gate_proj) or _lora.armed(mlp.up_proj):
            hn2 = self.post_attention_layernorm(h)
            g = _lora.apply(mlp.gate_proj, hn2, g)
            u = _lora.apply(mlp.up_proj, hn2, u)
        if _lora.armed(mlp.down_proj):
            out = h + mlp.down_proj(swiglu(g, u))
        else:
            out = _df.matmul_residual([g, u], mlp.down_proj.weight,
                                      mlp.down_proj.bias, h,
                                      act="swiglu")
        return out, (kp2, vp2)

    def forward(self, hidden_states, rope_cos, rope_sin,
                attention_mask=None, kv_cache=None, offset=None,
                position_ids=None, block_tables=None, cache_lens=None,
                ragged_meta=None):
        if kv_cache is not None and block_tables is not None \
                and self._fused_decode_eligible():
            return self._forward_decode_fused(
                hidden_states, rope_cos, rope_sin, kv_cache,
                block_tables, cache_lens, ragged_meta)
        residual = hidden_states
        h = self.input_layernorm(hidden_states)
        new_cache = None
        if kv_cache is not None:
            h, new_cache = self.self_attn(h, rope_cos, rope_sin,
                                          attention_mask, kv_cache, offset,
                                          position_ids=position_ids,
                                          block_tables=block_tables,
                                          cache_lens=cache_lens,
                                          ragged_meta=ragged_meta)
        else:
            h = self.self_attn(h, rope_cos, rope_sin, attention_mask)
            # tag for the "save_attn" selective remat policy: keep the
            # attention output, replay only norms/MLP in backward
            from jax.ad_checkpoint import checkpoint_name
            h = apply_jax("attn_out_tag",
                          lambda a: checkpoint_name(a, "attn_out"), h)
        h = residual + h
        residual = h
        h2 = self.post_attention_layernorm(h)
        h2 = self.mlp(h2)
        out = residual + h2
        if kv_cache is not None:
            return out, new_cache
        return out


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size)
        from ..nn.layer.container import LayerList
        self.layers = LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = _rope_tables(config.max_position_embeddings, head_dim,
                                config.rope_theta)
        self._rope_cos = Tensor(cos)
        self._rope_sin = Tensor(sin)

    def forward(self, input_ids, attention_mask=None, position_ids=None,
                caches=None, offset=None, block_tables=None,
                cache_lens=None, ragged_meta=None):
        input_ids = batch_shard(input_ids)
        h = self.embed_tokens(input_ids)
        if caches is not None:
            # decode path: full rope tables + per-layer kv caches
            # (dense [B, S, H, D] pairs, or — with block_tables — the
            # shared paged (k_pool, v_pool) per layer; with
            # ragged_meta, ONE packed mixed-batch row buffer)
            cos, sin = self._rope_cos, self._rope_sin
            new_caches = []
            for layer, kv in zip(self.layers, caches):
                h, kv2 = layer(h, cos, sin, attention_mask,
                               kv_cache=kv, offset=offset,
                               position_ids=position_ids,
                               block_tables=block_tables,
                               cache_lens=cache_lens,
                               ragged_meta=ragged_meta)
                new_caches.append(kv2)
            return self.norm(h), new_caches
        l = h.shape[1]
        cos = _wrap_out(as_jax(self._rope_cos)[:l])
        sin = _wrap_out(as_jax(self._rope_sin)[:l])
        from ..distributed.recompute import recompute
        interval = max(getattr(self.config, "recompute_interval", 1), 1)
        for i, layer in enumerate(self.layers):
            if self.config.recompute and self.training \
                    and i % interval == 0:
                h = recompute(layer, h, cos, sin, attention_mask)
            else:
                h = layer(h, cos, sin, attention_mask)
        return self.norm(h)


class LlamaPretrainingCriterion(Layer):
    """Masked cross entropy over pre-shifted labels (PaddleNLP
    ``LlamaPretrainingCriterion`` parity: the DATASET shifts —
    ``labels[t]`` is the target for ``logits[t]``; the criterion never
    shifts internally. Round-3 fix: the previous internal shift made
    ported reference scripts silently train on t+2 targets)."""

    def __init__(self, config: LlamaConfig = None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        def f(lg, lb):
            # CE via explicit row logsumexp instead of log_softmax: a
            # full log_softmax materializes TWO [B, L, V] f32 arrays
            # (~1 GB each at the bench shapes) where only [B, L] row
            # stats are needed. The max runs on the input dtype and the
            # f32 upcast happens on (lg - m), whose ONLY consumer is the
            # exp-sum reduction — XLA fuses it into the reduce, so no
            # vocab-size f32 array ever reaches HBM.
            m = jax.lax.stop_gradient(
                jnp.max(lg, axis=-1, keepdims=True))
            zs = (lg - m).astype(jnp.float32)
            lse = m[..., 0].astype(jnp.float32) + jnp.log(
                jnp.sum(jnp.exp(zs), axis=-1))
            lb_i = lb.astype(jnp.int32)
            picked = jnp.take_along_axis(
                lg, jnp.clip(lb_i, 0)[..., None],
                axis=-1)[..., 0].astype(jnp.float32)
            valid = lb_i != self.ignore_index
            loss = jnp.where(valid, lse - picked, 0.0)
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(jnp.float32)), 1.0)
        return apply_jax("llama_ce", f, logits, labels)


class LlamaForCausalLM(Layer, GenerationMixin):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)
        self.criterion = LlamaPretrainingCriterion(config)

    def forward(self, input_ids, labels=None, attention_mask=None,
                position_ids=None, caches=None, offset=None,
                block_tables=None, cache_lens=None, ragged_meta=None,
                return_hidden=False):
        if caches is not None:
            h, new_caches = self.llama(input_ids, attention_mask,
                                       position_ids, caches=caches,
                                       offset=offset,
                                       block_tables=block_tables,
                                       cache_lens=cache_lens,
                                       ragged_meta=ragged_meta)
            if return_hidden:
                return (self._head_and_loss(h, None), h), new_caches
            return self._head_and_loss(h, None), new_caches
        h = self.llama(input_ids, attention_mask, position_ids)
        return self._head_and_loss(h, labels)

    def init_caches(self, batch_size: int, max_length: int):
        """Zeroed per-layer (k, v) caches [B, S, H_kv, D] for decode."""
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        dtype = jnp.dtype(cfg.dtype)
        return [
            (jnp.zeros((batch_size, max_length, cfg.num_key_value_heads,
                        head_dim), dtype),
             jnp.zeros((batch_size, max_length, cfg.num_key_value_heads,
                        head_dim), dtype))
            for _ in range(cfg.num_hidden_layers)
        ]

    def init_paged_caches(self, num_blocks: int, block_size: int,
                          sharding=None, kv_cache_dtype=None):
        """Zeroed per-layer paged (k_pool, v_pool), each
        [num_blocks, block_size, H_kv, D] — the shared serving cache
        (block 0 is the null block; see ``ops/paged_cache.py``).
        ``sharding``: tensor-parallel pool placement (normally
        ``ops.paged_cache.pool_sharding(mesh)`` — the kv_head split),
        so each shard materializes only its slice. ``kv_cache_dtype``:
        ``"int8"`` builds quantized ``QuantKV`` pools (int8 data +
        per-(block, position, head) absmax scales); None keeps the
        model dtype — bit-for-bit the pre-quantization layout."""
        from ..ops.paged_cache import init_pool
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        dtype = jnp.dtype(cfg.dtype) if kv_cache_dtype is None \
            else kv_cache_dtype
        return [
            init_pool(num_blocks, block_size, cfg.num_key_value_heads,
                      head_dim, dtype, sharding=sharding)
            for _ in range(cfg.num_hidden_layers)
        ]

    def _head_and_loss(self, h, labels):
        if self.config.tie_word_embeddings:
            from ..ops.linalg import matmul
            logits = matmul(h, self.llama.embed_tokens.weight,
                            transpose_y=True)
        else:
            logits = self.lm_head(h)
        if labels is not None:
            return self.criterion(logits, labels)
        return logits


class LlamaForCausalLMPipe(LlamaForCausalLM):
    """Pipeline-parallel Llama (``LlamaForCausalLMPipe`` parity —
    PaddleNLP ``llama/modeling_pp.py`` over fleet
    ``meta_parallel/pipeline_parallel.py``'s 1F1B schedule).

    TPU-first schedule: the homogeneous decoder stack runs through the
    shard_map + ppermute scan pipeline (``distributed/pipeline.py``);
    the heterogeneous first/last-stage work (embedding, final norm, head,
    loss) executes outside the ring in GSPMD land. Per-microbatch grad
    accumulation and the 1F1B/FThenB bookkeeping of the reference are
    subsumed by differentiating through the scan — the backward ring is
    the transposed ppermute, and XLA overlaps stage compute with the
    permutes. Parameter layout and state_dict are identical to
    ``LlamaForCausalLM`` (same sublayers), so pp=1 checkpoints load
    unchanged and numeric parity is testable layer-for-layer."""

    def __init__(self, config: LlamaConfig, num_micro_batches=None,
                 num_stages=None):
        super().__init__(config)
        self.num_micro_batches = num_micro_batches
        self._num_stages = num_stages

    def forward(self, input_ids, labels=None, attention_mask=None,
                position_ids=None):
        from ..distributed.shard_utils import current_mesh
        mesh = current_mesh()
        pp = self._num_stages or (
            mesh.shape.get("pp", 1) if mesh is not None else 1)
        n_layers = self.config.num_hidden_layers
        if pp <= 1 or mesh is None or mesh.shape.get("pp", 1) <= 1 \
                or n_layers % pp != 0 or attention_mask is not None:
            # attention_mask is not threaded through the pipeline stage
            # function — run the (numerically identical) sequential path
            if attention_mask is not None and pp > 1:
                import warnings
                warnings.warn(
                    "LlamaForCausalLMPipe: attention_mask given; running "
                    "the sequential (non-pipelined) path")
            return super().forward(input_ids, labels, attention_mask,
                                   position_ids)
        lps = n_layers // pp

        core = self.llama
        input_ids = batch_shard(input_ids)
        h = core.embed_tokens(input_ids)
        b, l = h.shape[0], h.shape[1]
        cos = as_jax(core._rope_cos)[:l]
        sin = as_jax(core._rope_sin)[:l]

        n_micro = self.num_micro_batches or pp
        n_micro = min(n_micro, b)
        while b % n_micro != 0:  # static python loop at trace time
            n_micro -= 1

        from ..jit import _LayerBinder
        binder = _LayerBinder(core.layers[0])
        param_tensors = [p for lay in core.layers
                         for _, p in _LayerBinder(lay).param_items]
        n_p = len(binder.param_items)
        recompute = self.config.recompute and self.training

        def one_layer(params_local, x, cos_a, sin_a, i):
            arrs = [p[i] for p in params_local]
            out, _ = binder.call(
                arrs, [], (_wrap_out(x), _wrap_out(cos_a),
                           _wrap_out(sin_a)), {})
            return as_jax(out)

        def stage_fn(params_local, x, cos_a, sin_a):
            f = one_layer
            if recompute:
                f = jax.checkpoint(one_layer, static_argnums=(4,))
            for i in range(lps):
                x = f(params_local, x, cos_a, sin_a, i)
            return x

        from ..distributed.pipeline import pipeline_apply

        def run_pipe(h_a, cos_a, sin_a, *flat):
            per = [flat[k * n_p:(k + 1) * n_p] for k in range(n_layers)]
            # leaves [pp, lps, ...] — stage-major stacking
            stacked = [
                jnp.stack([jnp.stack([per[s * lps + i][j]
                                      for i in range(lps)])
                           for s in range(pp)])
                for j in range(n_p)
            ]
            mbs = h_a.reshape((n_micro, h_a.shape[0] // n_micro)
                              + h_a.shape[1:])
            out = pipeline_apply(stage_fn, stacked, mbs, mesh=mesh,
                                 extra_inputs=(cos_a, sin_a))
            return out.reshape(h_a.shape)

        h = apply_jax("llama_pipeline", run_pipe, h,
                      _wrap_out(cos), _wrap_out(sin), *param_tensors)
        h = core.norm(h)
        return self._head_and_loss(h, labels)
