"""DeepSeek-MoE model family (PaddleNLP ``paddlenlp/transformers/
deepseek_v2/modeling.py`` fine-grained-expert lineage) — BASELINE
config 5 second entry.

Architecture signatures vs Qwen2-MoE: the first ``first_k_dense_replace``
layers use a dense MLP; sparse layers combine fine-grained routed experts
(softmax-then-topk scoring, optionally normalized) with
``n_shared_experts`` always-on shared experts added UNGATED to the routed
output. Expert storage/dispatch reuses the stacked-expert einsum path
(``qwen2_moe.StackedExpertsMLP`` + ``distributed/moe.py``) so expert
parallelism is a mesh-axis sharding, not hand-coded all-to-alls.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ..nn.layer.layers import Layer
from ..nn.layer.norm import RMSNorm
from ..distributed.fleet.mp_layers import (ColumnParallelLinear,
                                           VocabParallelEmbedding)
from ..distributed.shard_utils import batch_shard
from ..generation import GenerationMixin
from .llama import (LlamaAttention, LlamaPretrainingCriterion,
                    _rope_tables)
from .qwen2_moe import StackedExpertsMLP, _DenseMLP

__all__ = ["DeepseekMoeConfig", "DeepseekMoeModel",
           "DeepseekMoeForCausalLM"]


@dataclass
class DeepseekMoeConfig:
    vocab_size: int = 102400
    hidden_size: int = 2048
    intermediate_size: int = 10944          # dense-layer MLP width
    moe_intermediate_size: int = 1408       # fine-grained expert width
    num_hidden_layers: int = 28
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    n_routed_experts: int = 64
    n_shared_experts: int = 2
    num_experts_per_tok: int = 6
    first_k_dense_replace: int = 1
    moe_layer_freq: int = 1
    norm_topk_prob: bool = False
    router_aux_loss_coef: float = 0.001
    capacity_factor: float = 1.25
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    qkv_bias: bool = False                  # DeepSeek attention: no bias
    recompute: bool = False
    expert_axis: str = "dp"
    # dropless grouped-matmul routing (megablox on TPU; EP shard_map
    # fast path when expert_axis is mesh-sharded) vs GShard capacity
    dropless: bool = False
    ep_buffer_factor: float = 2.0
    # fused-dispatch grouped matmuls (ops/pallas/moe_gmm.py); False (or
    # PADDLE_TPU_MOE_FUSED_GMM=0) pins the sort->pack->gmm path
    moe_fused_gmm: bool = True
    dtype: str = "float32"

    @staticmethod
    def tiny(vocab=1024, hidden=128, layers=3, heads=4, kv_heads=4,
             moe_ffn=64, dense_ffn=192, experts=8, shared=2, topk=2):
        return DeepseekMoeConfig(
            vocab_size=vocab, hidden_size=hidden,
            intermediate_size=dense_ffn, moe_intermediate_size=moe_ffn,
            num_hidden_layers=layers, num_attention_heads=heads,
            num_key_value_heads=kv_heads, n_routed_experts=experts,
            n_shared_experts=shared, num_experts_per_tok=topk,
            max_position_embeddings=512)


class DeepseekMoeBlock(Layer):
    """Routed fine-grained experts + ungated shared experts."""

    def __init__(self, config: DeepseekMoeConfig):
        super().__init__()
        from ..nn.layer.common import Linear
        self.config = config
        self.gate = Linear(config.hidden_size, config.n_routed_experts,
                           bias_attr=False)
        self.experts = StackedExpertsMLP(
            config.n_routed_experts, config.hidden_size,
            config.moe_intermediate_size, config.expert_axis,
            config.initializer_range)
        self.shared_experts = _DenseMLP(
            config.hidden_size,
            config.n_shared_experts * config.moe_intermediate_size,
            config.initializer_range)

    def forward(self, x):
        cfg = self.config
        b, l, d = x.shape
        from ..ops.manipulation import reshape
        x2 = reshape(x, [-1, d])
        logits = self.gate(x2)

        def f(x_arr, logit_arr, gate_up, down):
            if getattr(cfg, "dropless", False):
                from ..distributed.moe import \
                    moe_dispatch_combine_dropless
                return moe_dispatch_combine_dropless(
                    x_arr, logit_arr, cfg.n_routed_experts,
                    cfg.num_experts_per_tok, gate_up, down,
                    normalize_gates=cfg.norm_topk_prob,
                    expert_axis=cfg.expert_axis,
                    ep_buffer_factor=getattr(cfg, "ep_buffer_factor",
                                             2.0),
                    fused=getattr(cfg, "moe_fused_gmm", None))
            from ..distributed.moe import moe_dispatch_combine_grouped
            return moe_dispatch_combine_grouped(
                x_arr, logit_arr, cfg.n_routed_experts,
                cfg.num_experts_per_tok, gate_up, down,
                capacity_factor=cfg.capacity_factor,
                expert_axis=cfg.expert_axis,
                normalize_gates=cfg.norm_topk_prob,
                fused=getattr(cfg, "moe_fused_gmm", None))

        y, aux = apply_jax("deepseek_moe_block", f, x2, logits,
                           self.experts.gate_up_proj,
                           self.experts.down_proj, n_outputs=2)
        from ..ops.math import add
        out = add(y, self.shared_experts(x2))
        return reshape(out, [b, l, d]), aux


class DeepseekMoeDecoderLayer(Layer):
    def __init__(self, config: DeepseekMoeConfig, layer_idx: int):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        sparse = (layer_idx >= config.first_k_dense_replace and
                  layer_idx % config.moe_layer_freq == 0)
        if sparse:
            self.mlp = DeepseekMoeBlock(config)
        else:
            self.mlp = _DenseMLP(config.hidden_size,
                                 config.intermediate_size,
                                 config.initializer_range)
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)

    def forward(self, hidden_states, rope_cos, rope_sin,
                attention_mask=None, kv_cache=None, offset=None,
                position_ids=None, block_tables=None, cache_lens=None,
                ragged_meta=None):
        h = self.input_layernorm(hidden_states)
        new_cache = None
        if kv_cache is not None:
            a, new_cache = self.self_attn(h, rope_cos, rope_sin,
                                          attention_mask, kv_cache,
                                          offset,
                                          position_ids=position_ids,
                                          block_tables=block_tables,
                                          cache_lens=cache_lens,
                                          ragged_meta=ragged_meta)
        else:
            a = self.self_attn(h, rope_cos, rope_sin, attention_mask)
        h = hidden_states + a
        h2 = self.post_attention_layernorm(h)
        m = self.mlp(h2)
        if isinstance(m, tuple):
            m, aux = m
        else:
            import jax.numpy as jnp
            aux = _wrap_out(jnp.zeros((), jnp.float32))
        if kv_cache is not None:
            return h + m, aux, new_cache
        return h + m, aux


class DeepseekMoeModel(Layer):
    def __init__(self, config: DeepseekMoeConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size)
        from ..nn.layer.container import LayerList
        self.layers = LayerList(
            [DeepseekMoeDecoderLayer(config, i)
             for i in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = _rope_tables(config.max_position_embeddings, head_dim,
                                config.rope_theta)
        self._rope_cos = Tensor(cos)
        self._rope_sin = Tensor(sin)

    def forward(self, input_ids, attention_mask=None, caches=None,
                offset=None, position_ids=None, block_tables=None,
                cache_lens=None, ragged_meta=None):
        input_ids = batch_shard(input_ids)
        h = self.embed_tokens(input_ids)
        if caches is not None:
            new_caches = []
            for layer, kv in zip(self.layers, caches):
                h, _aux, kv2 = layer(h, self._rope_cos, self._rope_sin,
                                     attention_mask, kv_cache=kv,
                                     offset=offset,
                                     position_ids=position_ids,
                                     block_tables=block_tables,
                                     cache_lens=cache_lens,
                                     ragged_meta=ragged_meta)
                new_caches.append(kv2)
            return self.norm(h), None, new_caches
        l = h.shape[1]
        cos = _wrap_out(as_jax(self._rope_cos)[:l])
        sin = _wrap_out(as_jax(self._rope_sin)[:l])
        from ..distributed.recompute import recompute
        from ..ops.math import add
        aux_total = None
        for layer in self.layers:
            if self.config.recompute and self.training:
                h, aux = recompute(layer, h, cos, sin, attention_mask)
            else:
                h, aux = layer(h, cos, sin, attention_mask)
            aux_total = aux if aux_total is None else add(aux_total, aux)
        return self.norm(h), aux_total


class DeepseekMoeForCausalLM(Layer, GenerationMixin):
    def __init__(self, config: DeepseekMoeConfig):
        super().__init__()
        self.config = config
        self.deepseek = DeepseekMoeModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)
        self.criterion = LlamaPretrainingCriterion()

    def _logits(self, h):
        if self.config.tie_word_embeddings:
            from ..ops.linalg import matmul
            return matmul(h, self.deepseek.embed_tokens.weight,
                          transpose_y=True)
        return self.lm_head(h)

    def init_caches(self, batch_size: int, max_length: int):
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        import jax.numpy as jnp
        dtype = jnp.dtype(getattr(cfg, "dtype", "float32"))
        return [
            (jnp.zeros((batch_size, max_length, cfg.num_key_value_heads,
                        head_dim), dtype),
             jnp.zeros((batch_size, max_length, cfg.num_key_value_heads,
                        head_dim), dtype))
            for _ in range(cfg.num_hidden_layers)
        ]

    def init_paged_caches(self, num_blocks: int, block_size: int,
                          sharding=None, kv_cache_dtype=None):
        """Zeroed per-layer paged (k_pool, v_pool) — the shared serving
        cache layout (see ``ops/paged_cache.py``), identical protocol
        to Llama/Qwen2-MoE. ``kv_cache_dtype="int8"``: quantized
        ``QuantKV`` pools."""
        from ..ops.paged_cache import init_pool
        import jax.numpy as jnp
        cfg = self.config
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        dtype = jnp.dtype(getattr(cfg, "dtype", "float32")) \
            if kv_cache_dtype is None else kv_cache_dtype
        return [
            init_pool(num_blocks, block_size, cfg.num_key_value_heads,
                      head_dim, dtype, sharding=sharding)
            for _ in range(cfg.num_hidden_layers)
        ]

    def forward(self, input_ids, labels=None, attention_mask=None,
                caches=None, offset=None, position_ids=None,
                block_tables=None, cache_lens=None, ragged_meta=None):
        if caches is not None:
            h, _, new_caches = self.deepseek(input_ids, attention_mask,
                                             caches=caches, offset=offset,
                                             position_ids=position_ids,
                                             block_tables=block_tables,
                                             cache_lens=cache_lens,
                                             ragged_meta=ragged_meta)
            return self._logits(h), new_caches
        h, aux_total = self.deepseek(input_ids, attention_mask)
        logits = self._logits(h)
        if labels is None:
            return logits
        loss = self.criterion(logits, labels)
        if aux_total is not None and self.config.router_aux_loss_coef:
            from ..ops.math import add, scale
            loss = add(loss, scale(
                aux_total, self.config.router_aux_loss_coef))
        return loss
