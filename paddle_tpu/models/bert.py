"""BERT (PaddleNLP ``paddlenlp/transformers/bert/modeling.py`` parity) —
BASELINE config 3 (SST-2 finetune): encoder + pooler + classifier head."""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..framework.core import Tensor
from ..nn import functional as F
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.layers import Layer
from ..nn.layer.norm import LayerNorm
from ..nn.layer.transformer import (TransformerEncoder,
                                    TransformerEncoderLayer)
from ..distributed.shard_utils import batch_shard

__all__ = ["BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForPretraining"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    num_labels: int = 2

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def tiny(vocab=1024, hidden=128, layers=2, heads=4):
        return BertConfig(vocab_size=vocab, hidden_size=hidden,
                          num_hidden_layers=layers,
                          num_attention_heads=heads,
                          intermediate_size=hidden * 4)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size)
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = Embedding(config.type_vocab_size,
                                               config.hidden_size)
        self.layer_norm = LayerNorm(config.hidden_size,
                                    config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        l = input_ids.shape[1]
        from ..ops.creation import arange, zeros_like
        if position_ids is None:
            position_ids = arange(l, dtype="int64")
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(h))


class BertPooler(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size)

    def forward(self, hidden_states):
        first = hidden_states[:, 0]
        from ..ops.math import tanh
        return tanh(self.dense(first))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        enc_layer = TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, dropout=config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob,
            act_dropout=0.0)
        self.encoder = TransformerEncoder(enc_layer,
                                          config.num_hidden_layers)
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        input_ids = batch_shard(input_ids)
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        encoded = self.encoder(emb, attention_mask)
        return encoded, self.pooler(encoded)


class BertForSequenceClassification(Layer):
    def __init__(self, config: BertConfig = None, num_classes=None):
        super().__init__()
        config = config or BertConfig()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size,
                                 num_classes or config.num_labels)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels)
        return logits


class BertForPretraining(Layer):
    def __init__(self, config: BertConfig = None):
        super().__init__()
        config = config or BertConfig()
        self.bert = BertModel(config)
        self.mlm_head = Linear(config.hidden_size, config.vocab_size)
        self.nsp_head = Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, labels=None,
                next_sentence_label=None):
        encoded, pooled = self.bert(input_ids, token_type_ids)
        mlm_logits = self.mlm_head(encoded)
        nsp_logits = self.nsp_head(pooled)
        if labels is not None:
            loss = F.cross_entropy(mlm_logits, labels, ignore_index=-100)
            if next_sentence_label is not None:
                loss = loss + F.cross_entropy(nsp_logits,
                                              next_sentence_label)
            return loss
        return mlm_logits, nsp_logits
