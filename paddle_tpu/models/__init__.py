"""Model zoo (PaddleNLP-parity transformer families + vision models via
``paddle_tpu.vision.models``)."""
from . import bert, gpt, llama
from .bert import BertConfig, BertForSequenceClassification, BertModel
from .gpt import GPTConfig, GPTForCausalLM, GPTModel
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    LlamaPretrainingCriterion)
