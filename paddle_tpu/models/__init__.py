"""Model zoo (PaddleNLP-parity transformer families + vision models via
``paddle_tpu.vision.models``)."""
from . import bert, deepseek_moe, gpt, llama, qwen2, qwen2_moe
from .bert import BertConfig, BertForSequenceClassification, BertModel
from .deepseek_moe import (DeepseekMoeConfig, DeepseekMoeForCausalLM,
                           DeepseekMoeModel)
from .gpt import GPTConfig, GPTForCausalLM, GPTModel
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    LlamaPretrainingCriterion)
from .qwen2 import Qwen2Config, Qwen2ForCausalLM, Qwen2Model
from .qwen2_moe import (Qwen2MoeConfig, Qwen2MoeForCausalLM,
                        Qwen2MoeModel)
