"""``paddle.quantization`` (reference: ``python/paddle/quantization/``
— QuantConfig + QAT/PTQ flows over observer/quanter factories).

TPU-first: fake-quant is a pure jax op with a straight-through-estimator
custom VJP (the reference's ``fake_quantize_dequantize_moving_average_
abs_max`` CUDA kernel pair); observers are plain running statistics on
the host-visible activations. Quantized layers stay jit-compatible —
the QDQ ops fuse into the surrounding matmuls under XLA, and at export
time the scales are ordinary weights.
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ..nn.layer.layers import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "BaseQuanter",
           "FakeQuanterWithAbsMaxObserver", "AbsmaxObserver",
           "quanterize", "QuantedLinear"]


# ---------------------------------------------------------------------------
# fake quantize-dequantize with STE gradient
# ---------------------------------------------------------------------------

@jax.custom_vjp
def fake_quant_dequant(x, scale, qmax):
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax - 1, qmax)
    return q * s / qmax


def _fqd_fwd(x, scale, qmax):
    s = jnp.maximum(scale, 1e-9)
    in_range = jnp.abs(x) <= s
    return fake_quant_dequant(x, scale, qmax), in_range


def _fqd_bwd(res, g):
    # straight-through: pass gradients inside the clip range, zero out
    in_range = res
    return (jnp.where(in_range, g, 0.0), None, None)


fake_quant_dequant.defvjp(_fqd_fwd, _fqd_bwd)


# ---------------------------------------------------------------------------
# observers / quanters
# ---------------------------------------------------------------------------

class BaseQuanter(Layer):
    """Observes ranges and applies QDQ; subclasses define the scale."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self.qmax = float(2 ** (quant_bits - 1) - 1)

    def scales(self):
        raise NotImplementedError

    def _qdq(self, x, scale):
        """Apply QDQ with the STE backward — the single dispatch point
        for every quanter."""
        def f(a, s):
            return fake_quant_dequant(a, s.astype(jnp.float32),
                                      jnp.float32(self.qmax))
        return apply_jax("fake_quant", f, x, scale)

    def forward(self, x):
        return self._qdq(x, self.scales())


class AbsmaxObserver(BaseQuanter):
    """PTQ observer: tracks max(|x|) over calibration batches; forward
    is identity until ``convert`` swaps in QDQ."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._absmax = jnp.zeros((), jnp.float32)
        self._observing = True

    def scales(self):
        return _wrap_out(jnp.maximum(
            jnp.asarray(self._absmax, jnp.float32), 1e-9))

    def forward(self, x):
        if self._observing:
            arr = as_jax(x)
            # device-side update (no host sync); traced calibration
            # steps can't update host state -> skip (observe eagerly)
            if not isinstance(arr, jax.core.Tracer):
                self._absmax = jnp.maximum(
                    self._absmax, jnp.max(jnp.abs(arr))
                    .astype(jnp.float32))
            return x
        return super().forward(x)


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """QAT quanter (``FakeQuanterWithAbsMaxObserver`` parity): a moving
    average of per-batch abs-max drives the scale; QDQ applies from the
    first step with the STE backward."""

    def __init__(self, moving_rate=0.9, quant_bits=8, **kw):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate
        # buffers (not host attrs) so a traced training step threads the
        # moving-average state functionally, exactly like BN running stats
        self.register_buffer("_state", _wrap_out(jnp.zeros((), jnp.float32)))
        self.register_buffer("_inited", _wrap_out(jnp.zeros((), jnp.float32)))

    def scales(self):
        return _wrap_out(jnp.maximum(
            as_jax(self._state).astype(jnp.float32), 1e-9))

    def forward(self, x):
        arr = as_jax(x)
        state = as_jax(self._state).astype(jnp.float32)
        if self.training:
            from ..framework.core import in_functional_mode
            cur = jnp.max(jnp.abs(arr)).astype(jnp.float32)
            inited = as_jax(self._inited).astype(jnp.float32)
            r = jnp.float32(self.moving_rate)
            new_state = jnp.where(inited > 0,
                                  r * state + (1 - r) * cur, cur)
            if in_functional_mode() or not isinstance(cur, jax.core.Tracer):
                from ..framework.core import functional_buffer_write
                functional_buffer_write(self._state, new_state)
                functional_buffer_write(self._inited,
                                        jnp.ones((), jnp.float32))
            # QDQ with the freshly-blended scale: a whole-step-jitted QAT
            # model never quantizes against an uninitialized (zero) scale
            scale = jnp.maximum(new_state, 1e-9)
        else:
            scale = jnp.maximum(state, 1e-9)
        return self._qdq(x, _wrap_out(scale))


def quanterize(cls=FakeQuanterWithAbsMaxObserver, **kwargs):
    """Factory helper (reference's quanter config entries)."""
    return functools.partial(cls, **kwargs)


# ---------------------------------------------------------------------------
# quantized layers
# ---------------------------------------------------------------------------

class QuantedLinear(Layer):
    """Linear with weight + activation fake-quant (the reference's
    ``quanted.Linear``). Shares the wrapped layer's parameter objects so
    optimizers keep updating the same weights."""

    def __init__(self, linear, weight_quanter, act_quanter):
        super().__init__()
        self._inner = linear
        self.weight_quanter = weight_quanter
        self.activation_quanter = act_quanter
        # expose the same params (shared objects, not copies)
        self.weight = linear.weight
        if getattr(linear, "bias", None) is not None:
            self.bias = linear.bias

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        from ..ops.linalg import matmul
        out = matmul(x, w)
        if getattr(self._inner, "bias", None) is not None:
            from ..ops.math import add
            out = add(out, self._inner.bias)
        return out


_QUANTABLE: Dict[str, Type[Layer]] = {}


def _quantable_types():
    if not _QUANTABLE:
        from ..nn.layer.common import Linear
        _QUANTABLE["Linear"] = Linear
    return _QUANTABLE


# ---------------------------------------------------------------------------
# config + flows
# ---------------------------------------------------------------------------

class QuantConfig:
    """``paddle.quantization.QuantConfig`` parity (subset): per-layer
    and per-type quanter assignment."""

    def __init__(self, activation=None, weight=None):
        self.default_activation = activation
        self.default_weight = weight
        self._layer_cfg = {}   # id(layer) -> (act, weight)
        self._type_cfg = {}    # type -> (act, weight)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_cfg[id(l)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_cfg[t] = (activation, weight)

    def _factories_for(self, layer):
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        if self.default_activation or self.default_weight:
            return (self.default_activation, self.default_weight)
        return None


def _swap_layers(model, make_wrapper):
    """Replace quantable sublayers in place (recursively); the set of
    swappable types is the _QUANTABLE registry."""
    quantable = tuple(_quantable_types().values())
    replaced = 0
    for name, child in list(getattr(model, "_sub_layers", {}).items()):
        if isinstance(child, quantable):
            wrapper = make_wrapper(child)
            if wrapper is not None:
                model._sub_layers[name] = wrapper
                replaced += 1
        else:
            replaced += _swap_layers(child, make_wrapper)
    return replaced


class QAT:
    """Quantization-aware training flow (``paddle.quantization.QAT``)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=True):
        cfg = self.config

        def wrap(linear):
            factories = cfg._factories_for(linear)
            if factories is None:
                return None
            act_f, w_f = factories
            return QuantedLinear(linear,
                                 w_f() if w_f else None,
                                 act_f() if act_f else None)

        n = _swap_layers(model, wrap)
        model._quanted_layers = n
        return model


class PTQ:
    """Post-training quantization: observe during calibration, then
    ``convert`` freezes scales and activates QDQ."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=True):
        cfg = self.config

        def wrap(linear):
            factories = cfg._factories_for(linear)
            if factories is None:
                return None
            act_f, w_f = factories
            act = act_f() if act_f else None
            w = w_f() if w_f else None
            return QuantedLinear(linear, w, act)

        model._quanted_layers = _swap_layers(model, wrap)
        return model

    def convert(self, model, inplace=True):
        """Stop observing: every AbsmaxObserver switches to QDQ."""
        def visit(layer):
            for child in getattr(layer, "_sub_layers", {}).values():
                if isinstance(child, AbsmaxObserver):
                    child._observing = False
                visit(child)
        visit(model)
        return model
