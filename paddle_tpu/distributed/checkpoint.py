"""Distributed checkpoint (``python/paddle/distributed/checkpoint/``
parity) over orbax.

The reference writes per-rank shard files + global metadata and reshards
on load across different meshes (``save_state_dict.py`` /
``load_state_dict.py``). orbax-checkpoint provides exactly this natively
for jax shardings (SURVEY.md §5.4): async, sharded, reshard-on-load.
"""
from __future__ import annotations

import os
from typing import Any, Dict

import jax
import numpy as np

from ..framework.core import Tensor, as_jax

__all__ = ["save_state_dict", "load_state_dict", "async_save_state_dict"]


def _to_arrays(state_dict: Dict[str, Any]):
    out = {}
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            out[k] = as_jax(v)
        elif isinstance(v, dict):
            out[k] = _to_arrays(v)
        else:
            out[k] = v
    return out


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, async_save=False):
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tree = _to_arrays(state_dict)
    ckptr = _checkpointer()
    ckptr.save(path, tree, force=True)


def async_save_state_dict(state_dict, path, **kw):
    """Async save: orbax AsyncCheckpointer overlaps serialization with
    the next train steps (preemption-tolerant checkpointing)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    ckptr.save(path, _to_arrays(state_dict), force=True)
    return ckptr  # caller may .wait_until_finished()


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0):
    """Load into the provided state_dict IN PLACE, resharding each tensor
    to its current sharding (mesh/degree may differ from save time)."""
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    restored = ckptr.restore(path)

    def apply(dst, src):
        for k, v in dst.items():
            if k not in src:
                continue
            if isinstance(v, Tensor):
                arr = jax.numpy.asarray(np.asarray(src[k]))
                sharding = getattr(v._data, "sharding", None)
                if sharding is not None:
                    try:
                        arr = jax.device_put(arr, sharding)
                    except Exception:
                        pass
                v._data = arr.astype(v._data.dtype)
            elif isinstance(v, dict):
                apply(v, src[k])
            else:
                dst[k] = src[k]

    apply(state_dict, restored)
    return state_dict
