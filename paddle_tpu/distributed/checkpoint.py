"""Distributed checkpoint (``python/paddle/distributed/checkpoint/``
parity) over orbax.

The reference writes per-rank shard files + global metadata and reshards
on load across different meshes (``save_state_dict.py`` /
``load_state_dict.py``). orbax-checkpoint provides exactly this natively
for jax shardings (SURVEY.md §5.4): async, sharded, reshard-on-load.
"""
from __future__ import annotations

import os
from typing import Any, Dict

import jax
import numpy as np

from ..framework.core import Tensor, as_jax

__all__ = ["save_state_dict", "load_state_dict", "async_save_state_dict",
           "save_state_dict_shards", "load_state_dict_shards"]


def _to_arrays(state_dict: Dict[str, Any]):
    out = {}
    for k, v in state_dict.items():
        if isinstance(v, Tensor):
            out[k] = as_jax(v)
        elif isinstance(v, dict):
            out[k] = _to_arrays(v)
        else:
            out[k] = v
    return out


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.PyTreeCheckpointer()


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, async_save=False,
                    format="distcp"):
    """``format="distcp"`` (default): per-shard files + global metadata,
    the reference's transparent layout; ``format="orbax"``: one orbax
    tree (fast path for huge arrays). ``async_save=True`` keeps the
    orbax async path — the distcp writer is synchronous."""
    if async_save:
        return async_save_state_dict(state_dict, path)
    if format == "distcp":
        return save_state_dict_shards(state_dict, path)
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tree = _to_arrays(state_dict)
    ckptr = _checkpointer()
    ckptr.save(path, tree, force=True)


def async_save_state_dict(state_dict, path, **kw):
    """Async save: orbax AsyncCheckpointer overlaps serialization with
    the next train steps (preemption-tolerant checkpointing)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
    ckptr.save(path, _to_arrays(state_dict), force=True)
    return ckptr  # caller may .wait_until_finished()


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0):
    """Load into the provided state_dict IN PLACE, resharding each tensor
    to its current sharding (mesh/degree may differ from save time).
    Auto-detects the on-disk layout: the per-shard ``*.distcp`` +
    metadata layout or the orbax tree."""
    path = os.path.abspath(path)
    if os.path.exists(os.path.join(path, "metadata.json")):
        return load_state_dict_shards(state_dict, path)
    ckptr = _checkpointer()
    restored = ckptr.restore(path)

    def apply(dst, src):
        for k, v in dst.items():
            if k not in src:
                continue
            if isinstance(v, Tensor):
                arr = jax.numpy.asarray(np.asarray(src[k]))
                sharding = getattr(v._data, "sharding", None)
                if sharding is not None:
                    try:
                        arr = jax.device_put(arr, sharding)
                    except Exception:
                        pass
                v._data = arr.astype(v._data.dtype)
            elif isinstance(v, dict):
                apply(v, src[k])
            else:
                dst[k] = src[k]

    apply(state_dict, restored)
    return state_dict


# ---------------------------------------------------------------------------
# per-shard files + global metadata (reference layout semantics:
# ``python/paddle/distributed/checkpoint/save_state_dict.py`` writes
# ``<rank>_0.distcp`` shard files and a Metadata with
# LocalTensorMetadata/LocalTensorIndex; load reshards across meshes via
# the metadata — ``load_state_dict.py``)
# ---------------------------------------------------------------------------

def _flatten(d, prefix=""):
    out = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def save_state_dict_shards(state_dict, path):
    """Write each tensor's DEVICE shards into per-shard ``N_0.distcp``
    pickles plus a global ``metadata.json`` mapping tensor name ->
    (shape, dtype, shard slices, file). On a single-controller mesh the
    device index plays the reference's rank role."""
    import json
    import pickle
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    flat = {k: as_jax(v) if isinstance(v, Tensor) else v
            for k, v in _flatten(state_dict).items()}
    per_file: dict = {}
    meta = {"tensors": {}, "extras": {}}
    for name, arr in flat.items():
        if not hasattr(arr, "addressable_shards"):
            try:
                meta["extras"][name] = np.asarray(arr).tolist()
            except Exception as exc:
                raise TypeError(
                    f"state entry {name!r} ({type(arr).__name__}) is "
                    f"not serializable into the checkpoint: {exc}; "
                    "convert it to arrays/scalars before saving") \
                    from exc
            continue
        entry = {"shape": list(np.shape(arr)),
                 "dtype": str(np.asarray(arr.dtype)), "shards": []}
        seen = set()
        for shard in arr.addressable_shards:
            idx = tuple(
                (0 if sl.start is None else int(sl.start),
                 (dim if sl.stop is None else int(sl.stop)))
                for sl, dim in zip(shard.index, np.shape(arr)))
            if idx in seen:      # replicated copies: store once
                continue
            seen.add(idx)
            fname = f"{shard.device.id}_0.distcp"
            key = f"{name}@{'_'.join(f'{a}-{b}' for a, b in idx)}"
            per_file.setdefault(fname, {})[key] = np.asarray(shard.data)
            entry["shards"].append({"file": fname, "key": key,
                                    "offsets": [a for a, _ in idx],
                                    "ends": [b for _, b in idx]})
        meta["tensors"][name] = entry
    for fname, blob in per_file.items():
        with open(os.path.join(path, fname), "wb") as f:
            pickle.dump(blob, f, protocol=4)
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f)


def load_state_dict_shards(state_dict, path):
    """Reassemble tensors from the shard files per the metadata and
    redistribute to each destination tensor's CURRENT sharding — the
    cross-mesh reshard-on-load the reference implements."""
    import json
    import pickle
    path = os.path.abspath(path)
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    blobs: dict = {}

    def shard_data(ref):
        if ref["file"] not in blobs:
            with open(os.path.join(path, ref["file"]), "rb") as f:
                blobs[ref["file"]] = pickle.load(f)
        return blobs[ref["file"]][ref["key"]]

    flat_dst = _flatten(state_dict)
    missing = [name for name, v in flat_dst.items()
               if isinstance(v, Tensor)
               and name not in meta["tensors"]
               and name not in meta.get("extras", {})]
    if missing:
        raise KeyError(
            f"checkpoint at {path} is missing {len(missing)} tensor(s) "
            f"the destination expects (first few: {missing[:5]}); "
            "refusing a silent partial load")
    for name, v in flat_dst.items():
        if not isinstance(v, Tensor):
            continue
        ent = meta["tensors"].get(name)
        if ent is None:
            v._data = jax.numpy.asarray(
                meta["extras"][name]).astype(v._data.dtype)
            continue
        full = np.zeros(ent["shape"], np.dtype(ent["dtype"]))
        for ref in ent["shards"]:
            sl = tuple(slice(a, b) for a, b in zip(ref["offsets"],
                                                   ref["ends"]))
            full[sl] = shard_data(ref)
        arr = jax.numpy.asarray(full)
        sharding = getattr(v._data, "sharding", None)
        if sharding is not None:
            try:
                arr = jax.device_put(arr, sharding)
            except Exception:
                pass
        v._data = arr.astype(v._data.dtype)
    return state_dict
