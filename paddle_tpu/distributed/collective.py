"""Collective communication facades (``python/paddle/distributed/
communication/`` parity).

Two execution regimes, matching SURVEY.md §5.8:
  - Inside ``shard_map``-traced code (the real multi-chip path):
    facades emit ``jax.lax.p*`` collectives over the named mesh axis —
    XLA schedules them on ICI.
  - Eager single-process: world_size==1 group semantics (identity), so
    Paddle scripts run unchanged on one chip.

``Group`` carries a mesh-axis name instead of an NCCL communicator.

A third regime covers the reference's EAGER multi-process ProcessGroup
(Gloo role): when the process was launched with world_size > 1 (launch
env present), facades called OUTSIDE shard_map execute REAL
cross-process collectives over the native-TCPStore eager backend
(``eager_backend.py``) instead of identity.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, as_jax, _wrap_out
from . import env as _env


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """Process-group facade: a set of ranks bound to a mesh axis name."""

    _next_id = 0

    def __init__(self, ranks=None, axis_name=None, pg=None, name=None):
        self.ranks = list(ranks) if ranks is not None else list(
            range(_env.get_world_size()))
        self.axis_name = axis_name
        Group._next_id += 1
        self.id = Group._next_id
        self.name = name or f"group_{self.id}"

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def rank(self):
        r = _env.get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(axis={self.axis_name}, ranks={self.ranks})"


_default_group: Optional[Group] = None


def _get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        _default_group = Group(axis_name="dp")
    return _default_group


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    return Group(ranks=ranks, axis_name=axis_name)


def get_group(gid=0):
    return _get_default_group()


def _in_shard_map() -> bool:
    """True when called under a shard_map trace with named axes bound."""
    try:
        return bool(jax.core.get_axis_env().axis_sizes)  # jax>=0.6 internals
    except Exception:
        import jax.core as jcore
        frame = getattr(jcore, "thread_local_state", None)
        return False


def _axis(group):
    g = group or _get_default_group()
    return g.axis_name


def _eager(*tensors):
    """The cross-process backend, or None. Traced values fall through to
    the shard_map/identity regimes — a host-side store exchange cannot
    run on tracers."""
    for t in tensors:
        a = as_jax(t) if isinstance(t, Tensor) else t
        if isinstance(a, jax.core.Tracer):
            return None
    from .eager_backend import get_eager_backend
    return get_eager_backend()


def _group_ranks(group):
    g = group or _get_default_group()
    return g, list(g.ranks)


def _maybe_axis_active(axis_name) -> bool:
    if axis_name is None:
        return False
    try:
        jax.lax.axis_index(axis_name)  # raises NameError outside shard_map
        return True
    except Exception:
        return False


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis(group)
    arr = as_jax(tensor)
    if _maybe_axis_active(axis):
        fns = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
               ReduceOp.MIN: jax.lax.pmin,
               ReduceOp.AVG: jax.lax.pmean,
               # no lax.pprod primitive: gather the axis and reduce
               ReduceOp.PROD: lambda a, ax: jnp.prod(
                   jax.lax.all_gather(a, ax), axis=0)}
        out = fns[op](arr, axis)
        if isinstance(tensor, Tensor):
            tensor._data = out
            return tensor
        return _wrap_out(out)
    be = _eager(arr)
    if be is not None:
        g, ranks = _group_ranks(group)
        out = jnp.asarray(be.all_reduce(np.asarray(arr), op, ranks))
        if isinstance(tensor, Tensor):
            tensor._data = out
            return tensor
        return _wrap_out(out)
    # single-process world: identity
    return tensor if isinstance(tensor, Tensor) else _wrap_out(arr)


def _all_reduce_eager_mean(tensor, group=None):
    return all_reduce(tensor, ReduceOp.AVG, group)


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    ax_name = _axis(group)
    arr = as_jax(tensor)
    if _maybe_axis_active(ax_name):
        gathered = jax.lax.all_gather(arr, ax_name)
        n = gathered.shape[0]
        if isinstance(tensor_list, list):
            tensor_list.clear()
            tensor_list.extend(_wrap_out(gathered[i]) for i in range(n))
            return
        if axis != 0:
            # concat the per-rank shards along `axis` (same shape the
            # eager regime returns — regimes must agree)
            return _wrap_out(jnp.concatenate(
                [gathered[i] for i in range(n)], axis=axis))
        return _wrap_out(gathered)
    be = _eager(arr)
    if be is not None:
        g, ranks = _group_ranks(group)
        parts = [_wrap_out(jnp.asarray(a))
                 for a in be.all_gather(np.asarray(arr), ranks)]
        if isinstance(tensor_list, list):
            tensor_list.clear()
            tensor_list.extend(parts)
            return
        if axis != 0:
            # non-0 gather axis: concatenate the per-rank shards along
            # it (the reference concat_v2 path of c_allgather)
            return _wrap_out(jnp.concatenate(
                [as_jax(t) for t in parts], axis=axis))
        # match the shard_map regime's stacked [world, ...] shape
        return _wrap_out(jnp.stack([as_jax(t) for t in parts], axis=0))
    if isinstance(tensor_list, list):
        tensor_list.clear()
        tensor_list.append(tensor if isinstance(tensor, Tensor)
                           else _wrap_out(arr))
        return
    return tensor


def all_gather_object(obj_list, obj, group=None):
    be = _eager()
    obj_list.clear()
    if be is not None:
        g, ranks = _group_ranks(group)
        obj_list.extend(be.all_gather_object(obj, ranks))
        return
    obj_list.append(obj)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    ax_name = _axis(group)
    if tensor_list is not None:
        src = jnp.concatenate([as_jax(t) for t in tensor_list], axis=0)
    else:
        src = as_jax(tensor)
    if _maybe_axis_active(ax_name):
        out = jax.lax.psum_scatter(src, ax_name, tiled=True)
        if isinstance(tensor, Tensor):
            tensor._data = out
            return tensor
        return _wrap_out(out)
    be = _eager(src)
    if be is not None:
        g, ranks = _group_ranks(group)
        out = jnp.asarray(be.reduce_scatter(np.asarray(src), op, ranks))
        if isinstance(tensor, Tensor):
            tensor._data = out
            return tensor
        return _wrap_out(out)
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """``paddle.distributed.reduce``: reduce to rank ``dst``. The
    reference leaves non-root values undefined; here every rank gets
    the reduced value (an all-reduce) — a valid strengthening under
    the identity/GSPMD regimes, and what the eager regime's backend
    returns anyway."""
    return all_reduce(tensor, op=op, group=group, sync_op=sync_op)


def broadcast(tensor, src=0, group=None, sync_op=True):
    be = _eager(tensor)
    if be is not None and not _maybe_axis_active(_axis(group)):
        g, ranks = _group_ranks(group)
        out = be.broadcast(np.asarray(as_jax(tensor)), src, ranks)
        if isinstance(tensor, Tensor):
            tensor._data = jnp.asarray(out)
            return tensor
        return _wrap_out(jnp.asarray(out))
    # replicated-by-construction on the mesh; identity otherwise
    return tensor


def broadcast_object_list(object_list, src=0, group=None):
    be = _eager()
    if be is not None:
        g, ranks = _group_ranks(group)
        new = be.broadcast(list(object_list), src, ranks)
        object_list[:] = new
    return object_list


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        g = group or _get_default_group()
        idx = g.rank if g.rank >= 0 else 0
        tensor._rebind(tensor_list[idx] if isinstance(tensor_list[idx],
                                                      Tensor)
                       else _wrap_out(as_jax(tensor_list[idx])))
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None,
             sync_op=True):
    ax_name = _axis(group)
    if isinstance(in_tensor_list, (list, tuple)):
        stacked = jnp.stack([as_jax(t) for t in in_tensor_list])
    else:
        stacked = as_jax(in_tensor_list)
    be = _eager(stacked)
    if _maybe_axis_active(ax_name):
        out = jax.lax.all_to_all(stacked, ax_name, split_axis=0,
                                 concat_axis=0, tiled=False)
        outs = [_wrap_out(out[i]) for i in range(out.shape[0])]
    elif be is not None and isinstance(in_tensor_list, (list, tuple)):
        g, ranks = _group_ranks(group)
        got = be.all_to_all([np.asarray(as_jax(t))
                             for t in in_tensor_list], ranks)
        outs = [_wrap_out(jnp.asarray(a)) for a in got]
    else:
        outs = [t if isinstance(t, Tensor) else _wrap_out(as_jax(t))
                for t in (in_tensor_list if isinstance(
                    in_tensor_list, (list, tuple)) else [in_tensor_list])]
    if isinstance(out_tensor_list, list):
        out_tensor_list.clear()
        out_tensor_list.extend(outs)
        return
    return outs


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    ax_name = _axis(group)
    arr = as_jax(in_tensor)
    if _maybe_axis_active(ax_name):
        out = jax.lax.all_to_all(arr, ax_name, split_axis=0, concat_axis=0,
                                 tiled=True)
    else:
        out = arr
    if out_tensor is not None and isinstance(out_tensor, Tensor):
        out_tensor._data = out
        return out_tensor
    return _wrap_out(out)


def send(tensor, dst=0, group=None, sync_op=True):
    g = group or _get_default_group()
    if g.nranks == 1:
        return
    be = _eager(tensor)
    if be is not None:
        be.send(np.asarray(as_jax(tensor)), dst)
        return
    raise NotImplementedError(
        "point-to-point send INSIDE traced code: use ppermute-based "
        "pipeline schedules (paddle_tpu.distributed.fleet pp) instead")


def recv(tensor, src=0, group=None, sync_op=True):
    g = group or _get_default_group()
    if g.nranks == 1:
        return tensor
    be = _eager(tensor)
    if be is not None:
        out = jnp.asarray(be.recv(src))
        if isinstance(tensor, Tensor):
            tensor._data = out
            return tensor
        return _wrap_out(out)
    raise NotImplementedError(
        "point-to-point recv INSIDE traced code: use ppermute-based "
        "pipeline schedules (paddle_tpu.distributed.fleet pp) instead")


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    for op in p2p_op_list:
        op.op(op.tensor, op.peer, op.group)
    return []


def barrier(group=None):
    be = _eager()
    if be is not None:
        g, ranks = _group_ranks(group)
        be.barrier(ranks)
        return
    try:
        (jnp.zeros(()) + 0).block_until_ready()
    except Exception:
        pass


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        try:
            tensor._data.block_until_ready()
        except Exception:
            pass


def stream_all_reduce(*a, **k):
    return all_reduce(*a, **k)
