"""1F1B pipeline schedule with O(pp) activation memory (reference:
``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
1F1B mode — warmup forwards, steady one-forward-one-backward, cooldown
backwards).

TPU-first formulation: the schedule is precomputed in python as static
[pp, T] op/micro tables (SPMD programs cannot branch per rank, but they
can index constant tables by ``axis_index``), and the whole timetable
runs as ONE ``lax.scan`` inside a ``shard_map``. Each slot a device
executes F, B, or idle via ``lax.switch``:

- **F**: consume the ring-received boundary activation (stage 0: run
  ``first_fn`` on the raw feed), save it in a size-``pp`` ring (THE 1F1B
  memory property — at most ``pp`` in-flight microbatches per device),
  run the stage, ``ppermute`` the result forward.
- **B**: recompute the stage from the saved input (activation remat),
  pull the upstream gradient back through ``jax.vjp``, accumulate local
  parameter grads, ``ppermute`` the input-gradient backward. The last
  stage seeds the chain from the per-micro loss; stage 0 additionally
  backprops through ``first_fn``.

Forward and backward interleave in one scan, so peak live boundary
activations are ``pp`` per device — not ``n_micro`` as in fill-drain
GPipe — which is exactly what 1F1B buys the reference on GPUs.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from . import env as _env
from .pipeline import _live_batch_axes

__all__ = ["make_1f1b_schedule", "pipeline_1f1b_grads",
           "make_interleaved_schedule", "pipeline_interleaved_grads"]

_IDLE, _F, _B = 0, 1, 2


def make_1f1b_schedule(pp: int, n_micro: int):
    """Greedy slot assignment of the per-stage 1F1B op sequences under
    the ring's data dependencies. Returns (op[pp, T], mi[pp, T]) numpy
    tables: op in {0 idle, 1 F, 2 B}, mi the micro index."""
    seqs = []
    for s in range(pp):
        warm = min(pp - 1 - s, n_micro)
        seq = [("F", m) for m in range(warm)]
        b = 0
        for f in range(warm, n_micro):
            seq.append(("F", f))
            seq.append(("B", b))
            b += 1
        while b < n_micro:
            seq.append(("B", b))
            b += 1
        seqs.append(seq)

    slot_f, slot_b = {}, {}
    ptr = [0] * pp
    op_rows, mi_rows = [], []
    t = 0
    limit = 8 * (n_micro + pp) + 16
    while any(ptr[s] < len(seqs[s]) for s in range(pp)):
        col_op = [_IDLE] * pp
        col_mi = [0] * pp
        commit = []
        for s in range(pp):
            if ptr[s] >= len(seqs[s]):
                continue
            op, m = seqs[s][ptr[s]]
            if op == "F":
                ok = s == 0 or slot_f.get((s - 1, m), limit) < t
            else:
                ok = slot_f.get((s, m), limit) < t if s == pp - 1 \
                    else slot_b.get((s + 1, m), limit) < t
            if ok:
                col_op[s] = _F if op == "F" else _B
                col_mi[s] = m
                commit.append((s, op, m))
        for s, op, m in commit:
            (slot_f if op == "F" else slot_b)[(s, m)] = t
            ptr[s] += 1
        op_rows.append(col_op)
        mi_rows.append(col_mi)
        t += 1
        if t > limit:
            raise RuntimeError("1F1B schedule did not converge "
                               f"(pp={pp}, n_micro={n_micro})")
    return (np.array(op_rows, np.int32).T,
            np.array(mi_rows, np.int32).T)


def _pipe_env(mesh, axis, batch_axes, feeds, last_feeds, first_fn,
              first_params):
    """Shared prologue for both 1F1B engines: batch-axis partitioning,
    per-device feed/boundary shapes, and in/out spec helpers."""
    batch_spec = _live_batch_axes(mesh, axis, batch_axes, feeds.shape[1])
    _axes = (batch_spec,) if isinstance(batch_spec, str) \
        else (batch_spec or ())
    n_dp = int(np.prod([mesh.shape[a] for a in _axes])) if _axes else 1
    local_mb = feeds.shape[1] // n_dp
    feed_spec = P(None, batch_spec, *([None] * (feeds.ndim - 2)))
    lf_spec = None if last_feeds is None else P(
        None, batch_spec if last_feeds.shape[1] == feeds.shape[1]
        else None, *([None] * (last_feeds.ndim - 2)))
    local_feed = jax.ShapeDtypeStruct((local_mb,) + feeds.shape[2:],
                                      feeds.dtype)
    if first_fn is not None:
        h_struct = jax.eval_shape(first_fn, first_params, local_feed)
    else:
        h_struct = local_feed
    rep = lambda tree: jax.tree_util.tree_map(
        lambda x: P(*([None] * jnp.ndim(x))), tree)
    zeros_like_tree = lambda tree: jax.tree_util.tree_map(
        lambda x: jnp.zeros(jnp.shape(x), jnp.result_type(x)), tree)
    return {"axes": _axes, "n_dp": n_dp, "feed_spec": feed_spec,
            "lf_spec": lf_spec, "h_shape": h_struct.shape,
            "h_dtype": h_struct.dtype, "rep": rep,
            "zeros_like_tree": zeros_like_tree}


def _pipe_outputs(axis, axes, nm, n_dp, loss_acc, gm_acc, gf_acc,
                  gl_acc):
    """Shared epilogue: broadcast the loss, mean-scale and psum grads
    (pp owns its shard of the mid grads; first/last grads live on their
    owner stages)."""
    dp_plus_pp = (axis,) + tuple(axes)
    loss = jax.lax.psum(loss_acc, dp_plus_pp) / (nm * n_dp)
    scale = 1.0 / (nm * n_dp)
    ps = lambda tree: jax.tree_util.tree_map(
        lambda g: jax.lax.psum(g, dp_plus_pp) * scale, tree)
    gm_out = jax.tree_util.tree_map(
        lambda g: (jax.lax.psum(g, tuple(axes)) * scale
                   if axes else g * scale)[None], gm_acc)
    return loss, gm_out, ps(gf_acc), ps(gl_acc)


def pipeline_1f1b_grads(stage_fn: Callable, stacked_params, feeds,
                        last_fn: Callable, *, first_fn=None,
                        first_params=None, last_params=None,
                        last_feeds=None, mesh: Optional[Mesh] = None,
                        axis: str = "pp",
                        batch_axes=("dp", "sharding"),
                        loss_scale=None):
    """Run one full 1F1B train pass; returns
    ``(mean_loss, (g_stacked, g_first, g_last))``.

    stage_fn(params_local, h) -> h           (homogeneous stage body)
    first_fn(first_params, feed_mb) -> h     (stage-0 embed; optional)
    last_fn(last_params, h, last_feed_mb) -> scalar per-micro loss
    feeds: [n_micro, mb, ...] raw stage-0 inputs.
    last_feeds: [n_micro, ...] per-micro labels for last_fn.
    loss_scale: optional traced scalar — seeds the backward chain at the
    last stage (fp16 GradScaler semantics: every grad comes out
    multiplied by it; the reported loss stays unscaled).
    """
    mesh = mesh or _env.get_mesh()
    pp = mesh.shape[axis]
    nm = feeds.shape[0]
    from ..profiler import RecordEvent
    with RecordEvent("pipeline:1f1b_schedule"):
        op_tab, mi_tab = make_1f1b_schedule(pp, nm)
    T = op_tab.shape[1]
    # schedule-shape telemetry: slots per device and bubble fraction
    # (idle slots / total) — the quantity 1F1B exists to minimize
    from .. import monitor as _monitor
    _monitor.gauge("pipeline_schedule_slots",
                   "1F1B timetable length T per device",
                   labels=("pp", "n_micro")).labels(
        pp=str(pp), n_micro=str(nm)).set(int(T))
    _monitor.gauge("pipeline_bubble_fraction",
                   "idle-slot fraction of the 1F1B timetable",
                   labels=("pp", "n_micro")).labels(
        pp=str(pp), n_micro=str(nm)).set(
        round(float((op_tab == _IDLE).mean()), 4))
    env = _pipe_env(mesh, axis, batch_axes, feeds, last_feeds,
                    first_fn, first_params)
    _axes, n_dp = env["axes"], env["n_dp"]
    feed_spec, lf_spec = env["feed_spec"], env["lf_spec"]
    h_shape, h_dtype = env["h_shape"], env["h_dtype"]
    rep, zeros_like_tree = env["rep"], env["zeros_like_tree"]
    in_spec_params = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)

    op_arr = jnp.asarray(op_tab)
    mi_arr = jnp.asarray(mi_tab)

    def per_device(params_block, mbs, fparams, lparams, lfeeds, scale_a):
        params_local = jax.tree_util.tree_map(lambda x: x[0],
                                              params_block)
        stage = jax.lax.axis_index(axis)
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        perm_bwd = [(i, (i - 1) % pp) for i in range(pp)]
        is_first = stage == 0
        is_last = stage == pp - 1
        seed_g = scale_a.astype(jnp.float32)

        zr = lambda: jnp.zeros((pp,) + h_shape, h_dtype)
        g_mid0 = zeros_like_tree(params_local)
        g_first0 = zeros_like_tree(fparams)
        g_last0 = zeros_like_tree(lparams)

        def lf_of(m):
            return None if lfeeds is None else lfeeds[m]

        # ---- slot bodies (uniform signature) --------------------------
        def body_idle(oprnd):
            in_ring, fbuf, gbuf, m = oprnd
            zeros_h = jnp.zeros(h_shape, h_dtype)
            return (in_ring, zeros_h, zeros_h, g_mid0, g_first0,
                    g_last0, jnp.zeros((), jnp.float32))

        def body_F(oprnd):
            in_ring, fbuf, gbuf, m = oprnd
            if first_fn is not None:
                x0 = jax.lax.cond(
                    is_first, lambda: first_fn(fparams, mbs[m]),
                    lambda: jnp.zeros(h_shape, h_dtype))
                x_in = jnp.where(is_first, x0, fbuf[m % pp])
            else:
                x_in = jnp.where(is_first, mbs[m].astype(h_dtype),
                                 fbuf[m % pp])
            in_ring = in_ring.at[m % pp].set(x_in)
            # the last stage's F only banks its input: loss + grads are
            # (re)computed at its B slot
            y = jax.lax.cond(is_last,
                             lambda: jnp.zeros(h_shape, h_dtype),
                             lambda: stage_fn(params_local, x_in))
            return (in_ring, y, jnp.zeros(h_shape, h_dtype), g_mid0,
                    g_first0, g_last0, jnp.zeros((), jnp.float32))

        def body_B(oprnd):
            in_ring, fbuf, gbuf, m = oprnd
            x_saved = in_ring[m % pp]
            g_in = gbuf[m % pp]

            def last_case():
                def loss_of(p_mid, p_last, x):
                    y = stage_fn(p_mid, x)
                    return last_fn(p_last, y, lf_of(m)).astype(
                        jnp.float32)
                loss, pull = jax.vjp(loss_of, params_local, lparams,
                                     x_saved)
                # GradScaler: seed the chain with the loss scale — the
                # grads (incl. the boundary gx riding the ring) come out
                # scaled; the reported loss stays unscaled
                gm, gl, gx = pull(seed_g)
                return gm, g_first0, gl, gx, loss

            def first_case():
                if first_fn is None:
                    return mid_case()

                def fwd(p_first, p_mid, feed):
                    return stage_fn(p_mid, first_fn(p_first, feed))
                _, pull = jax.vjp(fwd, fparams, params_local, mbs[m])
                gf, gm, _ = pull(g_in)
                return gm, gf, g_last0, jnp.zeros(h_shape, h_dtype), \
                    jnp.zeros((), jnp.float32)

            def mid_case():
                _, pull = jax.vjp(
                    lambda p, x: stage_fn(p, x), params_local, x_saved)
                gm, gx = pull(g_in)
                return gm, g_first0, g_last0, gx, \
                    jnp.zeros((), jnp.float32)

            gm, gf, gl, gx, loss = jax.lax.cond(
                is_last, last_case,
                lambda: jax.lax.cond(is_first, first_case, mid_case))
            return (in_ring, jnp.zeros(h_shape, h_dtype), gx, gm, gf,
                    gl, loss)

        def slot(carry, t):
            in_ring, fbuf, gbuf, gm_acc, gf_acc, gl_acc, loss_acc = carry
            op = op_arr[stage, t]
            m = mi_arr[stage, t]
            in_ring, send_f, send_g, gm, gf, gl, loss = jax.lax.switch(
                op, [body_idle, body_F, body_B],
                (in_ring, fbuf, gbuf, m))
            # ---- ring communication (every slot, masked by schedule)
            recv_f = jax.lax.ppermute(send_f, axis, perm_fwd)
            recv_g = jax.lax.ppermute(send_g, axis, perm_bwd)
            prev = (stage - 1) % pp
            nxt = (stage + 1) % pp
            take_f = (op_arr[prev, t] == _F) & (stage > 0)
            take_g = (op_arr[nxt, t] == _B) & (stage < pp - 1)
            fbuf = jnp.where(take_f,
                             fbuf.at[mi_arr[prev, t] % pp].set(recv_f),
                             fbuf)
            gbuf = jnp.where(take_g,
                             gbuf.at[mi_arr[nxt, t] % pp].set(recv_g),
                             gbuf)
            add = jax.tree_util.tree_map
            return (in_ring, fbuf, gbuf,
                    add(jnp.add, gm_acc, gm), add(jnp.add, gf_acc, gf),
                    add(jnp.add, gl_acc, gl),
                    loss_acc + loss), None

        carry0 = (zr(), zr(), zr(), g_mid0, g_first0, g_last0,
                  jnp.zeros((), jnp.float32))
        (in_ring, fbuf, gbuf, gm_acc, gf_acc, gl_acc,
         loss_acc), _ = jax.lax.scan(slot, carry0, jnp.arange(T))

        # loss: only the last stage accumulated; grads for first/last
        # params: only their owner stages. dp shards each saw 1/n_dp of
        # the batch; the loss is the mean over shards.
        return _pipe_outputs(axis, _axes, nm, n_dp, loss_acc,
                             gm_acc, gf_acc, gl_acc)

    from .shard_utils import manual_region, shard_map_compat
    mapped = shard_map_compat(
        per_device, mesh,
        (in_spec_params, feed_spec, rep(first_params), rep(last_params),
         lf_spec, P()),
        (P(), jax.tree_util.tree_map(lambda _: P(axis), stacked_params),
         rep(first_params), rep(last_params)))
    scale_a = jnp.float32(1.0) if loss_scale is None \
        else jnp.asarray(loss_scale, jnp.float32)
    with manual_region(), RecordEvent("pipeline:1f1b"):
        loss, g_stacked, g_first, g_last = mapped(
            stacked_params, feeds, first_params, last_params, last_feeds,
            scale_a)
    return loss, (g_stacked, g_first, g_last)


# ---------------------------------------------------------------------------
# interleaved virtual stages (Megatron interleaved 1F1B — reference:
# ``pipeline_parallel.py`` with ``num_virtual_pipeline_stages``: each
# device hosts v model CHUNKS; model part index = chunk * pp + stage, so
# a microbatch crosses every device v times. Cuts the bubble fraction
# by ~v at the cost of v x boundary traffic.)
# ---------------------------------------------------------------------------

def make_interleaved_schedule(pp: int, n_micro: int, v: int):
    """Slot tables for interleaved 1F1B. Returns (op[pp,T], mi[pp,T],
    ci[pp,T]): op in {0 idle, 1 F, 2 B}; mi the micro; ci the chunk.

    Queue order per stage follows the published schedule (warmup
    forwards grouped chunk-major over micro-groups of size pp, then
    one-F-one-B, then drain); slots are assigned by the same greedy
    dependency simulation as the flat schedule."""
    if v <= 1:
        op, mi = make_1f1b_schedule(pp, n_micro)
        return op, mi, np.zeros_like(op)
    if n_micro % pp != 0:
        # the chunk-major micro-grouping is only feasible when micros
        # fill whole groups; other queue orders deadlock (verified)
        raise ValueError(
            f"interleaved schedule needs n_micro % pp == 0 "
            f"(got n_micro={n_micro}, pp={pp}); pad the microbatch "
            "count or use v=1")

    total_f = v * n_micro

    def f_order():
        # i-th forward -> (chunk, micro), chunk-major within
        # micro-groups of pp (same order on every stage)
        out = []
        for i in range(total_f):
            group, rem = divmod(i, pp * v)
            chunk, pos = divmod(rem, pp)
            out.append((chunk, group * pp + pos))
        return out

    def b_order():
        return [(v - 1 - c, m) for c, m in f_order()]

    seqs = []
    for s in range(pp):
        fs = f_order()
        bs = b_order()
        warm = min((pp - s - 1) * 2 + (v - 1) * pp, total_f)
        seq = [("F",) + fs[i] for i in range(warm)]
        bi = 0
        for fi in range(warm, total_f):
            seq.append(("F",) + fs[fi])
            seq.append(("B",) + bs[bi])
            bi += 1
        while bi < total_f:
            seq.append(("B",) + bs[bi])
            bi += 1
        seqs.append(seq)

    # dependency-respecting greedy slot assignment
    slot_f, slot_b = {}, {}
    ptr = [0] * pp
    op_rows, mi_rows, ci_rows = [], [], []
    t = 0
    limit = 16 * (v * n_micro + pp) + 32
    while any(ptr[s] < len(seqs[s]) for s in range(pp)):
        col_op = [_IDLE] * pp
        col_mi = [0] * pp
        col_ci = [0] * pp
        commit = []
        for s in range(pp):
            if ptr[s] >= len(seqs[s]):
                continue
            kind, c, m = seqs[s][ptr[s]]
            if kind == "F":
                if s > 0:
                    ok = slot_f.get((s - 1, c, m), limit) < t
                elif c > 0:
                    ok = slot_f.get((pp - 1, c - 1, m), limit) < t
                else:
                    ok = True
            else:
                if s == pp - 1 and c == v - 1:
                    ok = slot_f.get((s, c, m), limit) < t
                elif s == pp - 1:
                    ok = slot_b.get((0, c + 1, m), limit) < t
                else:
                    ok = slot_b.get((s + 1, c, m), limit) < t
            if ok:
                col_op[s] = _F if kind == "F" else _B
                col_mi[s] = m
                col_ci[s] = c
                commit.append((s, kind, c, m))
        for s, kind, c, m in commit:
            (slot_f if kind == "F" else slot_b)[(s, c, m)] = t
            ptr[s] += 1
        op_rows.append(col_op)
        mi_rows.append(col_mi)
        ci_rows.append(col_ci)
        t += 1
        if t > limit:
            raise RuntimeError(
                f"interleaved schedule did not converge (pp={pp}, "
                f"n_micro={n_micro}, v={v})")
    return (np.array(op_rows, np.int32).T,
            np.array(mi_rows, np.int32).T,
            np.array(ci_rows, np.int32).T)


def _ring_depth(op_tab, mi_tab, ci_tab, pp, v):
    """Minimal ring size such that no two in-flight entries of ANY of the
    three ``m % ring``-slotted buffers collide, computed from the tables
    so correctness never depends on a schedule-shape assumption.

    Occupancy windows per (stage, chunk), keyed by micro m:
    - in_ring (saved stage input): own F slot -> own B slot;
    - fbuf (boundary activation):  prev-stage F slot (ppermute arrival,
      end of slot) -> own F slot (read at slot start, so a same-slot
      rewrite is safe);
    - gbuf (boundary gradient):    next-stage B slot -> own B slot.
    Two windows with m1 % ring == m2 % ring collide iff one's write lands
    strictly inside the other's window."""
    T = op_tab.shape[1]
    f_slot, b_slot = {}, {}
    for s in range(pp):
        for t in range(T):
            k = (s, int(ci_tab[s, t]), int(mi_tab[s, t]))
            if op_tab[s, t] == _F:
                f_slot[k] = t
            elif op_tab[s, t] == _B:
                b_slot[k] = t

    spans = {}   # (buffer, stage, chunk) -> [(t_write, t_read, m)]

    def add(buf, s, c, tw, tr, m):
        spans.setdefault((buf, s, c), []).append((tw, tr, m))

    for (s, c, m), tf in f_slot.items():
        tb = b_slot.get((s, c, m))
        if tb is not None:
            add("in", s, c, tf, tb, m)                    # in_ring
        # fbuf: who wrote this activation? prev stage's F (chunk-routed)
        prev = (s - 1) % pp
        src_c = c - 1 if s == 0 else c
        if not (s == 0 and c == 0):
            tw = f_slot.get((prev, src_c, m))
            if tw is not None:
                add("f", s, c, tw, tf, m)
        # gbuf: written by next stage's B, read at own B
        if tb is not None and not (s == pp - 1 and c == v - 1):
            nxt = (s + 1) % pp
            src_c = c + 1 if s == pp - 1 else c
            tw = b_slot.get((nxt, src_c, m))
            if tw is not None:
                add("g", s, c, tw, tb, m)

    def collides(ring):
        for key, lst in spans.items():
            same_slot_read_ok = key[0] in ("f", "g")   # read-then-write
            for i in range(len(lst)):
                tw1, tr1, m1 = lst[i]
                for j in range(i + 1, len(lst)):
                    tw2, tr2, m2 = lst[j]
                    if m1 % ring != m2 % ring:
                        continue
                    hi1 = tr1 if same_slot_read_ok else tr1 + 1
                    hi2 = tr2 if same_slot_read_ok else tr2 + 1
                    if tw1 < tw2 < hi1 or tw2 < tw1 < hi2:
                        return True
        return False

    ring = 1
    n_micro = int(mi_tab.max()) + 1 if mi_tab.size else 1
    while ring < n_micro and collides(ring):
        ring += 1
    return ring


def pipeline_interleaved_grads(stage_fn: Callable, stacked_params, feeds,
                               last_fn: Callable, v: int, *,
                               first_fn=None, first_params=None,
                               last_params=None, last_feeds=None,
                               mesh: Optional[Mesh] = None,
                               axis: str = "pp",
                               batch_axes=("dp", "sharding"),
                               loss_scale=None):
    """Interleaved-virtual-stage 1F1B train pass. Like
    :func:`pipeline_1f1b_grads`, but each device hosts ``v`` model
    chunks (stacked_params leaves are [pp, v, ...]; model part
    ``c*pp + s`` lives at (stage s, chunk c)) and a microbatch crosses
    the ring ``v`` times. Returns
    ``(mean_loss, (g_stacked [pp, v, ...], g_first, g_last))``."""
    mesh = mesh or _env.get_mesh()
    pp = mesh.shape[axis]
    nm = feeds.shape[0]
    op_tab, mi_tab, ci_tab = make_interleaved_schedule(pp, nm, v)
    T = op_tab.shape[1]
    ring = _ring_depth(op_tab, mi_tab, ci_tab, pp, v)
    env = _pipe_env(mesh, axis, batch_axes, feeds, last_feeds,
                    first_fn, first_params)
    _axes, n_dp = env["axes"], env["n_dp"]
    feed_spec, lf_spec = env["feed_spec"], env["lf_spec"]
    h_shape, h_dtype = env["h_shape"], env["h_dtype"]
    rep, zeros_like_tree = env["rep"], env["zeros_like_tree"]
    in_spec_params = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)

    op_arr = jnp.asarray(op_tab)
    mi_arr = jnp.asarray(mi_tab)
    ci_arr = jnp.asarray(ci_tab)

    def per_device(params_block, mbs, fparams, lparams, lfeeds, scale_a):
        # leaves [1, v, ...] -> [v, ...]
        params_local = jax.tree_util.tree_map(lambda x: x[0],
                                              params_block)
        stage = jax.lax.axis_index(axis)
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        perm_bwd = [(i, (i - 1) % pp) for i in range(pp)]
        is_first = stage == 0
        is_last = stage == pp - 1
        seed_g = scale_a.astype(jnp.float32)

        zr = lambda: jnp.zeros((v, ring) + h_shape, h_dtype)
        g_mid0 = zeros_like_tree(params_local)        # [v, ...]
        g_first0 = zeros_like_tree(fparams)
        g_last0 = zeros_like_tree(lparams)

        def chunk_params(c):
            return jax.tree_util.tree_map(lambda x: x[c], params_local)

        def chunk_zero_like(tree):
            return jax.tree_util.tree_map(
                lambda x: jnp.zeros(x.shape[1:], x.dtype), tree)

        def lf_of(m):
            return None if lfeeds is None else lfeeds[m]

        def body_idle(oprnd):
            in_ring, fbuf, gbuf, m, c = oprnd
            zeros_h = jnp.zeros(h_shape, h_dtype)
            return (in_ring, zeros_h, zeros_h,
                    chunk_zero_like(params_local), g_first0, g_last0,
                    jnp.zeros((), jnp.float32), c)

        def body_F(oprnd):
            in_ring, fbuf, gbuf, m, c = oprnd
            p_c = chunk_params(c)
            first_part = is_first & (c == 0)
            last_part = is_last & (c == v - 1)
            if first_fn is not None:
                x0 = jax.lax.cond(
                    first_part, lambda: first_fn(fparams, mbs[m]),
                    lambda: jnp.zeros(h_shape, h_dtype))
                x_in = jnp.where(first_part, x0, fbuf[c, m % ring])
            else:
                x_in = jnp.where(first_part, mbs[m].astype(h_dtype),
                                 fbuf[c, m % ring])
            in_ring = in_ring.at[c, m % ring].set(x_in)
            y = jax.lax.cond(last_part,
                             lambda: jnp.zeros(h_shape, h_dtype),
                             lambda: stage_fn(p_c, x_in))
            return (in_ring, y, jnp.zeros(h_shape, h_dtype),
                    chunk_zero_like(params_local), g_first0, g_last0,
                    jnp.zeros((), jnp.float32), c)

        def body_B(oprnd):
            in_ring, fbuf, gbuf, m, c = oprnd
            p_c = chunk_params(c)
            x_saved = in_ring[c, m % ring]
            g_in = gbuf[c, m % ring]
            first_part = is_first & (c == 0)
            last_part = is_last & (c == v - 1)

            def last_case():
                def loss_of(p_mid, p_last, x):
                    y = stage_fn(p_mid, x)
                    return last_fn(p_last, y, lf_of(m)).astype(
                        jnp.float32)
                loss, pull = jax.vjp(loss_of, p_c, lparams, x_saved)
                gm, gl, gx = pull(seed_g)    # GradScaler seed
                return gm, g_first0, gl, gx, loss

            def first_case():
                if first_fn is None:
                    return mid_case()

                def fwd(p_first, p_mid, feed):
                    return stage_fn(p_mid, first_fn(p_first, feed))
                _, pull = jax.vjp(fwd, fparams, p_c, mbs[m])
                gf, gm, _ = pull(g_in)
                return gm, gf, g_last0, jnp.zeros(h_shape, h_dtype), \
                    jnp.zeros((), jnp.float32)

            def mid_case():
                _, pull = jax.vjp(
                    lambda p, x: stage_fn(p, x), p_c, x_saved)
                gm, gx = pull(g_in)
                return gm, g_first0, g_last0, gx, \
                    jnp.zeros((), jnp.float32)

            gm, gf, gl, gx, loss = jax.lax.cond(
                last_part, last_case,
                lambda: jax.lax.cond(first_part, first_case, mid_case))
            return (in_ring, jnp.zeros(h_shape, h_dtype), gx, gm, gf,
                    gl, loss, c)

        def slot(carry, t):
            (in_ring, fbuf, gbuf, gm_acc, gf_acc, gl_acc,
             loss_acc) = carry
            op = op_arr[stage, t]
            m = mi_arr[stage, t]
            c = ci_arr[stage, t]
            (in_ring, send_f, send_g, gm, gf, gl, loss,
             c_out) = jax.lax.switch(op, [body_idle, body_F, body_B],
                                     (in_ring, fbuf, gbuf, m, c))
            recv_f = jax.lax.ppermute(send_f, axis, perm_fwd)
            recv_g = jax.lax.ppermute(send_g, axis, perm_bwd)
            prev = (stage - 1) % pp
            nxt = (stage + 1) % pp
            p_op, p_mi, p_ci = op_arr[prev, t], mi_arr[prev, t], \
                ci_arr[prev, t]
            n_op, n_mi, n_ci = op_arr[nxt, t], mi_arr[nxt, t], \
                ci_arr[nxt, t]
            # forward routing: normal hop keeps the chunk; the wrap from
            # the last stage feeds the NEXT chunk at stage 0
            take_f = (p_op == _F) & (
                (stage > 0) | ((stage == 0) & (p_ci < v - 1)))
            fdst = jnp.where(stage == 0, jnp.minimum(p_ci + 1, v - 1),
                             p_ci)
            fbuf = jnp.where(take_f,
                             fbuf.at[fdst, p_mi % ring].set(recv_f),
                             fbuf)
            # backward routing mirrors it: the wrap from stage 0 feeds
            # the PREVIOUS chunk at the last stage
            take_g = (n_op == _B) & (
                (stage < pp - 1) | ((stage == pp - 1) & (n_ci > 0)))
            gdst = jnp.where(stage == pp - 1, jnp.maximum(n_ci - 1, 0),
                             n_ci)
            gbuf = jnp.where(take_g,
                             gbuf.at[gdst, n_mi % ring].set(recv_g),
                             gbuf)
            add = jax.tree_util.tree_map
            gm_acc = add(lambda acc, g: acc.at[c].add(g), gm_acc, gm)
            return (in_ring, fbuf, gbuf, gm_acc,
                    add(jnp.add, gf_acc, gf), add(jnp.add, gl_acc, gl),
                    loss_acc + loss), None

        carry0 = (zr(), zr(), zr(), g_mid0, g_first0, g_last0,
                  jnp.zeros((), jnp.float32))
        (in_ring, fbuf, gbuf, gm_acc, gf_acc, gl_acc,
         loss_acc), _ = jax.lax.scan(slot, carry0, jnp.arange(T))

        return _pipe_outputs(axis, _axes, nm, n_dp, loss_acc,
                             gm_acc, gf_acc, gl_acc)

    from .shard_utils import manual_region, shard_map_compat
    mapped = shard_map_compat(
        per_device, mesh,
        (in_spec_params, feed_spec, rep(first_params), rep(last_params),
         lf_spec, P()),
        (P(), jax.tree_util.tree_map(lambda _: P(axis), stacked_params),
         rep(first_params), rep(last_params)))
    scale_a = jnp.float32(1.0) if loss_scale is None \
        else jnp.asarray(loss_scale, jnp.float32)
    from ..profiler import RecordEvent
    with manual_region(), RecordEvent("pipeline:interleaved_1f1b"):
        loss, g_stacked, g_first, g_last = mapped(
            stacked_params, feeds, first_params, last_params, last_feeds,
            scale_a)
    return loss, (g_stacked, g_first, g_last)
