"""1F1B pipeline schedule with O(pp) activation memory (reference:
``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
1F1B mode — warmup forwards, steady one-forward-one-backward, cooldown
backwards).

TPU-first formulation: the schedule is precomputed in python as static
[pp, T] op/micro tables (SPMD programs cannot branch per rank, but they
can index constant tables by ``axis_index``), and the whole timetable
runs as ONE ``lax.scan`` inside a ``shard_map``. Each slot a device
executes F, B, or idle via ``lax.switch``:

- **F**: consume the ring-received boundary activation (stage 0: run
  ``first_fn`` on the raw feed), save it in a size-``pp`` ring (THE 1F1B
  memory property — at most ``pp`` in-flight microbatches per device),
  run the stage, ``ppermute`` the result forward.
- **B**: recompute the stage from the saved input (activation remat),
  pull the upstream gradient back through ``jax.vjp``, accumulate local
  parameter grads, ``ppermute`` the input-gradient backward. The last
  stage seeds the chain from the per-micro loss; stage 0 additionally
  backprops through ``first_fn``.

Forward and backward interleave in one scan, so peak live boundary
activations are ``pp`` per device — not ``n_micro`` as in fill-drain
GPipe — which is exactly what 1F1B buys the reference on GPUs.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from . import env as _env
from .pipeline import _live_batch_axes

__all__ = ["make_1f1b_schedule", "pipeline_1f1b_grads"]

_IDLE, _F, _B = 0, 1, 2


def make_1f1b_schedule(pp: int, n_micro: int):
    """Greedy slot assignment of the per-stage 1F1B op sequences under
    the ring's data dependencies. Returns (op[pp, T], mi[pp, T]) numpy
    tables: op in {0 idle, 1 F, 2 B}, mi the micro index."""
    seqs = []
    for s in range(pp):
        warm = min(pp - 1 - s, n_micro)
        seq = [("F", m) for m in range(warm)]
        b = 0
        for f in range(warm, n_micro):
            seq.append(("F", f))
            seq.append(("B", b))
            b += 1
        while b < n_micro:
            seq.append(("B", b))
            b += 1
        seqs.append(seq)

    slot_f, slot_b = {}, {}
    ptr = [0] * pp
    op_rows, mi_rows = [], []
    t = 0
    limit = 8 * (n_micro + pp) + 16
    while any(ptr[s] < len(seqs[s]) for s in range(pp)):
        col_op = [_IDLE] * pp
        col_mi = [0] * pp
        commit = []
        for s in range(pp):
            if ptr[s] >= len(seqs[s]):
                continue
            op, m = seqs[s][ptr[s]]
            if op == "F":
                ok = s == 0 or slot_f.get((s - 1, m), limit) < t
            else:
                ok = slot_f.get((s, m), limit) < t if s == pp - 1 \
                    else slot_b.get((s + 1, m), limit) < t
            if ok:
                col_op[s] = _F if op == "F" else _B
                col_mi[s] = m
                commit.append((s, op, m))
        for s, op, m in commit:
            (slot_f if op == "F" else slot_b)[(s, m)] = t
            ptr[s] += 1
        op_rows.append(col_op)
        mi_rows.append(col_mi)
        t += 1
        if t > limit:
            raise RuntimeError("1F1B schedule did not converge "
                               f"(pp={pp}, n_micro={n_micro})")
    return (np.array(op_rows, np.int32).T,
            np.array(mi_rows, np.int32).T)


def pipeline_1f1b_grads(stage_fn: Callable, stacked_params, feeds,
                        last_fn: Callable, *, first_fn=None,
                        first_params=None, last_params=None,
                        last_feeds=None, mesh: Optional[Mesh] = None,
                        axis: str = "pp",
                        batch_axes=("dp", "sharding")):
    """Run one full 1F1B train pass; returns
    ``(mean_loss, (g_stacked, g_first, g_last))``.

    stage_fn(params_local, h) -> h           (homogeneous stage body)
    first_fn(first_params, feed_mb) -> h     (stage-0 embed; optional)
    last_fn(last_params, h, last_feed_mb) -> scalar per-micro loss
    feeds: [n_micro, mb, ...] raw stage-0 inputs.
    last_feeds: [n_micro, ...] per-micro labels for last_fn.
    """
    mesh = mesh or _env.get_mesh()
    pp = mesh.shape[axis]
    nm = feeds.shape[0]
    op_tab, mi_tab = make_1f1b_schedule(pp, nm)
    T = op_tab.shape[1]

    batch_spec = _live_batch_axes(mesh, axis, batch_axes, feeds.shape[1])
    _axes = (batch_spec,) if isinstance(batch_spec, str) \
        else (batch_spec or ())
    n_dp = int(np.prod([mesh.shape[a] for a in _axes])) if _axes else 1
    local_mb = feeds.shape[1] // n_dp
    feed_spec = P(None, batch_spec, *([None] * (feeds.ndim - 2)))
    lf_spec = None if last_feeds is None else P(
        None, batch_spec if last_feeds.shape[1] == feeds.shape[1]
        else None, *([None] * (last_feeds.ndim - 2)))

    local_feed = jax.ShapeDtypeStruct((local_mb,) + feeds.shape[2:],
                                      feeds.dtype)
    if first_fn is not None:
        h_struct = jax.eval_shape(first_fn, first_params, local_feed)
    else:
        h_struct = local_feed
    h_shape, h_dtype = h_struct.shape, h_struct.dtype

    in_spec_params = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)
    rep = lambda tree: jax.tree_util.tree_map(
        lambda x: P(*([None] * jnp.ndim(x))), tree)
    zeros_like_tree = lambda tree: jax.tree_util.tree_map(
        lambda x: jnp.zeros(jnp.shape(x), jnp.result_type(x)), tree)

    op_arr = jnp.asarray(op_tab)
    mi_arr = jnp.asarray(mi_tab)

    def per_device(params_block, mbs, fparams, lparams, lfeeds):
        params_local = jax.tree_util.tree_map(lambda x: x[0],
                                              params_block)
        stage = jax.lax.axis_index(axis)
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        perm_bwd = [(i, (i - 1) % pp) for i in range(pp)]
        is_first = stage == 0
        is_last = stage == pp - 1

        zr = lambda: jnp.zeros((pp,) + h_shape, h_dtype)
        g_mid0 = zeros_like_tree(params_local)
        g_first0 = zeros_like_tree(fparams)
        g_last0 = zeros_like_tree(lparams)

        def lf_of(m):
            return None if lfeeds is None else lfeeds[m]

        # ---- slot bodies (uniform signature) --------------------------
        def body_idle(oprnd):
            in_ring, fbuf, gbuf, m = oprnd
            zeros_h = jnp.zeros(h_shape, h_dtype)
            return (in_ring, zeros_h, zeros_h, g_mid0, g_first0,
                    g_last0, jnp.zeros((), jnp.float32))

        def body_F(oprnd):
            in_ring, fbuf, gbuf, m = oprnd
            if first_fn is not None:
                x0 = jax.lax.cond(
                    is_first, lambda: first_fn(fparams, mbs[m]),
                    lambda: jnp.zeros(h_shape, h_dtype))
                x_in = jnp.where(is_first, x0, fbuf[m % pp])
            else:
                x_in = jnp.where(is_first, mbs[m].astype(h_dtype),
                                 fbuf[m % pp])
            in_ring = in_ring.at[m % pp].set(x_in)
            # the last stage's F only banks its input: loss + grads are
            # (re)computed at its B slot
            y = jax.lax.cond(is_last,
                             lambda: jnp.zeros(h_shape, h_dtype),
                             lambda: stage_fn(params_local, x_in))
            return (in_ring, y, jnp.zeros(h_shape, h_dtype), g_mid0,
                    g_first0, g_last0, jnp.zeros((), jnp.float32))

        def body_B(oprnd):
            in_ring, fbuf, gbuf, m = oprnd
            x_saved = in_ring[m % pp]
            g_in = gbuf[m % pp]

            def last_case():
                def loss_of(p_mid, p_last, x):
                    y = stage_fn(p_mid, x)
                    return last_fn(p_last, y, lf_of(m)).astype(
                        jnp.float32)
                (loss, (gm, gl, gx)) = jax.value_and_grad(
                    loss_of, argnums=(0, 1, 2))(params_local, lparams,
                                                x_saved)
                return gm, g_first0, gl, gx, loss

            def first_case():
                if first_fn is None:
                    return mid_case()

                def fwd(p_first, p_mid, feed):
                    return stage_fn(p_mid, first_fn(p_first, feed))
                _, pull = jax.vjp(fwd, fparams, params_local, mbs[m])
                gf, gm, _ = pull(g_in)
                return gm, gf, g_last0, jnp.zeros(h_shape, h_dtype), \
                    jnp.zeros((), jnp.float32)

            def mid_case():
                _, pull = jax.vjp(
                    lambda p, x: stage_fn(p, x), params_local, x_saved)
                gm, gx = pull(g_in)
                return gm, g_first0, g_last0, gx, \
                    jnp.zeros((), jnp.float32)

            gm, gf, gl, gx, loss = jax.lax.cond(
                is_last, last_case,
                lambda: jax.lax.cond(is_first, first_case, mid_case))
            return (in_ring, jnp.zeros(h_shape, h_dtype), gx, gm, gf,
                    gl, loss)

        def slot(carry, t):
            in_ring, fbuf, gbuf, gm_acc, gf_acc, gl_acc, loss_acc = carry
            op = op_arr[stage, t]
            m = mi_arr[stage, t]
            in_ring, send_f, send_g, gm, gf, gl, loss = jax.lax.switch(
                op, [body_idle, body_F, body_B],
                (in_ring, fbuf, gbuf, m))
            # ---- ring communication (every slot, masked by schedule)
            recv_f = jax.lax.ppermute(send_f, axis, perm_fwd)
            recv_g = jax.lax.ppermute(send_g, axis, perm_bwd)
            prev = (stage - 1) % pp
            nxt = (stage + 1) % pp
            take_f = (op_arr[prev, t] == _F) & (stage > 0)
            take_g = (op_arr[nxt, t] == _B) & (stage < pp - 1)
            fbuf = jnp.where(take_f,
                             fbuf.at[mi_arr[prev, t] % pp].set(recv_f),
                             fbuf)
            gbuf = jnp.where(take_g,
                             gbuf.at[mi_arr[nxt, t] % pp].set(recv_g),
                             gbuf)
            add = jax.tree_util.tree_map
            return (in_ring, fbuf, gbuf,
                    add(jnp.add, gm_acc, gm), add(jnp.add, gf_acc, gf),
                    add(jnp.add, gl_acc, gl),
                    loss_acc + loss), None

        carry0 = (zr(), zr(), zr(), g_mid0, g_first0, g_last0,
                  jnp.zeros((), jnp.float32))
        (in_ring, fbuf, gbuf, gm_acc, gf_acc, gl_acc,
         loss_acc), _ = jax.lax.scan(slot, carry0, jnp.arange(T))

        # loss: only the last stage accumulated; grads for first/last
        # params: only their owner stages. dp shards each saw 1/n_dp of
        # the batch; the loss is the mean over shards.
        dp_plus_pp = (axis,) + tuple(_axes)
        loss = jax.lax.psum(loss_acc, dp_plus_pp) / (nm * n_dp)
        scale = 1.0 / (nm * n_dp)
        ps = lambda tree, axes: jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, axes) * scale, tree)
        gm_out = jax.tree_util.tree_map(
            lambda g: (jax.lax.psum(g, tuple(_axes)) * scale
                       if _axes else g * scale)[None], gm_acc)
        gf_out = ps(gf_acc, dp_plus_pp)
        gl_out = ps(gl_acc, dp_plus_pp)
        return loss, gm_out, gf_out, gl_out

    from .shard_utils import manual_region, shard_map_compat
    mapped = shard_map_compat(
        per_device, mesh,
        (in_spec_params, feed_spec, rep(first_params), rep(last_params),
         lf_spec),
        (P(), jax.tree_util.tree_map(lambda _: P(axis), stacked_params),
         rep(first_params), rep(last_params)))
    with manual_region():
        loss, g_stacked, g_first, g_last = mapped(
            stacked_params, feeds, first_params, last_params, last_feeds)
    return loss, (g_stacked, g_first, g_last)
