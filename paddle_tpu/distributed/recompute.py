"""Activation recompute (``python/paddle/distributed/fleet/recompute/
recompute.py`` parity).

The reference replays forward under saved RNG state inside a PyLayer.
TPU-first: ``jax.checkpoint`` (remat) — XLA rematerializes activations in
backward, trading FLOPs for HBM exactly as the reference does, but
compiler-scheduled. Works in both the eager tape (via jax.vjp over the
remat-wrapped function) and the jitted step.
"""
from __future__ import annotations

import jax

from ..framework.core import Tensor, apply_jax, as_jax

__all__ = ["recompute", "recompute_sequential", "RecomputeFunction"]


def _remat_policy():
    """Checkpoint policy knob (FLAGS_paddle_tpu_remat_policy /
    PADDLE_TPU_REMAT_POLICY): "full" (save nothing — max HBM savings),
    "dots" (save matmul outputs, recompute elementwise — the usual MFU
    sweet spot when HBM allows), "save_attn" (save ONLY the per-layer
    attention outputs tagged with ``checkpoint_name`` — the selective
    policy for deep stacks: cheaper than "dots" in memory, cheaper than
    "full" in recompute FLOPs because the attention block — the most
    expensive thing to rematerialize at long seq — is never replayed),
    "nothing_saveable" alias of full."""
    import os
    from ..base_flags import get_flag, register_flag
    register_flag("FLAGS_paddle_tpu_remat_policy", "full")
    name = os.environ.get("PADDLE_TPU_REMAT_POLICY") or \
        get_flag("FLAGS_paddle_tpu_remat_policy", "full")
    cp = jax.checkpoint_policies
    return {
        "full": None, "nothing_saveable": None,
        "dots": cp.dots_with_no_batch_dims_saveable,
        "dots_saveable": cp.dots_saveable,
        "save_attn": cp.save_only_these_names("attn_out"),
        # dots + tagged attention outputs: backward never replays the
        # flash-attention forward (a pallas custom call the dots policy
        # does not cover) — the deep-stack sweet spot
        "dots_attn": cp.save_from_both_policies(
            cp.dots_with_no_batch_dims_saveable,
            cp.save_only_these_names("attn_out")),
    }.get(name, None)


def recompute(function, *args, **kwargs):
    """``paddle.distributed.fleet.utils.recompute`` parity.

    When ``function`` is a Layer, its parameters are passed as explicit
    VJP inputs (bound by array-swap during the remat call) so the tape
    records their gradients — closed-over params would be invisible."""
    kwargs.pop("preserve_rng_state", True)
    kwargs.pop("use_reentrant", True)

    tensor_args = []
    spec = []
    for a in args:
        if isinstance(a, Tensor):
            spec.append(len(tensor_args))
            tensor_args.append(a)
        else:
            spec.append(a)
    n_act = len(tensor_args)

    params = []
    if hasattr(function, "parameters"):
        params = [p for p in function.parameters()
                  if not p.stop_gradient]

    import functools as _ft

    @_ft.partial(jax.checkpoint, policy=_remat_policy())
    def inner(*arrays):
        rebuilt = []
        for s in spec:
            if isinstance(s, int):
                rebuilt.append(Tensor(arrays[s]))
            else:
                rebuilt.append(s)
        saved = [p._data for p in params]
        try:
            for p, arr in zip(params, arrays[n_act:]):
                p._data = arr
            from ..framework.core import no_grad
            with no_grad():
                out = function(*rebuilt, **kwargs)
        finally:
            for p, arr in zip(params, saved):
                p._data = arr
        if isinstance(out, (tuple, list)):
            return tuple(as_jax(o) for o in out)
        return as_jax(out)

    return apply_jax("recompute", inner, *tensor_args, *params)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """``recompute_sequential`` parity: chunk a Sequential and remat each
    segment."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    chunk = max(len(layers) // max(segments, 1), 1)
    x = args[0]
    for i in range(0, len(layers), chunk):
        seg = layers[i:i + chunk]

        def run_seg(t, seg=seg):
            out = t
            for l in seg:
                out = l(out)
            return out
        x = recompute(run_seg, x)
    return x


RecomputeFunction = recompute
