"""``paddle.distributed.rpc`` (reference: ``paddle/fluid/distributed/
rpc/`` brpc-based RPC + ``python/paddle/distributed/rpc/`` API:
init_rpc / rpc_sync / rpc_async / shutdown / get_worker_info).

TPU-first: the heavy brpc stack serves the parameter-server world; for
the heterogeneous-job coordination this API actually gets used for
(control messages, small python payloads between workers), a socket
server per worker plus the native TCPStore for address discovery is the
whole requirement. Calls pickle (fn, args, kwargs), execute on the
callee's worker thread pool, and return the pickled result — same
at-most-once, raise-on-error semantics as the reference.
"""
from __future__ import annotations

import concurrent.futures
import pickle
import socket
import struct
import threading
from typing import Any, Dict, Optional

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


class WorkerInfo:
    def __init__(self, name, rank, ip, port):
        self.name = name
        self.rank = rank
        self.ip = ip
        self.port = port

    def __repr__(self):
        return (f"WorkerInfo(name={self.name}, rank={self.rank}, "
                f"ip={self.ip}, port={self.port})")


_state: Dict[str, Any] = {}


def _recv_exact(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return buf


def _send_msg(conn, payload: bytes):
    conn.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(conn) -> bytes:
    (n,) = struct.unpack("<Q", _recv_exact(conn, 8))
    if n > (256 << 20):
        raise ValueError(f"rpc payload too large: {n} bytes")
    return _recv_exact(conn, n)


def _serve_loop(server_sock, pool):
    while True:
        try:
            conn, _ = server_sock.accept()
        except OSError:
            return  # closed during shutdown

        def handle(c):
            try:
                with c:
                    try:
                        req = pickle.loads(_recv_msg(c))
                        fn, args, kwargs = req
                        result = ("ok", fn(*args, **kwargs))
                    except Exception as exc:  # ship the callee error
                        result = ("err", exc)
                    try:
                        payload = pickle.dumps(result)
                    except Exception as exc:  # unpicklable result/error
                        payload = pickle.dumps(
                            ("err", RuntimeError(
                                f"rpc result not picklable: {exc!r}")))
                    _send_msg(c, payload)
            except (ConnectionError, OSError):
                pass

        pool.submit(handle, conn)


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's RPC server and register its endpoint with
    the rendezvous store (rank 0 hosts it at master_endpoint)."""
    import os
    from ...native import TCPStore

    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) \
        if rank is None else int(rank)
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else int(world_size)
    master = master_endpoint or os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:8813")
    host, port = master.rsplit(":", 1)

    # Trust model: RPC executes pickled callables from peers, so the
    # server must only be reachable from the training cluster. Bind to
    # the interface that routes to the master (like TCPStore's
    # host-limited bind) — never INADDR_ANY, which would expose an
    # unauthenticated code-execution endpoint on every interface.
    # gethostbyname(gethostname()) is wrong here: many distros map the
    # hostname to 127.0.1.1, which peers cannot reach. A connected UDP
    # socket towards the master yields the actual routed interface.
    if host in ("127.0.0.1", "localhost"):
        my_ip = "127.0.0.1"
    else:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            probe.connect((host, int(port)))
            my_ip = probe.getsockname()[0]
        finally:
            probe.close()
    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind((my_ip, 0))
    server.listen(128)
    my_port = server.getsockname()[1]

    store = TCPStore(host=host, port=int(port), is_master=rank == 0,
                     world_size=world_size, timeout=60.0)
    store.set(f"rpc/worker/{rank}",
              pickle.dumps(WorkerInfo(name, rank, my_ip, my_port)))

    # DISTINCT pools for inbound service vs outbound client calls:
    # sharing one pool deadlocks when outbound calls saturate it and
    # the inbound handlers (that would produce their responses) queue
    pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=8, thread_name_prefix="paddle-rpc-srv")
    client_pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=8, thread_name_prefix="paddle-rpc-cli")
    thread = threading.Thread(target=_serve_loop, args=(server, pool),
                              daemon=True, name="paddle-rpc-server")
    thread.start()

    # wait for the full roster (init_rpc is a barrier in the reference)
    infos = {}
    for r in range(world_size):
        infos[r] = pickle.loads(store.get(f"rpc/worker/{r}"))
    _state.update(dict(name=name, rank=rank, world_size=world_size,
                       store=store, server=server, pool=pool,
                       client_pool=client_pool, infos=infos))
    return infos[rank]


def _resolve(to) -> WorkerInfo:
    if not _state:
        raise RuntimeError("call init_rpc first")
    infos = _state["infos"]
    if isinstance(to, int):
        return infos[to]
    for info in infos.values():
        if info.name == to:
            return info
    raise KeyError(f"unknown rpc worker {to!r}; known: "
                   f"{[i.name for i in infos.values()]}")


def _call(info: WorkerInfo, fn, args, kwargs, timeout):
    with socket.create_connection((info.ip, info.port),
                                  timeout=timeout or None) as conn:
        _send_msg(conn, pickle.dumps((fn, args or (), kwargs or {})))
        status, payload = pickle.loads(_recv_msg(conn))
    if status == "err":
        raise payload
    return payload


def rpc_sync(to, fn, args=None, kwargs=None, timeout=180.0):
    """Execute ``fn(*args, **kwargs)`` on worker ``to``; returns the
    result (callee exceptions re-raise here)."""
    return _call(_resolve(to), fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=180.0):
    """Like rpc_sync but returns a Future (``.wait()`` parity)."""
    info = _resolve(to)
    fut = _state["client_pool"].submit(_call, info, fn, args,
                                       kwargs, timeout)
    fut.wait = fut.result  # paddle Future surface
    return fut


def get_worker_info(name=None) -> WorkerInfo:
    if not _state:
        raise RuntimeError("call init_rpc first")
    if name is None:
        return _state["infos"][_state["rank"]]
    return _resolve(name)


def get_all_worker_infos():
    if not _state:
        raise RuntimeError("call init_rpc first")
    return list(_state["infos"].values())


def shutdown(timeout=60):
    """Graceful two-phase barrier then stop (reference: shutdown
    synchronizes). Workers announce, the master (who HOSTS the store)
    waits for every announcement, publishes the all-clear, and only
    then tears the store down — so no peer polls a dead store.

    ``timeout`` bounds the per-peer wait; long-lived servers (fleet PS
    ``run_server``) pass a large value so they genuinely block until
    the trainers drain instead of tearing down mid-training."""
    if not _state:
        return
    import time
    store = _state["store"]
    rank = _state["rank"]
    store.set(f"rpc/shutdown/{rank}", "1")
    try:
        if rank == 0:
            for r in range(_state["world_size"]):
                store.wait(f"rpc/shutdown/{r}", timeout=timeout)
            store.set("rpc/shutdown/all", "1")
            time.sleep(0.3)  # let peers read the all-clear
        else:
            store.wait("rpc/shutdown/all", timeout=timeout)
    except Exception:
        pass  # a vanished peer/store must not block teardown
    _state["server"].close()
    _state["pool"].shutdown(wait=False)
    _state["client_pool"].shutdown(wait=False)
    store.close()
    _state.clear()
