"""Pipeline parallelism on TPU: GPipe/1F1B as shard_map + collective_permute.

Reference parity: ``python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py`` (PipelineParallel.train_batch, FThenB/1F1B
schedules) + ``pp_utils/p2p_communication.py`` (batched NCCL send/recv).

TPU-first design (SURVEY.md §5.8, §7.4): there is no NCCL p2p — stage
activations ride ``jax.lax.ppermute`` over the ``pp`` mesh axis inside a
``shard_map``; the fill-drain schedule is a ``lax.scan`` over ticks, so
XLA sees one static program and overlaps the permute with stage compute.
All stages execute the same homogeneous stage function with their own
weight shard (stacked params, leading dim sharded over ``pp``), which is
how GSPMD-style pipelining wants it. Backward is just ``jax.grad``
through the scan — ppermute transposes to the reverse permute, giving the
backward pipeline for free (no hand-written 1F1B bookkeeping).
"""
from __future__ import annotations

from typing import Any, Callable, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from . import env as _env

__all__ = ["pipeline_apply", "stack_stage_params", "PipelineStageFn"]

PipelineStageFn = Callable[[Any, jnp.ndarray], jnp.ndarray]


def stack_stage_params(per_stage_params: List[Any]):
    """[stage0_tree, stage1_tree, ...] → one tree with leading pp dim."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def pipeline_apply(stage_fn: PipelineStageFn, stacked_params,
                   microbatches, mesh: Mesh = None, axis: str = "pp",
                   extra_inputs=None, batch_axes=("dp", "sharding")):
    """Run the pipelined forward.

    stage_fn(params_local, x, *extra) -> y  — one stage's compute; must
        be shape-preserving on x (homogeneous stages).
    stacked_params: pytree, leaves [pp, ...] (will be sharded over axis).
    microbatches: [n_micro, mb, ...] array; fed to stage 0 in order.
    batch_axes: mesh axes (those present with size>1) that shard the
        per-microbatch batch dim (dim 1) inside the pipe — data parallel
        composes with pp without leaving the shard_map.
    Returns [n_micro, mb, ...] outputs (valid on every device — the last
    stage's results are broadcast over the pp axis).
    """
    mesh = mesh or _env.get_mesh()
    pp = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    n_ticks = n_micro + pp - 1
    extra = extra_inputs if extra_inputs is not None else ()

    in_spec_params = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)
    # keep a batch axis only while the *product* of kept axes still
    # divides the per-microbatch batch dim (per-axis checks would admit
    # e.g. 2x2 devices for a batch of 2)
    live_batch = []
    _prod = 1
    for a in (batch_axes or ()):
        sz = mesh.shape.get(a, 1)
        if a != axis and sz > 1 and \
                microbatches.shape[1] % (_prod * sz) == 0:
            live_batch.append(a)
            _prod *= sz
    live_batch = tuple(live_batch)
    mb_spec = P(None, live_batch if len(live_batch) > 1
                else (live_batch[0] if live_batch else None),
                *([None] * (microbatches.ndim - 2)))

    def per_device(params_block, mbs, *extra_args):
        # params_block leaves: [1, ...] (this stage's slice)
        params_local = jax.tree_util.tree_map(
            lambda x: x[0], params_block)
        stage_idx = jax.lax.axis_index(axis)
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]

        mb_shape = mbs.shape[1:]
        y0 = jnp.zeros(mb_shape, mbs.dtype)

        def tick(carry, t):
            recv = carry
            feed = jnp.where(t < n_micro, t, 0)
            x_in = jnp.where(stage_idx == 0, mbs[feed], recv)
            y = stage_fn(params_local, x_in, *extra_args)
            send = jax.lax.ppermute(y, axis, perm_fwd)
            # output from the last stage this tick (microbatch t-pp+1)
            out = jnp.where(stage_idx == pp - 1, y,
                            jnp.zeros_like(y))
            return send, out

        _, outs = jax.lax.scan(tick, y0, jnp.arange(n_ticks))
        # outs: [n_ticks, mb...]; last stage's valid range is
        # ticks [pp-1, pp-1+n_micro). psum over pp broadcasts them
        # (all other stages contributed zeros).
        valid = jax.lax.dynamic_slice_in_dim(outs, pp - 1, n_micro, axis=0)
        return jax.lax.psum(valid, axis)

    from .shard_utils import manual_region, shard_map_compat
    mapped = shard_map_compat(
        per_device, mesh,
        (in_spec_params, mb_spec,
         *[P(*([None] * jnp.ndim(e))) for e in extra]),
        mb_spec)
    with manual_region():
        return mapped(stacked_params, microbatches, *extra)
