"""Pipeline parallelism on TPU: GPipe/1F1B as shard_map + collective_permute.

Reference parity: ``python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py`` (PipelineParallel.train_batch, FThenB/1F1B
schedules) + ``pp_utils/p2p_communication.py`` (batched NCCL send/recv).

TPU-first design (SURVEY.md §5.8, §7.4): there is no NCCL p2p — stage
activations ride ``jax.lax.ppermute`` over the ``pp`` mesh axis inside a
``shard_map``; the fill-drain schedule is a ``lax.scan`` over ticks, so
XLA sees one static program and overlaps the permute with stage compute.
All stages execute the same homogeneous stage function with their own
weight shard (stacked params, leading dim sharded over ``pp``), which is
how GSPMD-style pipelining wants it. Backward is just ``jax.grad``
through the scan — ppermute transposes to the reverse permute, giving the
backward pipeline for free (no hand-written 1F1B bookkeeping).
"""
from __future__ import annotations

from typing import Any, Callable, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from . import env as _env

__all__ = ["pipeline_apply", "stack_stage_params", "PipelineStageFn"]

PipelineStageFn = Callable[[Any, jnp.ndarray], jnp.ndarray]


def stack_stage_params(per_stage_params: List[Any]):
    """[stage0_tree, stage1_tree, ...] → one tree with leading pp dim."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def _live_batch_axes(mesh, axis, batch_axes, mb_dim):
    """Mesh axes that may shard the per-microbatch batch dim: keep an
    axis only while the *product* of kept axes still divides it."""
    live = []
    prod = 1
    for a in (batch_axes or ()):
        sz = mesh.shape.get(a, 1)
        if a != axis and sz > 1 and mb_dim % (prod * sz) == 0:
            live.append(a)
            prod *= sz
    live = tuple(live)
    return live if len(live) > 1 else (live[0] if live else None)


def pipeline_apply(stage_fn: PipelineStageFn, stacked_params,
                   microbatches, mesh: Mesh = None, axis: str = "pp",
                   extra_inputs=None, batch_axes=("dp", "sharding"),
                   first_fn=None, first_params=None,
                   last_fn=None, last_params=None, last_feeds=None,
                   remat=False):
    """Run the pipelined forward.

    stage_fn(params_local, x, *extra) -> y  — one stage's compute; must
        be shape-preserving on x (homogeneous stages).
    stacked_params: pytree, leaves [pp, ...] (will be sharded over axis).
    microbatches: [n_micro, mb, ...] array; fed to stage 0 in order.
    batch_axes: mesh axes (those present with size>1) that shard the
        per-microbatch batch dim (dim 1) inside the pipe — data parallel
        composes with pp without leaving the shard_map.

    Heterogeneous first/last stages (the reference's first/last-stage
    special-casing in ``pipeline_parallel.py``):

    first_fn(first_params, feed_mb, *extra) -> h  — runs ONLY on stage 0,
        per tick, converting the raw feed microbatch (e.g. token ids)
        into the ring's boundary activation. Its work overlaps the
        pipeline instead of running replicated up front.
    last_fn(last_params, y, last_feed_mb, *extra) -> out  — runs ONLY on
        the last stage (head / loss prep). ``last_feeds`` is an optional
        [n_micro, ...] per-micro side input (e.g. labels).
    remat=True checkpoints stage_fn so the backward recomputes stage
        interiors — per-device live activations are the per-tick BOUNDARY
        tensors only (the GPipe+remat memory regime; see
        ``pipeline_1f1b`` for the O(pp) schedule).

    Returns [n_micro, ...] outputs (valid on every device — the last
    stage's results are broadcast over the pp axis).
    """
    mesh = mesh or _env.get_mesh()
    pp = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    n_ticks = n_micro + pp - 1
    extra = extra_inputs if extra_inputs is not None else ()
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    in_spec_params = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)
    batch_spec = _live_batch_axes(mesh, axis, batch_axes,
                                  microbatches.shape[1])
    mb_spec = P(None, batch_spec, *([None] * (microbatches.ndim - 2)))
    _axes = (batch_spec,) if isinstance(batch_spec, str) \
        else (batch_spec or ())
    _prod = int(np.prod([mesh.shape[a] for a in _axes])) if _axes else 1
    local_mb = microbatches.shape[1] // _prod

    # boundary activation spec (ring dtype/shape) — PER-DEVICE view:
    # the batch dim inside shard_map is the local shard
    local_feed = jax.ShapeDtypeStruct(
        (local_mb,) + microbatches.shape[2:], microbatches.dtype)
    if first_fn is not None:
        h_struct = jax.eval_shape(
            lambda p, x, *e: first_fn(p, x, *e),
            first_params, local_feed, *extra)
    else:
        h_struct = local_feed
    if last_fn is not None:
        lf_struct = None if last_feeds is None else jax.ShapeDtypeStruct(
            last_feeds.shape[1:], last_feeds.dtype)
        out_struct = jax.eval_shape(
            lambda p, y, lf, *e: last_fn(p, y, lf, *e),
            last_params, h_struct, lf_struct, *extra)
    else:
        out_struct = h_struct
    out_spec = P(None) if out_struct.ndim == 0 else P(
        None, batch_spec if out_struct.shape[0] == local_mb else None,
        *([None] * (out_struct.ndim - 1)))

    rep = lambda tree: jax.tree_util.tree_map(
        lambda x: P(*([None] * jnp.ndim(x))), tree)

    def per_device(params_block, mbs, fparams, lparams, lfeeds,
                   *extra_args):
        # params_block leaves: [1, ...] (this stage's slice)
        params_local = jax.tree_util.tree_map(
            lambda x: x[0], params_block)
        stage_idx = jax.lax.axis_index(axis)
        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]

        y0 = jnp.zeros(h_struct.shape, h_struct.dtype)

        def tick(carry, t):
            recv = carry
            feed = jnp.where(t < n_micro, t, 0)
            if first_fn is not None:
                x_first = jax.lax.cond(
                    stage_idx == 0,
                    lambda: first_fn(fparams, mbs[feed], *extra_args),
                    lambda: jnp.zeros(h_struct.shape, h_struct.dtype))
                x_in = jnp.where(stage_idx == 0, x_first, recv)
            else:
                x_in = jnp.where(stage_idx == 0, mbs[feed], recv)
            y = stage_fn(params_local, x_in, *extra_args)
            send = jax.lax.ppermute(y, axis, perm_fwd)
            if last_fn is not None:
                oidx = jnp.clip(t - (pp - 1), 0, n_micro - 1)
                lf = None if lfeeds is None else lfeeds[oidx]
                out = jax.lax.cond(
                    stage_idx == pp - 1,
                    lambda: last_fn(lparams, y, lf, *extra_args),
                    lambda: jnp.zeros(out_struct.shape, out_struct.dtype))
            else:
                # output from the last stage this tick
                out = jnp.where(stage_idx == pp - 1, y,
                                jnp.zeros_like(y))
            return send, out

        _, outs = jax.lax.scan(tick, y0, jnp.arange(n_ticks))
        # outs: [n_ticks, ...]; last stage's valid range is
        # ticks [pp-1, pp-1+n_micro). psum over pp broadcasts them
        # (all other stages contributed zeros).
        valid = jax.lax.dynamic_slice_in_dim(outs, pp - 1, n_micro, axis=0)
        return jax.lax.psum(valid, axis)

    # per-micro labels must follow the same batch sharding as the
    # microbatches, or dp shards would pair local activations with the
    # GLOBAL label slice
    lf_spec = None if last_feeds is None else P(
        None, batch_spec if last_feeds.shape[1] == microbatches.shape[1]
        else None, *([None] * (last_feeds.ndim - 2)))

    from .shard_utils import manual_region, shard_map_compat
    mapped = shard_map_compat(
        per_device, mesh,
        (in_spec_params, mb_spec, rep(first_params), rep(last_params),
         lf_spec,
         *[P(*([None] * jnp.ndim(e))) for e in extra]),
        out_spec)
    with manual_region():
        return mapped(stacked_params, microbatches, first_params,
                      last_params, last_feeds, *extra)
