"""GSPMD sharding helpers shared by TP/SP/sharding/auto-parallel layers.

Design (SURVEY.md §7.2): parallel layers are *facades that set
PartitionSpecs*. Parameters carry ``dist_spec``; activations get
``with_sharding_constraint`` hints; XLA/GSPMD inserts the collectives the
reference implements by hand in ``ProcessGroupNCCL``.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from . import env as _env

__all__ = ["P", "mesh_axis_size", "annotate_param", "constraint",
           "place_param", "batch_shard", "current_mesh", "manual_region",
           "in_manual_region"]


def current_mesh() -> Optional[Mesh]:
    return _env.get_mesh()


# Inside a shard_map body the mesh axes are Manual — GSPMD constraint /
# reshard ops emitted there (by TP layers etc.) are invalid. The pipeline
# engine traces its stage functions under this flag so the sharding
# facades become identities; the shard_map in/out specs already define
# the data placement.
import contextlib as _contextlib
import threading as _threading

_manual_tls = _threading.local()


@_contextlib.contextmanager
def manual_region():
    prev = getattr(_manual_tls, "on", False)
    _manual_tls.on = True
    try:
        yield
    finally:
        _manual_tls.on = prev


def in_manual_region() -> bool:
    return getattr(_manual_tls, "on", False)


def shard_map_compat(f, mesh, in_specs, out_specs):
    """shard_map across jax versions (check_rep→check_vma rename)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        except TypeError:
            return sm(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as esm
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def mesh_axis_size(axis) -> int:
    mesh = current_mesh()
    if mesh is None:
        return 1
    if isinstance(axis, (tuple, list)):
        n = 1
        for a in axis:
            n *= mesh.shape.get(a, 1)
        return n
    return mesh.shape.get(axis, 1)


def annotate_param(param: Tensor, spec: Sequence):
    """Attach a PartitionSpec to a parameter and (eagerly) place it."""
    param.dist_spec = P(*spec)
    place_param(param)
    return param


def place_param(param: Tensor):
    mesh = current_mesh()
    spec = getattr(param, "dist_spec", None)
    if mesh is None or spec is None:
        return param
    # only shard axes that exist with size>1; GSPMD treats missing as
    # replicated
    try:
        param._data = jax.device_put(param._data,
                                     NamedSharding(mesh, spec))
    except Exception:
        pass  # mesh smaller than spec (e.g. degree 1) -> replicated
    return param


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def constraint(x, *spec):
    """with_sharding_constraint as a differentiable identity op."""
    mesh = current_mesh()
    if mesh is None or in_manual_region():
        return x if isinstance(x, Tensor) else _wrap_out(as_jax(x))
    sharding = NamedSharding(mesh, P(*spec))

    def f(a):
        try:
            return jax.lax.with_sharding_constraint(a, sharding)
        except Exception:
            return a
    return apply_jax("sharding_constraint", f, x)


def batch_shard(x, axes=("dp", "sharding", "ep")):
    """Shard the leading (batch) dim over the data-parallel axes (the
    expert axis carries tokens too: EP shards the batch like DP and
    exchanges (token, slot) pairs by all-to-all inside the MoE
    dispatch)."""
    mesh = current_mesh()
    if mesh is None or in_manual_region():
        return x
    live = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
    if not live:
        return x
    arr = as_jax(x)
    spec = P(live) if len(live) > 1 else P(live[0])
    full = P(*([spec[0]] + [None] * (arr.ndim - 1)))
    if _is_tracer(arr):
        out = jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, full))
    else:
        out = jax.device_put(arr, NamedSharding(mesh, full))
    if isinstance(x, Tensor):
        x._data = out
        return x
    return _wrap_out(out)
