"""Mixture-of-Experts with expert parallelism
(``python/paddle/incubate/distributed/models/moe/moe_layer.py`` +
``gate/*.py`` parity).

TPU-first (SURVEY.md §7.4): GShard-style static-capacity dispatch. Expert
weights are stacked with a leading expert dim sharded over the expert
axis; dispatch/combine are einsums against one-hot capacity masks, so the
all-to-all the reference codes against ProcessGroup appears as GSPMD
collectives when the expert dim is mesh-sharded. Static shapes throughout
(capacity padding), as jit requires.
"""
from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ..nn import functional as F
from ..nn.layer.layers import Layer
from .shard_utils import annotate_param, constraint, mesh_axis_size

__all__ = ["MoELayer", "NaiveGate", "GShardGate", "SwitchGate",
           "moe_dispatch_combine", "ClipGradForMOEByGlobalNorm"]


from ..nn.clip import ClipGradByGlobalNorm as _ClipGradByGlobalNorm


class ClipGradForMOEByGlobalNorm(_ClipGradByGlobalNorm):
    """MoE-aware global-norm clip (reference:
    ``incubate/distributed/models/moe/grad_clip.py``). The reference
    splits (param, grad) pairs into expert / non-expert sets and
    all-reduces the expert-set norm over the moe group, because with EP
    each rank holds only its local experts; expert_sq + normal_sq is
    then the true global norm. TPU-first: expert params are stacked
    GSPMD arrays that are *logically global*, so the plain global norm
    over all grads is already the same quantity — this subclass exists
    so reference scripts construct the same class name, and keeps the
    constructor surface (predicate/group args are metadata here)."""

    def __init__(self, clip_norm, is_expert_param_func=None,
                 moe_group=None, group_name="default_moe_group"):
        super().__init__(clip_norm, group_name=group_name)
        self.is_expert_param_func = is_expert_param_func
        self.moe_group = moe_group


class BaseGate(Layer):
    def __init__(self, d_model, num_expert):
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert
        self.loss = None


class NaiveGate(BaseGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(d_model, num_expert)
        from ..nn.layer.common import Linear
        self.gate = Linear(d_model, num_expert)
        self.top_k = topk

    def forward(self, x):
        return self.gate(x)


class GShardGate(NaiveGate):
    """GShard top-2 gate (``gate/gshard_gate.py`` parity): the 2nd-choice
    expert receives the token only with probability ``min(1, 2*g2)``
    (GShard's random routing), sampled per token during training."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), group=None, gate_bias=True):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity_factor = capacity[0]
        self.second_expert_policy = "random"


class SwitchGate(NaiveGate):
    """Switch top-1 gate (``gate/switch_gate.py`` parity): multiplicative
    jitter noise ``U(1-eps, 1+eps)`` on the router input during
    training; capacity-drop statistics surface via ``drop_rate``."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.capacity_factor = capacity[0]
        self.switch_eps = float(switch_eps)

    def forward(self, x):
        if self.training and self.switch_eps > 0:
            from ..framework import random as _random
            key = _random.next_key()
            eps = self.switch_eps

            def jitter(a):
                noise = jax.random.uniform(
                    key, a.shape, jnp.float32, 1.0 - eps, 1.0 + eps)
                return a * noise.astype(a.dtype)
            x = apply_jax("switch_jitter", jitter, x)
        return self.gate(x)


# ---------------------------------------------------------------------------
# Gather-only dispatch plumbing.
#
# The (token, slot) -> (expert, capacity-slot) mapping is a partial
# permutation whose inverse we hold explicitly (one tiny int32 scatter
# builds it), so BOTH autodiff directions of pack/combine can be row
# gathers. XLA cannot know a scatter's indices are unique, so its
# scatter-add lowering serializes on TPU; these custom VJPs replace every
# float scatter in the MoE fwd+bwd with a gather (measured 10.5 -> 7.9
# ms/block fwd+bwd at the bench shapes [s=8192, d=1024, e=32, k=4]).
# ---------------------------------------------------------------------------

import functools as _functools


def _positions(onehot, flat_e):
    """(pos_within_expert [N], counts [E]) from routing one-hots.

    A plain ``jnp.cumsum`` over N=32k rows lowers to a long serial
    scan on TPU (~1.4 ms at bench shapes); chunking into 128-row tiles
    turns it into one batched triangular f32 matmul (MXU) plus a
    256-step scan over chunk totals (0.93 ms, bit-exact — f32 is exact
    for counts < 2^24)."""
    n, e = onehot.shape
    if n % 128 or n < 256:
        cum = jnp.cumsum(onehot, axis=0) - onehot
        pos = jnp.take_along_axis(cum, flat_e[:, None], axis=1)[:, 0]
        return pos.astype(jnp.int32), jnp.sum(onehot, axis=0)
    c = 128
    nc = n // c
    x = onehot.reshape(nc, c, e).astype(jnp.float32)
    tri = jnp.tril(jnp.ones((c, c), jnp.float32), k=-1)  # exclusive
    within = jnp.einsum("ij,nje->nie", tri, x)
    chunk_tot = x.sum(axis=1)
    offs = jnp.cumsum(chunk_tot, axis=0) - chunk_tot
    pos = (within + offs[:, None, :]).reshape(n, e)
    pos = jnp.take_along_axis(pos, flat_e[:, None], axis=1)[:, 0]
    return pos.astype(jnp.int32), chunk_tot.sum(axis=0).astype(
        onehot.dtype)


@_functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _moe_pack(x, src_row, filled, dest, top_k):
    """expert_in[e, c] = x[src_row[e, c]] * filled[e, c].

    src_row: [e, c] token id feeding each expert slot (any value where
    unfilled); filled: [e, c] bool; dest: [s, k] int32 flat index of each
    (token, slot) in the padded [e * (c+1)] layout (sentinel column c for
    dropped slots) — used only by the backward gather.
    """
    ei = jnp.take(x, src_row, axis=0)
    return ei * filled[..., None].astype(x.dtype)


def _moe_pack_fwd(x, src_row, filled, dest, top_k):
    out = _moe_pack(x, src_row, filled, dest, top_k)
    return out, (out.shape[:2], dest)


def _moe_pack_bwd(top_k, res, g):
    (e, c), dest = res
    # dx[s] = sum_k g[dest(s, k)]; pad a zero sentinel column per expert
    # so dropped slots read zeros instead of needing a mask
    gf = jnp.pad(g, ((0, 0), (0, 1), (0, 0))).reshape(e * (c + 1), -1)
    rows = jnp.take(gf, dest.reshape(-1), axis=0)
    dx = rows.reshape(-1, top_k, gf.shape[-1]).sum(axis=1)
    return (dx.astype(g.dtype), None, None, None)


_moe_pack.defvjp(_moe_pack_fwd, _moe_pack_bwd)


@jax.custom_vjp
def _moe_combine(expert_out, gates, dest, src_row, filled, gates_ec):
    """y[s] = sum_k gates[s, k] * expert_out[dest(s, k)].

    gates_ec: [e, c] the gate weight of the (token, slot) feeding each
    expert slot (zero where unfilled) — the backward gather's coefficient.
    """
    e, c, d = expert_out.shape
    eof = jnp.pad(expert_out, ((0, 0), (0, 1), (0, 0))) \
        .reshape(e * (c + 1), d)
    k = dest.shape[1]
    picked = jnp.take(eof, dest.reshape(-1), axis=0).reshape(-1, k, d)
    return jnp.einsum("sk,skd->sd", gates.astype(expert_out.dtype),
                      picked)


def _moe_combine_fwd(expert_out, gates, dest, src_row, filled, gates_ec):
    y = _moe_combine(expert_out, gates, dest, src_row, filled, gates_ec)
    return y, (expert_out, gates, dest, src_row, filled, gates_ec)


def _moe_combine_bwd(res, dy):
    expert_out, gates, dest, src_row, filled, gates_ec = res
    e, c, d = expert_out.shape
    k = dest.shape[1]
    # d_expert_out[e, c] = dy[src_row] * gate-of-that-slot  (gather)
    deo = jnp.take(dy, src_row, axis=0)
    coef = (gates_ec * filled.astype(gates_ec.dtype))
    deo = deo * coef[..., None].astype(dy.dtype)
    # d_gates[s, k] = <dy[s], expert_out[dest(s, k)]>
    eof = jnp.pad(expert_out, ((0, 0), (0, 1), (0, 0))) \
        .reshape(e * (c + 1), d)
    picked = jnp.take(eof, dest.reshape(-1), axis=0).reshape(-1, k, d)
    dgates = jnp.einsum("sd,skd->sk", dy.astype(jnp.float32),
                        picked.astype(jnp.float32))
    return (deo.astype(expert_out.dtype), dgates.astype(gates.dtype),
            None, None, None, None)


_moe_combine.defvjp(_moe_combine_fwd, _moe_combine_bwd)


@jax.custom_vjp
def _perm_rows(x, idx, inv_idx):
    """y[i] = x[idx[i]] where idx is a permutation with inverse inv_idx
    (backward is the inverse gather, never a scatter)."""
    return jnp.take(x, idx, axis=0)


def _perm_rows_fwd(x, idx, inv_idx):
    return jnp.take(x, idx, axis=0), (idx, inv_idx)


def _perm_rows_bwd(res, g):
    idx, inv_idx = res
    return (jnp.take(g, inv_idx, axis=0), None, None)


_perm_rows.defvjp(_perm_rows_fwd, _perm_rows_bwd)


@_functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _expand_sort(x, src_tok, rank, top_k):
    """xs[r] = x[src_tok[r]]: expand each token to its top_k slots in
    expert-sorted order. rank: [s * k] position of (token, slot) in the
    sorted order (token-major) — the inverse mapping for the backward
    gather: dx[s] = sum_k g[rank[s * k + k]]."""
    return jnp.take(x, src_tok, axis=0)


def _expand_sort_fwd(x, src_tok, rank, top_k):
    return jnp.take(x, src_tok, axis=0), (rank,)


def _expand_sort_bwd(top_k, res, g):
    (rank,) = res
    rows = jnp.take(g, rank, axis=0)               # token-major [s*k, d]
    dx = rows.reshape(-1, top_k, g.shape[-1]).sum(axis=1)
    return (dx.astype(g.dtype), None, None)


_expand_sort.defvjp(_expand_sort_fwd, _expand_sort_bwd)


def moe_dispatch_combine(x, gate_logits, num_expert, top_k=2,
                         capacity_factor=1.25, expert_fn=None,
                         expert_axis=None, normalize_gates=True,
                         second_expert_policy="all", rng_key=None,
                         return_stats=False):
    """Pure-array GShard dispatch → expert_fn → combine.

    x: [tokens, d]; gate_logits: [tokens, e]. expert_fn(inputs[e, c, d])
    -> [e, c, d]. Returns (y [tokens, d], aux_loss scalar), plus a stats
    dict (capacity ``drop_rate``) when ``return_stats``.
    ``normalize_gates=False`` combines with the raw softmax probs of the
    selected experts (Qwen2-MoE/DeepSeek ``norm_topk_prob=False``).
    ``second_expert_policy="random"`` + ``rng_key`` enables GShard's
    random routing: slot j>=1 dispatches with probability
    ``min(1, k * g_j)``.

    Pack and combine are gather-only in both autodiff directions (see
    the custom-VJP helpers above); the single scatter left is the int32
    slot-occupancy map, which is negligible next to the float traffic.
    """
    s, d = x.shape
    e = num_expert
    c = max(int(math.ceil(capacity_factor * s * top_k / e)), 1)

    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    # top-k selection
    topk_prob, topk_idx = jax.lax.top_k(probs, top_k)  # [s, k]

    # position of each (token, k) within its expert's queue
    onehot = jax.nn.one_hot(topk_idx, e, dtype=jnp.int32)  # [s, k, e]

    sel = None
    if second_expert_policy == "random" and rng_key is not None \
            and top_k >= 2:
        u = jax.random.uniform(rng_key, (s, top_k))
        sel = u < jnp.minimum(top_k * topk_prob, 1.0)
        sel = sel.at[:, 0].set(True)  # 1st choice always dispatches
        onehot = onehot * sel[..., None].astype(onehot.dtype)

    flat = onehot.reshape(s * top_k, e)
    # chunked MXU scan (see _positions) instead of a serial cumsum
    pos, _counts = _positions(flat, topk_idx.reshape(-1).astype(
        jnp.int32))
    pos = pos.reshape(s, top_k)
    slot_used = jnp.sum(onehot, axis=-1) > 0  # [s, k]
    keep = (pos < c) & slot_used

    # load-balancing aux loss (GShard eq.: e * sum(me * ce))
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(onehot[:, 0].astype(jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)

    # random-skipped slots are zeroed BEFORE normalization (GShard/
    # fairseq top2gating order): a token whose 2nd expert was skipped
    # combines with weight ~1.0, not g1/(g1+g2)
    eff_prob = topk_prob if sel is None \
        else topk_prob * sel.astype(topk_prob.dtype)
    if normalize_gates:
        gates = eff_prob / jnp.maximum(
            jnp.sum(eff_prob, axis=-1, keepdims=True), 1e-9)
    else:
        gates = eff_prob
    gates = jnp.where(keep, gates, 0.0).astype(x.dtype)

    # slot-occupancy map: one int32 scatter builds the inverse of the
    # (token, slot) -> (expert, pos) mapping; dropped slots land in a
    # per-expert sentinel column that pack/combine read as zeros
    flat_e = topk_idx.reshape(-1)                       # [s*k]
    flat_p = jnp.where(keep, pos, c).reshape(-1)        # [s*k]
    dest = (flat_e * (c + 1) + flat_p).astype(jnp.int32)
    inv = jnp.zeros(e * (c + 1), jnp.int32)
    inv = inv.at[dest].set(jnp.arange(s * top_k, dtype=jnp.int32) + 1)
    inv = inv.reshape(e, c + 1)[:, :c]                  # [e, c]
    src_slot = jnp.maximum(inv - 1, 0)
    src_row = src_slot // top_k                         # token per slot
    filled = inv > 0
    gates_ec = jnp.take(gates.reshape(-1), src_slot.reshape(-1)) \
        .reshape(e, c)
    dest = dest.reshape(s, top_k)

    expert_in = _moe_pack(x, src_row, filled, dest, top_k)
    if expert_axis is not None:
        expert_in = _ep_constraint(expert_in, expert_axis)
    expert_out = expert_fn(expert_in)          # [e, c, d_out]
    if expert_axis is not None:
        expert_out = _ep_constraint(expert_out, expert_axis)
    y = _moe_combine(expert_out, gates, dest, src_row, filled, gates_ec)
    if return_stats:
        # fraction of requested (token, slot) dispatches that were
        # dropped — capacity overflow plus random-routing skips
        stats = {"drop_rate": 1.0 - jnp.sum(keep.astype(jnp.float32))
                 / float(s * top_k)}
        return y, aux, stats
    return y, aux


# megablox grouped-matmul tilings tuned on the bench shapes (v5e: the
# (m, k, n) tile must keep the last two block dims 8/128-aligned).
# Backward kernels (transposed gmm + tgmm) prefer the smaller k tile:
# tgmm at [32768, 1024->1408] measured 3.30 ms with (512,1024,512) vs
# 2.32 with (512,512,512)
_GMM_TILING = (512, 1024, 512)
_GMM_TILING_BWD = (512, 512, 512)


@_functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gmm32(lhs, rhs, group_sizes, tiling):
    """megablox gmm with every Pallas trace under disable_x64.

    The stock ``megablox.ops.gmm`` custom VJP traces its backward
    kernels when jax.grad runs — outside any caller context manager —
    and under jax_enable_x64 (the framework default) a weak-f64 constant
    makes Mosaic's convert lowering recurse forever. This wrapper owns
    the VJP so fwd AND bwd kernels trace in 32-bit mode.
    """
    import importlib
    # the megablox package re-exports a FUNCTION named gmm that shadows
    # the module of the same name; importlib reaches the module
    _mb = importlib.import_module(
        "jax.experimental.pallas.ops.tpu.megablox.gmm")
    from ..ops.pallas.flash_attention_kernel import disable_x64
    with disable_x64():
        return _mb.gmm(lhs, rhs, group_sizes,
                       preferred_element_type=lhs.dtype, tiling=tiling)


def _gmm32_fwd(lhs, rhs, group_sizes, tiling):
    return _gmm32(lhs, rhs, group_sizes, tiling), (lhs, rhs, group_sizes)


def _gmm32_bwd(tiling, res, g):
    import importlib
    _mb = importlib.import_module(
        "jax.experimental.pallas.ops.tpu.megablox.gmm")
    from ..ops.pallas.flash_attention_kernel import disable_x64
    lhs, rhs, gs = res
    with disable_x64():
        dlhs = _mb.gmm(g, rhs, gs, preferred_element_type=lhs.dtype,
                       tiling=_GMM_TILING_BWD, transpose_rhs=True)
        drhs = _mb.tgmm(lhs.swapaxes(0, 1), g, gs,
                        preferred_element_type=rhs.dtype,
                        tiling=_GMM_TILING_BWD,
                        num_actual_groups=rhs.shape[0])
    return dlhs.astype(lhs.dtype), drhs.astype(rhs.dtype), None


_gmm32.defvjp(_gmm32_fwd, _gmm32_bwd)


def _use_megablox(n_rows, d_in, d_out):
    """The Pallas grouped-matmul kernel beats lax.ragged_dot on real TPU
    at MXU-scale shapes (measured 2.25 -> 1.70 ms at [32768, 1024, 1408])
    but needs a tpu backend and 8-aligned dims (its TILE dims carry the
    (8, 128) rule; array dims only need sublane alignment — d=704 works
    under the fixed (512, 1024, 512) tiling). Everything else (CPU test
    meshes, tiny shapes, expert-sharded runs where GSPMD owns the
    partitioning) takes the ragged_dot path, as does any shape the
    kernel rejects at trace time (see the fallback in the caller)."""
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:
        return False
    return (n_rows >= 1024 and d_in % 8 == 0 and d_out % 8 == 0)


def moe_dispatch_combine_dropless(x, gate_logits, num_expert, top_k,
                                  gate_up, down, normalize_gates=True,
                                  expert_axis=None, return_stats=False):
    """DROPLESS dispatch → SwiGLU experts → combine (reference:
    capacity-free routing the fused-MoE kernels in
    ``phi/kernels/fusion/`` approximate; design follows the MegaBlocks
    grouped-matmul formulation).

    No capacity factor and no dropped tokens: (token, slot) pairs are
    grouped by expert and the expert MLP runs as TWO grouped matmuls —
    the megablox Pallas kernel on real TPU (tiles each ragged expert
    segment onto the MXU), ``jax.lax.ragged_dot`` elsewhere. The sorted
    order is derived WITHOUT an argsort: position-within-expert comes
    from a cumsum over the routing one-hots, and
    ``rank = group_start[expert] + pos`` is itself the inverse
    permutation, so sort and unsort are gathers in both autodiff
    directions (``_expand_sort`` / ``_perm_rows`` custom VJPs). Under an
    expert-sharded mesh the cross-device exchange this implies is
    ``ragged_all_to_all``; inside one jitted program GSPMD inserts the
    equivalent collectives from the sharding annotations.

    x: [s, d]; gate_logits: [s, e]; gate_up: [e, d, 2f]; down: [e, f, d].
    Returns (y [s, d], aux) (+ stats dict with drop_rate=0.0).
    """
    s, d = x.shape
    e = num_expert
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    topk_prob, topk_idx = jax.lax.top_k(probs, top_k)       # [s, k]

    # group (token, slot) pairs by destination expert via cumsum-rank:
    # rank[i] = start of expert(i)'s segment + arrival position
    # (chunked MXU scan — see _positions)
    flat_e = topk_idx.reshape(-1)                           # [s*k]
    onehot = jax.nn.one_hot(flat_e, e, dtype=jnp.int32)     # [s*k, e]
    pos, counts = _positions(onehot, flat_e.astype(jnp.int32))
    starts = jnp.cumsum(counts) - counts
    rank = (starts[flat_e] + pos).astype(jnp.int32)         # inverse perm
    order = jnp.zeros(s * top_k, jnp.int32).at[rank].set(
        jnp.arange(s * top_k, dtype=jnp.int32))
    group_sizes = counts.astype(jnp.int32)

    xs = _expand_sort(x, order // top_k, rank, top_k)       # [s*k, d]

    # expert weights shard over the EP axis (same constraint the
    # capacity path puts on its expert buffers); GSPMD turns the
    # token-side exchange into the ragged all-to-all equivalent
    sharded = False
    if expert_axis is not None:
        sharded = mesh_axis_size(expert_axis) > 1
        gate_up = _ep_constraint(gate_up, expert_axis)
        down = _ep_constraint(down, expert_axis)
    f2 = gate_up.shape[-1]
    ys = None
    if not sharded and _use_megablox(s * top_k, d, f2) \
            and _use_megablox(s * top_k, f2 // 2, d):
        try:
            gu = _gmm32(xs, gate_up.astype(xs.dtype), group_sizes,
                        _GMM_TILING)
            g, u = jnp.split(gu, 2, axis=-1)
            h = (jax.nn.silu(g.astype(jnp.float32)).astype(xs.dtype)
                 * u)
            ys = _gmm32(h, down.astype(xs.dtype), group_sizes,
                        _GMM_TILING)
        except Exception as exc:
            # shape the kernel rejects at trace time -> ragged_dot.
            # Scope note: this guards the FORWARD trace only; _gmm32's
            # backward traces inside jax.grad with the same tiling and
            # the same (8, 128) block alignment (dims swapped), so a
            # shape that passes here passes there. Warn so a fallback
            # is never a silent perf downgrade.
            import warnings
            warnings.warn(
                "moe dropless: megablox gmm unavailable for shape "
                f"[{s * top_k}, {d}] x [{e}, {d}, {f2}] ({exc!r}); "
                "using lax.ragged_dot")
            ys = None
    if ys is None:
        gu = jax.lax.ragged_dot(xs, gate_up.astype(xs.dtype),
                                group_sizes)
        g, u = jnp.split(gu, 2, axis=-1)
        h = (jax.nn.silu(g.astype(jnp.float32)).astype(xs.dtype) * u)
        ys = jax.lax.ragged_dot(h, down.astype(xs.dtype), group_sizes)

    # unsort back to (token, slot) order and combine — both directions
    # of the permutation are gathers (custom VJP)
    picked = _perm_rows(ys, rank, order).reshape(s, top_k, -1)

    if normalize_gates:
        gates = topk_prob / jnp.maximum(
            jnp.sum(topk_prob, axis=-1, keepdims=True), 1e-9)
    else:
        gates = topk_prob
    y = jnp.einsum("sk,skd->sd", gates.astype(x.dtype), picked)

    # same GShard load-balance aux as the capacity path
    me = jnp.mean(probs, axis=0)
    onehot0 = jax.nn.one_hot(topk_idx[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.sum(me * jnp.mean(onehot0, axis=0))
    if return_stats:
        return y, aux, {"drop_rate": jnp.float32(0.0)}
    return y, aux


def _ep_constraint(arr, axis):
    from . import env as _env
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _env.get_mesh()
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return arr
    spec = P(*([axis] + [None] * (arr.ndim - 1)))
    try:
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, spec))
    except Exception as exc:
        import warnings
        warnings.warn(
            f"moe: expert-parallel sharding constraint on axis {axis!r} "
            f"failed ({exc!r}); expert compute stays replicated")
        return arr


class MoELayer(Layer):
    """``MoELayer`` parity. experts: list of Layers (one per local
    expert) with identical structure; their params are stacked into
    [e, ...] arrays sharded over ``moe_axis``."""

    def __init__(self, d_model, experts: List[Layer] = None, gate=None,
                 moe_group=None, mp_group=None, recompute_interval=0,
                 top_k=2, capacity_factor=None, moe_axis="dp", **kwargs):
        super().__init__()
        self.d_model = d_model
        from ..nn.layer.container import LayerList
        self.experts = LayerList(experts or [])
        self.num_expert = len(self.experts)
        if gate is None or isinstance(gate, dict):
            cfg = gate or {}
            gtype = cfg.get("type", "gshard")
            topk = cfg.get("top_k", top_k)
            cls = {"naive": NaiveGate, "gshard": GShardGate,
                   "switch": SwitchGate}[gtype]
            gate = cls(d_model, self.num_expert, topk=topk)
        self.gate = gate
        self.top_k = getattr(gate, "top_k", top_k)
        # explicit layer arg wins; else the gate's capacity; else 1.25
        if capacity_factor is not None:
            self.capacity_factor = capacity_factor
        else:
            gate_cap = getattr(gate, "capacity_factor", None)
            self.capacity_factor = 1.25 if gate_cap is None else gate_cap
        self.moe_axis = moe_axis
        # stacked expert params: [e, ...] (template = expert 0)
        self._template = self.experts[0] if self.num_expert else None
        # mark for MoE-aware grad clip (ClipGradForMOEByGlobalNorm)
        for exp in self.experts:
            for p in exp.parameters():
                p.is_expert_param = True
        self.drop_rate = None

    def _flat_params(self):
        """All expert params expert-major, as the live Tensor objects (so
        the tape records grads against each expert's own parameters)."""
        items = [list(exp.named_parameters()) for exp in self.experts]
        n_per = len(items[0])
        flat = [p for exp_items in items for _, p in exp_items]
        return n_per, flat

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        from ..ops.manipulation import reshape
        x2 = reshape(x, [-1, d])
        logits = self.gate(x2)
        n_per, flat_params = self._flat_params()
        e = self.num_expert
        template = self._template
        param_objs = [p for _, p in template.named_parameters()]

        second_policy = getattr(self.gate, "second_expert_policy", "all")
        rng_key = None
        if second_policy == "random" and self.training:
            from ..framework import random as _random
            rng_key = _random.next_key()

        def f(x_arr, logit_arr, *flat):
            # restack [e, ...] per param position from the flat operands
            stk = [jnp.stack([flat[i * n_per + j] for i in range(e)],
                             axis=0) for j in range(n_per)]

            def efn(expert_in):
                def one(args):
                    params_i, xi = args
                    saved = [p._data for p in param_objs]
                    try:
                        for p, arr in zip(param_objs, params_i):
                            p._data = arr
                        from ..framework.core import no_grad, \
                            functional_mode
                        with functional_mode(), no_grad():
                            out = template(Tensor(xi))
                        return as_jax(out)
                    finally:
                        for p, arr in zip(param_objs, saved):
                            p._data = arr
                return jax.lax.map(one, (tuple(stk), expert_in))
            y, aux, stats = moe_dispatch_combine(
                x_arr, logit_arr, self.num_expert, self.top_k,
                self.capacity_factor, efn, self.moe_axis,
                second_expert_policy=second_policy, rng_key=rng_key,
                return_stats=True)
            return y, aux, stats["drop_rate"]

        y, aux, drop = apply_jax("moe", f, x2, logits, *flat_params,
                                 n_outputs=3)
        self.gate.loss = aux
        self._aux_loss = aux
        self.drop_rate = drop
        return reshape(y, list(orig_shape))
