"""Mixture-of-Experts with expert parallelism
(``python/paddle/incubate/distributed/models/moe/moe_layer.py`` +
``gate/*.py`` parity).

TPU-first (SURVEY.md §7.4). Three dispatch formulations share one
sort-based router (``_sort_pairs``: a stable argsort groups (token,
slot) pairs expert-major; the inverse permutation is one int32
scatter):

- ``moe_dispatch_combine`` — GShard static-capacity dispatch against a
  ``[e, c, d]`` padded buffer; works with ARBITRARY per-expert layers
  (``expert_fn``) and under any GSPMD sharding. The all-to-all the
  reference codes against ProcessGroup appears as GSPMD collectives
  when the expert dim is mesh-sharded.
- ``moe_dispatch_combine_grouped`` — capacity SEMANTICS on the
  grouped-matmul engine for stacked SwiGLU experts: dropped pairs are
  zero-gated instead of excluded, so compute is the dropless total
  (s*k rows) with no capacity padding.
- ``moe_dispatch_combine_dropless`` — capacity-free routing as two
  grouped matmuls (megablox Pallas kernel on TPU, lax.ragged_dot
  elsewhere). Under an expert-sharded mesh the whole pipeline runs
  INSIDE ``shard_map`` (``_dropless_ep``): explicit all-to-alls place
  pairs on the shard owning their expert, the grouped kernels run on
  static per-shard shapes, and a hand-written custom VJP replays the
  same structure backward with separately tuned tilings.

``MOE_STATS`` records (at trace time) which path/kernel a compilation
took; static shapes throughout, as jit requires.
"""
from __future__ import annotations

import contextlib
import math
import os
import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ..nn import functional as F
from ..nn.layer.layers import Layer
from .shard_utils import annotate_param, constraint, mesh_axis_size

__all__ = ["MoELayer", "NaiveGate", "GShardGate", "SwitchGate",
           "moe_dispatch_combine", "moe_dispatch_combine_dropless",
           "moe_dispatch_combine_grouped", "moe_stats",
           "reset_moe_stats", "moe_fused_enabled", "serving_stats_tap",
           "serving_rows_mask", "ClipGradForMOEByGlobalNorm"]


from ..nn.clip import ClipGradByGlobalNorm as _ClipGradByGlobalNorm


class ClipGradForMOEByGlobalNorm(_ClipGradByGlobalNorm):
    """MoE-aware global-norm clip (reference:
    ``incubate/distributed/models/moe/grad_clip.py``). The reference
    splits (param, grad) pairs into expert / non-expert sets and
    all-reduces the expert-set norm over the moe group, because with EP
    each rank holds only its local experts; expert_sq + normal_sq is
    then the true global norm. TPU-first: expert params are stacked
    GSPMD arrays that are *logically global*, so the plain global norm
    over all grads is already the same quantity — this subclass exists
    so reference scripts construct the same class name, and keeps the
    constructor surface (predicate/group args are metadata here)."""

    def __init__(self, clip_norm, is_expert_param_func=None,
                 moe_group=None, group_name="default_moe_group"):
        super().__init__(clip_norm, group_name=group_name)
        self.is_expert_param_func = is_expert_param_func
        self.moe_group = moe_group


class BaseGate(Layer):
    def __init__(self, d_model, num_expert):
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert
        self.loss = None


class NaiveGate(BaseGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(d_model, num_expert)
        from ..nn.layer.common import Linear
        self.gate = Linear(d_model, num_expert)
        self.top_k = topk

    def forward(self, x):
        return self.gate(x)


class GShardGate(NaiveGate):
    """GShard top-2 gate (``gate/gshard_gate.py`` parity): the 2nd-choice
    expert receives the token only with probability ``min(1, 2*g2)``
    (GShard's random routing), sampled per token during training."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), group=None, gate_bias=True):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity_factor = capacity[0]
        self.second_expert_policy = "random"


class SwitchGate(NaiveGate):
    """Switch top-1 gate (``gate/switch_gate.py`` parity): multiplicative
    jitter noise ``U(1-eps, 1+eps)`` on the router input during
    training; capacity-drop statistics surface via ``drop_rate``."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.capacity_factor = capacity[0]
        self.switch_eps = float(switch_eps)

    def forward(self, x):
        if self.training and self.switch_eps > 0:
            from ..framework import random as _random
            key = _random.next_key()
            eps = self.switch_eps

            def jitter(a):
                noise = jax.random.uniform(
                    key, a.shape, jnp.float32, 1.0 - eps, 1.0 + eps)
                return a * noise.astype(a.dtype)
            x = apply_jax("switch_jitter", jitter, x)
        return self.gate(x)


# ---------------------------------------------------------------------------
# Gather-only dispatch plumbing.
#
# The (token, slot) -> (expert, capacity-slot) mapping is a partial
# permutation whose inverse we hold explicitly (one tiny int32 scatter
# builds it), so BOTH autodiff directions of pack/combine can be row
# gathers. XLA cannot know a scatter's indices are unique, so its
# scatter-add lowering serializes on TPU; these custom VJPs replace every
# float scatter in the MoE fwd+bwd with a gather (measured 10.5 -> 7.9
# ms/block fwd+bwd at the bench shapes [s=8192, d=1024, e=32, k=4]).
# ---------------------------------------------------------------------------

import functools as _functools


# Trace-time path-selection statistics. Incremented while the dispatch
# functions TRACE (not per executed step), so a test — or an operator
# reading bench output — can prove WHICH kernel a given mesh/shape
# combination compiled: the megablox grouped Pallas kernel, the
# lax.ragged_dot grouped fallback, or the dense capacity-padded einsum
# path, and whether the EP shard_map fast path was entered.
#
# Since the telemetry PR these are SERVED BY the framework-wide metrics
# registry (``paddle_tpu.monitor``): ``MOE_STATS`` is a thin mapping
# alias over a ``moe_path_calls{path=...}`` gauge plus a
# ``moe_grouped_mm_kernel`` info metric, so the counters land in the
# JSONL export/atexit table alongside every other metric while the
# historical dict-style API (``MOE_STATS[k] += 1``, ``moe_stats()``,
# ``reset_moe_stats()``) keeps working unchanged.
from .. import monitor as _monitor

_moe_path_calls = _monitor.gauge(
    "moe_path_calls",
    "MoE dispatch path selections recorded at trace time",
    labels=("path",))
_moe_kernel_info = _monitor.info(
    "moe_grouped_mm_kernel",
    "last grouped-matmul kernel a compilation selected")

from collections.abc import MutableMapping as _MutableMapping


class _MoeStats(_MutableMapping):
    """Dict-shaped view over the registry-backed MoE path counters."""

    _COUNTER_KEYS = ("grouped_mm_calls", "ep_shard_map_calls",
                     "padded_einsum_calls")
    _KEYS = ("grouped_mm_calls", "grouped_mm_kernel",
             "ep_shard_map_calls", "padded_einsum_calls")

    def __getitem__(self, k):
        if k == "grouped_mm_kernel":
            return _moe_kernel_info.get()
        if k in self._COUNTER_KEYS:
            return int(_moe_path_calls.labels(path=k).value())
        raise KeyError(k)

    def __setitem__(self, k, v):
        if k == "grouped_mm_kernel":
            _moe_kernel_info.set(v)
        elif k in self._COUNTER_KEYS:
            _moe_path_calls.labels(path=k).set(int(v))
        else:
            raise KeyError(k)

    def __delitem__(self, k):
        raise TypeError("MOE_STATS keys are fixed")

    def __iter__(self):
        return iter(self._KEYS)

    def __len__(self):
        return len(self._KEYS)

    def __repr__(self):
        return repr(dict(self))


MOE_STATS = _MoeStats()


def reset_moe_stats():
    MOE_STATS.update(grouped_mm_calls=0, grouped_mm_kernel=None,
                     ep_shard_map_calls=0, padded_einsum_calls=0)


def moe_stats():
    return dict(MOE_STATS)


def _sort_pairs(flat_e, e, valid=None):
    """Sort-based token→expert grouping (replaces the r5 chunked-cumsum
    position scan, which profiling showed dominating dispatch at bench
    shapes). A single stable argsort of the pair→expert keys groups the
    (token, slot) pairs expert-major while preserving arrival order —
    so capacity semantics (earlier tokens win slots) are unchanged —
    and its inverse permutation comes from one int32 scatter.

    Returns ``(order, rank, counts)``: ``order[r]`` = pair index at
    sorted position r, ``rank`` = inverse permutation, ``counts[j]`` =
    pairs routed to expert j. Pairs with ``valid=False`` get sentinel
    key ``e`` so they sort last and are excluded from ``counts``."""
    n = flat_e.shape[0]
    key = flat_e if valid is None else jnp.where(valid, flat_e, e)
    order = jnp.argsort(key).astype(jnp.int32)
    rank = jnp.zeros(n, jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    counts = jnp.zeros(e, jnp.int32).at[key].add(1, mode="drop")
    return order, rank, counts


@_functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _moe_pack(x, src_row, filled, dest, top_k):
    """expert_in[e, c] = x[src_row[e, c]] * filled[e, c].

    src_row: [e, c] token id feeding each expert slot (any value where
    unfilled); filled: [e, c] bool; dest: [s, k] int32 flat index of each
    (token, slot) in the padded [e * (c+1)] layout (sentinel column c for
    dropped slots) — used only by the backward gather.
    """
    ei = jnp.take(x, src_row, axis=0)
    return ei * filled[..., None].astype(x.dtype)


def _moe_pack_fwd(x, src_row, filled, dest, top_k):
    out = _moe_pack(x, src_row, filled, dest, top_k)
    return out, (out.shape[:2], dest)


def _moe_pack_bwd(top_k, res, g):
    (e, c), dest = res
    # dx[s] = sum_k g[dest(s, k)]; pad a zero sentinel column per expert
    # so dropped slots read zeros instead of needing a mask
    gf = jnp.pad(g, ((0, 0), (0, 1), (0, 0))).reshape(e * (c + 1), -1)
    rows = jnp.take(gf, dest.reshape(-1), axis=0)
    dx = rows.reshape(-1, top_k, gf.shape[-1]).sum(axis=1)
    return (dx.astype(g.dtype), None, None, None)


_moe_pack.defvjp(_moe_pack_fwd, _moe_pack_bwd)


@jax.custom_vjp
def _moe_combine(expert_out, gates, dest, src_row, filled, gates_ec):
    """y[s] = sum_k gates[s, k] * expert_out[dest(s, k)].

    gates_ec: [e, c] the gate weight of the (token, slot) feeding each
    expert slot (zero where unfilled) — the backward gather's coefficient.
    """
    e, c, d = expert_out.shape
    eof = jnp.pad(expert_out, ((0, 0), (0, 1), (0, 0))) \
        .reshape(e * (c + 1), d)
    k = dest.shape[1]
    picked = jnp.take(eof, dest.reshape(-1), axis=0).reshape(-1, k, d)
    return jnp.einsum("sk,skd->sd", gates.astype(expert_out.dtype),
                      picked)


def _moe_combine_fwd(expert_out, gates, dest, src_row, filled, gates_ec):
    y = _moe_combine(expert_out, gates, dest, src_row, filled, gates_ec)
    return y, (expert_out, gates, dest, src_row, filled, gates_ec)


def _moe_combine_bwd(res, dy):
    expert_out, gates, dest, src_row, filled, gates_ec = res
    e, c, d = expert_out.shape
    k = dest.shape[1]
    # d_expert_out[e, c] = dy[src_row] * gate-of-that-slot  (gather)
    deo = jnp.take(dy, src_row, axis=0)
    coef = (gates_ec * filled.astype(gates_ec.dtype))
    deo = deo * coef[..., None].astype(dy.dtype)
    # d_gates[s, k] = <dy[s], expert_out[dest(s, k)]>
    eof = jnp.pad(expert_out, ((0, 0), (0, 1), (0, 0))) \
        .reshape(e * (c + 1), d)
    picked = jnp.take(eof, dest.reshape(-1), axis=0).reshape(-1, k, d)
    dgates = jnp.einsum("sd,skd->sk", dy.astype(jnp.float32),
                        picked.astype(jnp.float32))
    return (deo.astype(expert_out.dtype), dgates.astype(gates.dtype),
            None, None, None, None)


_moe_combine.defvjp(_moe_combine_fwd, _moe_combine_bwd)


@jax.custom_vjp
def _perm_rows(x, idx, inv_idx):
    """y[i] = x[idx[i]] where idx is a permutation with inverse inv_idx
    (backward is the inverse gather, never a scatter)."""
    return jnp.take(x, idx, axis=0)


def _perm_rows_fwd(x, idx, inv_idx):
    return jnp.take(x, idx, axis=0), (idx, inv_idx)


def _perm_rows_bwd(res, g):
    idx, inv_idx = res
    return (jnp.take(g, inv_idx, axis=0), None, None)


_perm_rows.defvjp(_perm_rows_fwd, _perm_rows_bwd)


@_functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _expand_sort(x, src_tok, rank, top_k):
    """xs[r] = x[src_tok[r]]: expand each token to its top_k slots in
    expert-sorted order. rank: [s * k] position of (token, slot) in the
    sorted order (token-major) — the inverse mapping for the backward
    gather: dx[s] = sum_k g[rank[s * k + k]]."""
    return jnp.take(x, src_tok, axis=0)


def _expand_sort_fwd(x, src_tok, rank, top_k):
    return jnp.take(x, src_tok, axis=0), (rank,)


def _expand_sort_bwd(top_k, res, g):
    (rank,) = res
    rows = jnp.take(g, rank, axis=0)               # token-major [s*k, d]
    dx = rows.reshape(-1, top_k, g.shape[-1]).sum(axis=1)
    return (dx.astype(g.dtype), None, None)


_expand_sort.defvjp(_expand_sort_fwd, _expand_sort_bwd)


def moe_dispatch_combine(x, gate_logits, num_expert, top_k=2,
                         capacity_factor=1.25, expert_fn=None,
                         expert_axis=None, normalize_gates=True,
                         second_expert_policy="all", rng_key=None,
                         return_stats=False):
    """Pure-array GShard dispatch → expert_fn → combine.

    x: [tokens, d]; gate_logits: [tokens, e]. expert_fn(inputs[e, c, d])
    -> [e, c, d]. Returns (y [tokens, d], aux_loss scalar), plus a stats
    dict (capacity ``drop_rate``) when ``return_stats``.
    ``normalize_gates=False`` combines with the raw softmax probs of the
    selected experts (Qwen2-MoE/DeepSeek ``norm_topk_prob=False``).
    ``second_expert_policy="random"`` + ``rng_key`` enables GShard's
    random routing: slot j>=1 dispatches with probability
    ``min(1, k * g_j)``.

    Pack and combine are gather-only in both autodiff directions (see
    the custom-VJP helpers above); the single scatter left is the int32
    slot-occupancy map, which is negligible next to the float traffic.
    """
    s, d = x.shape
    e = num_expert
    c = max(int(math.ceil(capacity_factor * s * top_k / e)), 1)

    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    # top-k selection
    topk_prob, topk_idx = jax.lax.top_k(probs, top_k)  # [s, k]

    sel = None
    if second_expert_policy == "random" and rng_key is not None \
            and top_k >= 2:
        u = jax.random.uniform(rng_key, (s, top_k))
        sel = u < jnp.minimum(top_k * topk_prob, 1.0)
        sel = sel.at[:, 0].set(True)  # 1st choice always dispatches

    # position of each (token, k) within its expert's queue via the
    # sort-based grouping (random-skipped slots don't consume capacity)
    flat_e_all = topk_idx.reshape(-1).astype(jnp.int32)
    _order, rank, counts = _sort_pairs(
        flat_e_all, e, valid=None if sel is None else sel.reshape(-1))
    starts = jnp.cumsum(counts) - counts
    pos = (rank - starts[flat_e_all]).reshape(s, top_k)
    slot_used = jnp.ones((s, top_k), bool) if sel is None else sel
    keep = (pos < c) & slot_used

    # load-balancing aux loss (GShard eq.: e * sum(me * ce))
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(topk_idx[:, 0], e,
                                 dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)
    MOE_STATS["padded_einsum_calls"] += 1

    # random-skipped slots are zeroed BEFORE normalization (GShard/
    # fairseq top2gating order): a token whose 2nd expert was skipped
    # combines with weight ~1.0, not g1/(g1+g2)
    eff_prob = topk_prob if sel is None \
        else topk_prob * sel.astype(topk_prob.dtype)
    if normalize_gates:
        gates = eff_prob / jnp.maximum(
            jnp.sum(eff_prob, axis=-1, keepdims=True), 1e-9)
    else:
        gates = eff_prob
    gates = jnp.where(keep, gates, 0.0).astype(x.dtype)

    # slot-occupancy map: one int32 scatter builds the inverse of the
    # (token, slot) -> (expert, pos) mapping; dropped slots land in a
    # per-expert sentinel column that pack/combine read as zeros
    flat_e = topk_idx.reshape(-1)                       # [s*k]
    flat_p = jnp.where(keep, pos, c).reshape(-1)        # [s*k]
    dest = (flat_e * (c + 1) + flat_p).astype(jnp.int32)
    inv = jnp.zeros(e * (c + 1), jnp.int32)
    inv = inv.at[dest].set(jnp.arange(s * top_k, dtype=jnp.int32) + 1)
    inv = inv.reshape(e, c + 1)[:, :c]                  # [e, c]
    src_slot = jnp.maximum(inv - 1, 0)
    src_row = src_slot // top_k                         # token per slot
    filled = inv > 0
    gates_ec = jnp.take(gates.reshape(-1), src_slot.reshape(-1)) \
        .reshape(e, c)
    dest = dest.reshape(s, top_k)

    from ..profiler import RecordEvent
    with RecordEvent("moe:dispatch"):
        expert_in = _moe_pack(x, src_row, filled, dest, top_k)
        if expert_axis is not None:
            expert_in = _ep_constraint(expert_in, expert_axis)
    with RecordEvent("moe:expert_mm"):
        expert_out = expert_fn(expert_in)      # [e, c, d_out]
        if expert_axis is not None:
            expert_out = _ep_constraint(expert_out, expert_axis)
    with RecordEvent("moe:combine"):
        y = _moe_combine(expert_out, gates, dest, src_row, filled,
                         gates_ec)
    if return_stats:
        # fraction of requested (token, slot) dispatches that were
        # dropped — capacity overflow plus random-routing skips
        stats = {"drop_rate": 1.0 - jnp.sum(keep.astype(jnp.float32))
                 / float(s * top_k)}
        return y, aux, stats
    return y, aux


# megablox grouped-matmul tilings tuned on the bench shapes (v5e: the
# (m, k, n) tile must keep the last two block dims 8/128-aligned).
# Backward kernels (transposed gmm + tgmm) prefer the smaller k tile:
# tgmm at [32768, 1024->1408] measured 3.30 ms with (512,1024,512) vs
# 2.32 with (512,512,512)
_GMM_TILING = (512, 1024, 512)
_GMM_TILING_BWD = (512, 512, 512)


@_functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gmm32(lhs, rhs, group_sizes, tiling):
    """megablox gmm with every Pallas trace under disable_x64.

    The stock ``megablox.ops.gmm`` custom VJP traces its backward
    kernels when jax.grad runs — outside any caller context manager —
    and under jax_enable_x64 (the framework default) a weak-f64 constant
    makes Mosaic's convert lowering recurse forever. This wrapper owns
    the VJP so fwd AND bwd kernels trace in 32-bit mode.
    """
    import importlib
    # the megablox package re-exports a FUNCTION named gmm that shadows
    # the module of the same name; importlib reaches the module
    _mb = importlib.import_module(
        "jax.experimental.pallas.ops.tpu.megablox.gmm")
    from ..ops.pallas.flash_attention_kernel import disable_x64
    with disable_x64():
        return _mb.gmm(lhs, rhs, group_sizes,
                       preferred_element_type=lhs.dtype, tiling=tiling)


def _gmm32_fwd(lhs, rhs, group_sizes, tiling):
    return _gmm32(lhs, rhs, group_sizes, tiling), (lhs, rhs, group_sizes)


def _mb_bwd_dlhs(g, rhs, group_sizes):
    """Raw megablox d(lhs): transpose-rhs gmm under the bwd tiling."""
    import importlib
    _mb = importlib.import_module(
        "jax.experimental.pallas.ops.tpu.megablox.gmm")
    from ..ops.pallas.flash_attention_kernel import disable_x64
    with disable_x64():
        return _mb.gmm(g, rhs, group_sizes,
                       preferred_element_type=g.dtype,
                       tiling=_GMM_TILING_BWD, transpose_rhs=True)


def _mb_bwd_drhs(lhs, g, group_sizes, num_groups):
    """Raw megablox d(rhs): tgmm under the bwd tiling."""
    import importlib
    _mb = importlib.import_module(
        "jax.experimental.pallas.ops.tpu.megablox.gmm")
    from ..ops.pallas.flash_attention_kernel import disable_x64
    with disable_x64():
        return _mb.tgmm(lhs.swapaxes(0, 1), g, group_sizes,
                        preferred_element_type=g.dtype,
                        tiling=_GMM_TILING_BWD,
                        num_actual_groups=num_groups)


def _gmm32_bwd(tiling, res, g):
    # no fallback here by design: a shape that traced the forward
    # kernel traces the backward (same block alignment, dims swapped)
    lhs, rhs, gs = res
    dlhs = _mb_bwd_dlhs(g, rhs, gs)
    drhs = _mb_bwd_drhs(lhs, g, gs, rhs.shape[0])
    return dlhs.astype(lhs.dtype), drhs.astype(rhs.dtype), None


_gmm32.defvjp(_gmm32_fwd, _gmm32_bwd)


def _use_megablox(n_rows, d_in, d_out):
    """The Pallas grouped-matmul kernel beats lax.ragged_dot on real TPU
    at MXU-scale shapes (measured 2.25 -> 1.70 ms at [32768, 1024, 1408])
    but needs a tpu backend and 8-aligned dims (its TILE dims carry the
    (8, 128) rule; array dims only need sublane alignment — d=704 works
    under the fixed (512, 1024, 512) tiling). Since r6 this predicate
    also gates the PER-SHARD shapes inside the EP shard_map fast path —
    per-shard buffer shapes are static there, so the kernel is legal
    under expert sharding. CPU test meshes, tiny shapes, and any shape
    the kernel rejects at trace time take the ragged_dot path (see the
    fallback in the callers)."""
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:
        return False
    return (n_rows >= 1024 and d_in % 8 == 0 and d_out % 8 == 0)


def _grouped_mm(lhs, rhs, group_sizes, tiling=None,
                allow_pallas=True):
    """Single entry point for the grouped expert matmul: the megablox
    Pallas kernel on real TPU at MXU-scale aligned shapes (fwd AND bwd
    run grouped kernels via the ``_gmm32`` custom VJP, with the
    separately tuned backward tiling), ``jax.lax.ragged_dot`` elsewhere.
    Increments ``MOE_STATS`` at trace time so tests can assert which
    kernel a given mesh/shape combination actually compiled.

    ``allow_pallas=False`` forces ragged_dot: the Pallas kernel is only
    legal on REPLICATED/manual (shard_map) operands — under GSPMD
    sharding an opaque pallas_call can't be partitioned, so the sharded
    non-shard_map fallback path must keep the r5 ragged_dot gate."""
    MOE_STATS["grouped_mm_calls"] += 1
    if allow_pallas and _use_megablox(lhs.shape[0], lhs.shape[1],
                                      rhs.shape[-1]):
        try:
            out = _gmm32(lhs, rhs, group_sizes, tiling or _GMM_TILING)
            MOE_STATS["grouped_mm_kernel"] = "megablox"
            return out
        except Exception as exc:
            import warnings
            warnings.warn(
                "moe: megablox gmm unavailable for shape "
                f"{lhs.shape} x {rhs.shape} ({exc!r}); using "
                "lax.ragged_dot")
    MOE_STATS["grouped_mm_kernel"] = "ragged_dot"
    return jax.lax.ragged_dot(lhs, rhs, group_sizes)


def _grouped_mm_dlhs(g, rhs, group_sizes):
    """d(lhs) of the grouped matmul for the hand-written EP backward:
    transpose-rhs grouped matmul with the backward tiling."""
    MOE_STATS["grouped_mm_calls"] += 1
    if _use_megablox(g.shape[0], g.shape[1], rhs.shape[1]):
        try:
            out = _mb_bwd_dlhs(g, rhs, group_sizes)
            MOE_STATS["grouped_mm_kernel"] = "megablox"
            return out
        except Exception as exc:
            import warnings
            warnings.warn(f"moe: megablox bwd gmm unavailable "
                          f"({exc!r}); using lax.ragged_dot")
    MOE_STATS["grouped_mm_kernel"] = "ragged_dot"
    return jax.lax.ragged_dot(g, rhs.swapaxes(1, 2), group_sizes)


def _grouped_mm_drhs(lhs, g, group_sizes, num_groups):
    """d(rhs) of the grouped matmul for the hand-written EP backward:
    megablox tgmm with the backward tiling on TPU, the linear transpose
    of ragged_dot elsewhere."""
    MOE_STATS["grouped_mm_calls"] += 1
    if _use_megablox(lhs.shape[0], lhs.shape[1], g.shape[-1]):
        try:
            out = _mb_bwd_drhs(lhs, g, group_sizes, num_groups)
            MOE_STATS["grouped_mm_kernel"] = "megablox"
            return out
        except Exception as exc:
            import warnings
            warnings.warn(f"moe: megablox tgmm unavailable "
                          f"({exc!r}); using ragged_dot transpose")
    MOE_STATS["grouped_mm_kernel"] = "ragged_dot"
    shape = jax.ShapeDtypeStruct(
        (num_groups, lhs.shape[1], g.shape[-1]), g.dtype)
    transposed = jax.linear_transpose(
        lambda r: jax.lax.ragged_dot(lhs, r, group_sizes), shape)
    return transposed(g)[0]


def _expert_swiglu_grouped(xs, gate_up, down, group_sizes, dtype,
                           allow_pallas=True):
    """Expert SwiGLU MLP over expert-sorted rows as TWO grouped
    matmuls (``[n, d] x [e, d, 2f] -> [n, 2f]``, swiglu,
    ``[n, f] x [e, f, d] -> [n, d]``)."""
    gu = _grouped_mm(xs, gate_up.astype(dtype), group_sizes,
                     allow_pallas=allow_pallas)
    g, u = jnp.split(gu, 2, axis=-1)
    h = jax.nn.silu(g.astype(jnp.float32)).astype(dtype) * u
    return _grouped_mm(h, down.astype(dtype), group_sizes,
                       allow_pallas=allow_pallas)


# ---------------------------------------------------------------------------
# Fused-dispatch grouped MoE (ops/pallas/moe_gmm.py): the sort/dispatch
# permutation folds into the grouped matmuls themselves — gather-on-read
# lhs (the sorted packed buffer never reaches HBM), swiglu in the first
# matmul's epilogue (the [m, 2f] projection never reaches HBM), and the
# combine's unsort as the second matmul's scatter store. The custom VJP
# below replays the same gather/scatter structure backward.
# ---------------------------------------------------------------------------


def moe_fused_enabled() -> bool:
    """Kill switch: ``PADDLE_TPU_MOE_FUSED_GMM=0`` restores the
    sort→pack→gmm path everywhere, bit-for-bit (the fused kernels are
    never traced)."""
    return os.environ.get("PADDLE_TPU_MOE_FUSED_GMM", "1") != "0"


def _use_fused_gmm(n_rows, d_model, d_ffn, fused=None):
    """Eligibility of the fused-dispatch kernels for this shape.
    Returns ``False`` (sorted path), ``"tpu"`` (compiled kernels) or
    ``"interpret"`` (Pallas interpreter — CPU tests set
    ``PADDLE_TPU_MOE_FUSED_GMM=interpret`` to exercise the fused
    graph end-to-end off-TPU). ``fused``: the per-call/config override
    (``None`` = env default). Production gating mirrors
    ``_use_megablox``: real TPU backend, MXU-scale row count, and
    128-aligned dims so ``pick_tiling`` finds lane-aligned tiles."""
    env = os.environ.get("PADDLE_TPU_MOE_FUSED_GMM", "1")
    if env == "0" or fused is False:
        return False
    aligned = (d_model % 128 == 0 and d_ffn % 128 == 0
               and n_rows % 128 == 0)
    if env == "interpret":
        return "interpret" if aligned else False
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:
        return False
    return "tpu" if (n_rows >= 1024 and aligned) else False


@_functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _fused_moe_core(top_k, interpret, x, gate_up, down, gates, order,
                    src_rows, gs):
    """Fused dispatch→experts→combine over the sorted row partition:
    ``y[s] = sum_k gates[s, k] * (swiglu expert of x[s])`` with the
    sort (``src_rows = order // top_k``) fused into the first matmul's
    load and the unsort (``order``) into the second's store. ``gs``
    must sum to ``s * top_k`` (tail-padded last group, exactly as the
    sorted path)."""
    from ..ops.pallas.moe_gmm import gather_gmm_swiglu, scatter_gmm
    h = gather_gmm_swiglu(x, src_rows, gate_up.astype(x.dtype), gs,
                          interpret=interpret)
    ys_tok = scatter_gmm(h, down.astype(x.dtype), gs, order,
                         interpret=interpret)
    s = x.shape[0]
    picked = ys_tok.reshape(s, top_k, -1)
    return jnp.einsum("sk,skd->sd", gates.astype(x.dtype), picked)


def _fused_moe_fwd(top_k, interpret, x, gate_up, down, gates, order,
                   src_rows, gs):
    from ..ops.pallas.moe_gmm import gather_gmm_swiglu, scatter_gmm
    h = gather_gmm_swiglu(x, src_rows, gate_up.astype(x.dtype), gs,
                          interpret=interpret)
    ys_tok = scatter_gmm(h, down.astype(x.dtype), gs, order,
                         interpret=interpret)
    s = x.shape[0]
    picked = ys_tok.reshape(s, top_k, -1)
    y = jnp.einsum("sk,skd->sd", gates.astype(x.dtype), picked)
    return y, (x, gate_up, down, gates, order, src_rows, gs, h,
               ys_tok)


def _fused_moe_bwd(top_k, interpret, res, dy):
    """Backward replays the SAME fused structure: the token-major
    cotangent is gathered into sorted order by the first backward
    matmul's load (``order`` drives it exactly like ``src_rows`` drove
    the forward), and d(x) leaves the last backward matmul through the
    scatter epilogue. The gate/up projection — never materialized
    forward — is recomputed here with one extra gather-gmm (recompute
    beats carrying an ``[m, 2f]`` residual through the step, same
    trade as remat); weight grads run the tuned tgmm path on
    materialized sorted operands (backward-only traffic)."""
    from ..ops.pallas.moe_gmm import gather_gmm, scatter_gmm
    x, gate_up, down, gates, order, src_rows, gs, h, ys_tok = res
    s, d = x.shape
    e = gate_up.shape[0]
    picked = ys_tok.reshape(s, top_k, -1)
    dgates = jnp.einsum("sd,skd->sk", dy.astype(jnp.float32),
                        picked.astype(jnp.float32))
    dpair_tok = (gates.astype(dy.dtype)[..., None] * dy[:, None, :]) \
        .reshape(s * top_k, d)
    dh = gather_gmm(dpair_tok, order, down.astype(dy.dtype), gs,
                    transpose_rhs=True, interpret=interpret)
    dpair_sorted = jnp.take(dpair_tok, order, axis=0)
    ddown = _grouped_mm_drhs(h, dpair_sorted, gs, e)
    gu = gather_gmm(x, src_rows, gate_up.astype(x.dtype), gs,
                    interpret=interpret)
    g_a, u_a = jnp.split(gu, 2, axis=-1)
    g32 = g_a.astype(jnp.float32)
    sig = jax.nn.sigmoid(g32)
    dh32 = dh.astype(jnp.float32)
    dg = dh32 * u_a.astype(jnp.float32) * sig * (1 + g32 * (1 - sig))
    du = dh32 * (g32 * sig)
    dgu = jnp.concatenate([dg, du], axis=-1).astype(x.dtype)
    xs = jnp.take(x, src_rows, axis=0)
    dguw = _grouped_mm_drhs(xs, dgu, gs, e)
    dx_tok = scatter_gmm(dgu, gate_up.astype(x.dtype), gs, order,
                         transpose_rhs=True, interpret=interpret)
    dx = dx_tok.reshape(s, top_k, d).sum(axis=1)
    return (dx.astype(x.dtype), dguw.astype(gate_up.dtype),
            ddown.astype(down.dtype), dgates.astype(gates.dtype),
            None, None, None)


_fused_moe_core.defvjp(_fused_moe_fwd, _fused_moe_bwd)


# -- serving-time routing telemetry tap -------------------------------------
# The serving engine arms a per-thread sink while TRACING its
# executables; an armed dispatch adds one tiny jax.debug.callback
# (per-expert load fractions + routing entropy) that fires on every
# EXECUTION of the compiled step — decode-time router telemetry with no
# change to the model-step calling convention.
_SERVING_TAP = threading.local()


@contextlib.contextmanager
def serving_stats_tap(sink):
    """Arm ``sink(load [e] np.ndarray, entropy float)`` for every MoE
    dispatch traced on this thread inside the context."""
    prev = getattr(_SERVING_TAP, "sink", None)
    _SERVING_TAP.sink = sink
    try:
        yield
    finally:
        _SERVING_TAP.sink = prev


@contextlib.contextmanager
def serving_rows_mask(mask):
    """Arm a per-ROW validity mask (``[s]`` bool, traced) for MoE
    dispatches traced inside the context. Serving executables run
    fixed-shape row buffers whose PAD rows still route through the
    dispatch — without the mask their (identical, meaningless) expert
    picks would dominate the routing telemetry of a lightly loaded
    tick, reading as hot-expert skew that isn't there. The engine's
    ``_compile_*`` wrappers arm the step's live-row mask around the
    model trace; the tap then counts only real rows."""
    prev = getattr(_SERVING_TAP, "rows_mask", None)
    _SERVING_TAP.rows_mask = mask
    try:
        yield
    finally:
        _SERVING_TAP.rows_mask = prev


def _tap_routing(flat_e, e, top_k, counts):
    """If a serving sink is armed (trace time), emit this dispatch's
    per-expert load fractions and routing entropy (nats) at run time —
    over LIVE rows only when a row mask is armed (pad rows of the
    fixed-shape serving buffers are excluded; see
    ``serving_rows_mask``)."""
    sink = getattr(_SERVING_TAP, "sink", None)
    if sink is None:
        return
    mask = getattr(_SERVING_TAP, "rows_mask", None)
    if mask is not None \
            and mask.shape[0] * top_k == flat_e.shape[0]:
        valid = jnp.repeat(mask.astype(jnp.int32), top_k)
        counts = jnp.zeros(e, jnp.int32).at[flat_e].add(valid,
                                                        mode="drop")
    total = jnp.maximum(jnp.sum(counts), 1).astype(jnp.float32)
    load = counts.astype(jnp.float32) / total
    ent = -jnp.sum(jnp.where(load > 0,
                             load * jnp.log(jnp.maximum(load, 1e-12)),
                             0.0))
    jax.debug.callback(sink, load, ent)


def moe_dispatch_combine_dropless(x, gate_logits, num_expert, top_k,
                                  gate_up, down, normalize_gates=True,
                                  expert_axis=None, return_stats=False,
                                  ep_buffer_factor=2.0, fused=None):
    """DROPLESS dispatch → SwiGLU experts → combine (reference:
    capacity-free routing the fused-MoE kernels in
    ``phi/kernels/fusion/`` approximate; design follows the MegaBlocks
    grouped-matmul formulation).

    No capacity factor and no dropped tokens: (token, slot) pairs are
    grouped by expert with ONE stable argsort (``_sort_pairs``) and the
    expert MLP runs as TWO grouped matmuls — the megablox Pallas kernel
    on real TPU (tiles each ragged expert segment onto the MXU),
    ``jax.lax.ragged_dot`` elsewhere. Sort and unsort are gathers in
    both autodiff directions (``_expand_sort`` / ``_perm_rows`` custom
    VJPs). Under an expert-sharded mesh the whole pipeline moves INSIDE
    ``shard_map`` (``_dropless_ep``): explicit all-to-alls place each
    pair on the shard owning its expert, the grouped kernels run on
    static per-shard shapes, and a hand-written custom VJP replays the
    same structure backward with the separately tuned backward tilings.
    ``ep_buffer_factor`` bounds the per-(src, dst) exchange slots;
    >= the EP degree is exactly dropless (overflow is reported in the
    ``drop_rate`` stat).

    x: [s, d]; gate_logits: [s, e]; gate_up: [e, d, 2f]; down: [e, f, d].
    Returns (y [s, d], aux) (+ stats dict with drop_rate).
    """
    return _grouped_dispatch(
        x, gate_logits, num_expert, top_k, gate_up, down,
        capacity_factor=None, normalize_gates=normalize_gates,
        expert_axis=expert_axis, ep_buffer_factor=ep_buffer_factor,
        return_stats=return_stats, fused=fused)


def moe_dispatch_combine_grouped(x, gate_logits, num_expert, top_k,
                                 gate_up, down, capacity_factor=1.25,
                                 normalize_gates=True,
                                 second_expert_policy="all",
                                 rng_key=None, expert_axis=None,
                                 return_stats=False, fused=None):
    """GShard CAPACITY semantics on the grouped-matmul engine: same
    routing, same capacity rule (earlier tokens win their expert's
    slots), same gate zeroing for dropped pairs as the padded
    ``moe_dispatch_combine`` — but the expert MLP runs as two grouped
    matmuls over expert-sorted rows instead of the ``[e, c, d]``
    capacity-padded batched einsum. Dropped pairs are zero-gated at
    combine rather than excluded from the matmul, so the compute is
    exactly the dropless total (s*k rows) and the ~(cf-1) capacity
    padding waste is gone.

    Under an expert-sharded mesh this falls back to the padded GSPMD
    formulation (the capacity rule needs global arrival positions; the
    shard_map fast path is dropless-only)."""
    sharded = expert_axis is not None and mesh_axis_size(expert_axis) > 1
    if sharded:
        def efn(expert_in):
            gu = jnp.einsum("ecd,edm->ecm", expert_in,
                            gate_up.astype(expert_in.dtype))
            g, u = jnp.split(gu, 2, axis=-1)
            h = jax.nn.silu(g.astype(jnp.float32)) \
                .astype(expert_in.dtype) * u
            return jnp.einsum("ecm,emd->ecd", h,
                              down.astype(expert_in.dtype))
        return moe_dispatch_combine(
            x, gate_logits, num_expert, top_k=top_k,
            capacity_factor=capacity_factor, expert_fn=efn,
            expert_axis=expert_axis, normalize_gates=normalize_gates,
            second_expert_policy=second_expert_policy, rng_key=rng_key,
            return_stats=return_stats)
    return _grouped_dispatch(
        x, gate_logits, num_expert, top_k, gate_up, down,
        capacity_factor=capacity_factor, normalize_gates=normalize_gates,
        second_expert_policy=second_expert_policy, rng_key=rng_key,
        expert_axis=expert_axis, return_stats=return_stats, fused=fused)


def _grouped_dispatch(x, gate_logits, num_expert, top_k, gate_up, down,
                      *, capacity_factor, normalize_gates=True,
                      second_expert_policy="all", rng_key=None,
                      expert_axis=None, ep_buffer_factor=2.0,
                      return_stats=False, fused=None):
    """Shared engine behind the dropless and capacity-grouped paths:
    route → sort-group → grouped expert matmuls → combine, with the EP
    shard_map fast path when the expert axis is mesh-sharded."""
    s, d = x.shape
    e = num_expert
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    topk_prob, topk_idx = jax.lax.top_k(probs, top_k)       # [s, k]
    topk_idx = topk_idx.astype(jnp.int32)

    sel = None
    if second_expert_policy == "random" and rng_key is not None \
            and top_k >= 2:
        u = jax.random.uniform(rng_key, (s, top_k))
        sel = u < jnp.minimum(top_k * topk_prob, 1.0)
        sel = sel.at[:, 0].set(True)  # 1st choice always dispatches

    flat_e = topk_idx.reshape(-1)                           # [s*k]
    valid = None if sel is None else sel.reshape(-1)
    order, rank, counts = _sort_pairs(flat_e, e, valid=valid)

    if capacity_factor is None:
        keep = sel                                          # dropless
    else:
        c = max(int(math.ceil(capacity_factor * s * top_k / e)), 1)
        starts = jnp.cumsum(counts) - counts
        pos = (rank - starts[flat_e]).reshape(s, top_k)
        slot_used = jnp.ones((s, top_k), bool) if sel is None else sel
        keep = (pos < c) & slot_used

    # random-skipped slots are zeroed BEFORE normalization (GShard/
    # fairseq top2gating order), capacity-dropped slots after
    eff_prob = topk_prob if sel is None \
        else topk_prob * sel.astype(topk_prob.dtype)
    if normalize_gates:
        gates = eff_prob / jnp.maximum(
            jnp.sum(eff_prob, axis=-1, keepdims=True), 1e-9)
    else:
        gates = eff_prob
    if keep is not None:
        gates = jnp.where(keep, gates, 0.0)
    gates = gates.astype(x.dtype)

    ep = mesh_axis_size(expert_axis) if expert_axis is not None else 1
    ep_drop = None
    _tap_routing(flat_e, e, top_k, counts)
    from ..profiler import RecordEvent
    if ep > 1 and capacity_factor is None and e % ep == 0 \
            and s % ep == 0 and _env_mesh() is not None:
        with RecordEvent("moe:ep_dispatch_combine"):
            y, ep_drop = _dropless_ep(x, gates, topk_idx, gate_up,
                                      down, expert_axis, ep,
                                      ep_buffer_factor)
    else:
        if ep > 1:
            gate_up = _ep_constraint(gate_up, expert_axis)
            down = _ep_constraint(down, expert_axis)
        gs = counts.at[e - 1].add(
            jnp.int32(s * top_k) - jnp.sum(counts, dtype=jnp.int32))
        d_ffn = down.shape[1]
        # inside a TP engine's trace GSPMD owns the partitioning (the
        # expert weights arrive mp-sharded): opaque Pallas kernels —
        # fused AND megablox — must stay off, exactly like the r5
        # sharded-fallback ragged_dot gate
        from ..ops.pallas.paged_attention import serving_tp_active
        gspmd_tp = serving_tp_active()
        fmode = _use_fused_gmm(s * top_k, d, d_ffn, fused=fused) \
            if ep <= 1 and not gspmd_tp else False
        if fmode:
            # fused-dispatch path: the sort is the first matmul's
            # gather-on-read load, swiglu its epilogue, the unsort the
            # second matmul's scatter store — the packed [s*k, d]
            # buffer and the [s*k, 2f] projection never reach HBM.
            # Same routing, same gs tail-pad, so capacity zero-gating
            # and random-skip absorption behave exactly as the sorted
            # path they replace.
            MOE_STATS["grouped_mm_calls"] += 2
            MOE_STATS["grouped_mm_kernel"] = "fused_gmm"
            with RecordEvent("moe:fused_dispatch_combine"):
                y = _fused_moe_core(
                    top_k, fmode == "interpret", x, gate_up, down,
                    gates, order, (order // top_k).astype(jnp.int32),
                    gs)
        else:
            # local sorted grouped-matmul path: all s*k pairs flow
            # through the grouped matmuls (capacity-dropped pairs are
            # zero-gated at combine — same total rows as dropless, no
            # capacity padding); pairs skipped by random routing sort
            # into the tail and are absorbed into the last group. When
            # the expert axis IS sharded but the shard_map fast path
            # was ineligible (non-divisible e/s), GSPMD owns the
            # partitioning — the opaque Pallas kernel can't be
            # partitioned, so force the ragged_dot lowering (the r5
            # gate, kept exactly where it is still required).
            with RecordEvent("moe:dispatch"):
                xs = _expand_sort(x, order // top_k, rank,
                                  top_k)                   # [s*k, d]
            with RecordEvent("moe:expert_mm"):
                ys = _expert_swiglu_grouped(
                    xs, gate_up, down, gs, x.dtype,
                    allow_pallas=(ep <= 1 and not gspmd_tp))
            with RecordEvent("moe:combine"):
                picked = _perm_rows(ys, rank, order) \
                    .reshape(s, top_k, -1)
                y = jnp.einsum("sk,skd->sd", gates, picked)

    # GShard load-balance aux (top-1 occupancy), as the padded path
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(topk_idx[:, 0], e,
                                 dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)
    if return_stats:
        if ep_drop is not None:
            drop = ep_drop          # EP exchange-buffer overflow
        elif keep is None:
            drop = jnp.float32(0.0)
        else:
            drop = 1.0 - jnp.sum(keep.astype(jnp.float32)) \
                / float(s * top_k)
        return y, aux, {"drop_rate": drop}
    return y, aux


def _env_mesh():
    from . import env as _env
    return _env.get_mesh()


def _dropless_ep(x, gates, topk_idx, gate_up, down, axis, ep,
                 buffer_factor):
    """EP-sharded dropless fast path: grouped matmuls INSIDE shard_map.

    The r5 sharded path handed the whole dispatch to GSPMD with
    sharding constraints and fell back to ``lax.ragged_dot`` — the
    megablox kernel was gated off exactly where multi-chip training
    runs. Inside ``shard_map`` the per-shard buffer shapes are STATIC,
    so the Pallas grouped kernel is legal under expert sharding, and
    the collective placement is explicit instead of inferred.

    Per shard (s_l = s/ep local tokens, e_l = e/ep local experts,
    experts laid out shard-major so destination-shard regions are
    contiguous in expert-sorted order):

      1. stable-sort local (token, slot) pairs by destination expert;
      2. gather rows into a ``[ep, cap_pair, d]`` send buffer, exchange
         per-expert counts, then ONE ``lax.all_to_all`` places every
         pair on the shard owning its expert;
      3. derive per-row local expert ids from the exchanged counts,
         re-sort received rows expert-major, run the TWO grouped
         matmuls (megablox on TPU), unsort;
      4. the reverse ``all_to_all`` returns expert outputs to their
         source shard, which combines them with the gate weights.

    The whole pipeline is one custom_vjp: the backward replays the same
    all-to-all structure on cotangents and runs the grouped matmuls
    with the separately tuned backward tilings (transpose-rhs gmm +
    tgmm) instead of letting autodiff transpose the dispatch gathers
    into serialized scatters.

    ``cap_pair`` bounds each (src, dst) exchange slot at
    ``buffer_factor * s_l * k / ep`` rows (rounded up to the sublane
    multiple); pairs beyond it are dropped and reported via the
    returned drop fraction. ``buffer_factor >= ep`` is exactly
    dropless (the per-slot worst case is all local pairs to one
    shard)."""
    mesh = _env_mesh()
    s, d = x.shape
    k = topk_idx.shape[1]
    e = gate_up.shape[0]
    e_l = e // ep
    s_l = s // ep
    n_l = s_l * k
    cap_pair = int(math.ceil(float(buffer_factor) * n_l / ep))
    cap_pair = min(max(cap_pair, 1), n_l)
    cap_pair = -(-cap_pair // 8) * 8          # sublane-align the slots
    n_r = ep * cap_pair
    MOE_STATS["ep_shard_map_calls"] += 1

    def _fwd(x_l, gates_l, idx_l, gu_w, dn_w):
        flat_e = idx_l.reshape(-1)                        # [n_l] global
        order, rank, counts = _sort_pairs(flat_e, e)
        cnt_de = counts.reshape(ep, e_l)                  # [dest, le]
        shard_cnt = cnt_de.sum(axis=1)                    # [ep]
        shard_start = jnp.cumsum(shard_cnt) - shard_cnt
        # per-(dest, expert) counts that fit the slot (tail clipped)
        exp_off = jnp.cumsum(cnt_de, axis=1) - cnt_de
        cnt_send = jnp.clip(jnp.minimum(cnt_de, cap_pair - exp_off),
                            0, None).astype(jnp.int32)
        # gather pairs into send slots (dest-major sorted order)
        pslot = shard_start[:, None] + jnp.arange(cap_pair)[None, :]
        sent = jnp.arange(cap_pair)[None, :] < jnp.minimum(
            shard_cnt, cap_pair)[:, None]                 # [ep, cap]
        send_pair = jnp.take(order, jnp.clip(pslot, 0, n_l - 1))
        send = jnp.take(x_l, (send_pair // k).reshape(-1), axis=0) \
            .reshape(ep, cap_pair, d)
        cnt_recv = jax.lax.all_to_all(cnt_send, axis, 0, 0)
        recv = jax.lax.all_to_all(send, axis, 0, 0)       # [src, cap, d]
        # local expert id of each received row from the counts matrix;
        # rows past a slot's total get sentinel e_l and sort last
        bounds = jnp.cumsum(cnt_recv, axis=1)             # [src, e_l]
        j = jnp.arange(cap_pair)
        eid = (j[None, :, None] >= bounds[:, None, :]).sum(-1) \
            .astype(jnp.int32)
        order2, rank2, _ = _sort_pairs(eid.reshape(-1), e_l)
        xs = jnp.take(recv.reshape(n_r, d), order2, axis=0)
        gs = cnt_recv.sum(axis=0).astype(jnp.int32)
        gs = gs.at[e_l - 1].add(
            jnp.int32(n_r) - jnp.sum(gs, dtype=jnp.int32))    # pads
        gu = _grouped_mm(xs, gu_w.astype(xs.dtype), gs)
        g_a, u_a = jnp.split(gu, 2, axis=-1)
        h = jax.nn.silu(g_a.astype(jnp.float32)).astype(xs.dtype) * u_a
        ys = _grouped_mm(h, dn_w.astype(xs.dtype), gs)
        back = jnp.take(ys, rank2, axis=0).reshape(ep, cap_pair, d)
        outs = jax.lax.all_to_all(back, axis, 0, 0)       # [dest, cap, d]
        dest = flat_e // e_l
        off = rank - shard_start[dest]
        kept = off < cap_pair
        slot = dest * cap_pair + jnp.minimum(off, cap_pair - 1)
        per_pair = jnp.take(outs.reshape(n_r, d), slot, axis=0)
        per_pair = jnp.where(kept[:, None], per_pair,
                             jnp.zeros((), x_l.dtype))
        picked = per_pair.reshape(s_l, k, d)
        y = jnp.einsum("sk,skd->sd", gates_l.astype(x_l.dtype), picked)
        drop = jax.lax.psum(jnp.sum((~kept).astype(jnp.float32)),
                            axis) / float(s * k)
        res = (xs, gu, picked, gates_l, send_pair, sent, order2,
               rank2, kept, slot, gs, gu_w, dn_w)
        return (y, drop), res

    @jax.custom_vjp
    def core(x_l, gates_l, idx_l, gu_w, dn_w):
        out, _ = _fwd(x_l, gates_l, idx_l, gu_w, dn_w)
        return out

    def core_fwd(x_l, gates_l, idx_l, gu_w, dn_w):
        return _fwd(x_l, gates_l, idx_l, gu_w, dn_w)

    def core_bwd(res, ct):
        dy, _ddrop = ct
        (xs, gu, picked, gates_l, send_pair, sent, order2, rank2,
         kept, slot, gs, gu_w, dn_w) = res
        dy32 = dy.astype(jnp.float32)
        dgates = jnp.einsum("sd,skd->sk", dy32,
                            picked.astype(jnp.float32))
        # per-pair output cotangent routed through the SAME slots
        dpair = (gates_l.astype(jnp.float32)[..., None]
                 * dy32[:, None, :]).reshape(n_l, d).astype(dy.dtype)
        dsend = jnp.take(dpair, send_pair.reshape(-1), axis=0) \
            .reshape(ep, cap_pair, d)
        dsend = jnp.where(sent[..., None], dsend,
                          jnp.zeros((), dsend.dtype))
        dback = jax.lax.all_to_all(dsend, axis, 0, 0)
        dys = jnp.take(dback.reshape(n_r, d), order2, axis=0)
        g_a, u_a = jnp.split(gu, 2, axis=-1)
        g32 = g_a.astype(jnp.float32)
        sg = jax.nn.silu(g32)
        h = (sg * u_a.astype(jnp.float32)).astype(xs.dtype)
        ddn = _grouped_mm_drhs(h, dys, gs, e_l)
        dh = _grouped_mm_dlhs(dys, dn_w.astype(dys.dtype), gs) \
            .astype(jnp.float32)
        sig = jax.nn.sigmoid(g32)
        dg = dh * u_a.astype(jnp.float32) * sig * (1 + g32 * (1 - sig))
        du = dh * sg
        dgu = jnp.concatenate([dg, du], axis=-1).astype(xs.dtype)
        dguw = _grouped_mm_drhs(xs, dgu, gs, e_l)
        dxs = _grouped_mm_dlhs(dgu, gu_w.astype(dgu.dtype), gs)
        drecv = jnp.take(dxs, rank2, axis=0).reshape(ep, cap_pair, d)
        dsent = jax.lax.all_to_all(drecv, axis, 0, 0)
        dpx = jnp.take(dsent.reshape(n_r, d), slot, axis=0)
        dpx = jnp.where(kept[:, None], dpx, jnp.zeros((), dpx.dtype))
        dx = dpx.reshape(s_l, k, d).sum(axis=1)
        return (dx.astype(xs.dtype), dgates.astype(gates_l.dtype),
                None, dguw.astype(gu_w.dtype), ddn.astype(dn_w.dtype))

    core.defvjp(core_fwd, core_bwd)

    from .shard_utils import shard_map_compat
    from jax.sharding import PartitionSpec as P
    f = shard_map_compat(
        core, mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis, None),
                  P(axis, None, None), P(axis, None, None)),
        out_specs=(P(axis, None), P()))
    return f(x, gates, topk_idx, gate_up, down)


def _ep_constraint(arr, axis):
    from . import env as _env
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _env.get_mesh()
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return arr
    spec = P(*([axis] + [None] * (arr.ndim - 1)))
    try:
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, spec))
    except Exception as exc:
        import warnings
        warnings.warn(
            f"moe: expert-parallel sharding constraint on axis {axis!r} "
            f"failed ({exc!r}); expert compute stays replicated")
        return arr


class MoELayer(Layer):
    """``MoELayer`` parity. experts: list of Layers (one per local
    expert) with identical structure; their params are stacked into
    [e, ...] arrays sharded over ``moe_axis``."""

    def __init__(self, d_model, experts: List[Layer] = None, gate=None,
                 moe_group=None, mp_group=None, recompute_interval=0,
                 top_k=2, capacity_factor=None, moe_axis="dp", **kwargs):
        super().__init__()
        self.d_model = d_model
        from ..nn.layer.container import LayerList
        self.experts = LayerList(experts or [])
        self.num_expert = len(self.experts)
        if gate is None or isinstance(gate, dict):
            cfg = gate or {}
            gtype = cfg.get("type", "gshard")
            topk = cfg.get("top_k", top_k)
            cls = {"naive": NaiveGate, "gshard": GShardGate,
                   "switch": SwitchGate}[gtype]
            gate = cls(d_model, self.num_expert, topk=topk)
        self.gate = gate
        self.top_k = getattr(gate, "top_k", top_k)
        # explicit layer arg wins; else the gate's capacity; else 1.25
        if capacity_factor is not None:
            self.capacity_factor = capacity_factor
        else:
            gate_cap = getattr(gate, "capacity_factor", None)
            self.capacity_factor = 1.25 if gate_cap is None else gate_cap
        self.moe_axis = moe_axis
        # stacked expert params: [e, ...] (template = expert 0)
        self._template = self.experts[0] if self.num_expert else None
        # mark for MoE-aware grad clip (ClipGradForMOEByGlobalNorm)
        for exp in self.experts:
            for p in exp.parameters():
                p.is_expert_param = True
        self.drop_rate = None

    def _flat_params(self):
        """All expert params expert-major, as the live Tensor objects (so
        the tape records grads against each expert's own parameters)."""
        items = [list(exp.named_parameters()) for exp in self.experts]
        n_per = len(items[0])
        flat = [p for exp_items in items for _, p in exp_items]
        return n_per, flat

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        from ..ops.manipulation import reshape
        x2 = reshape(x, [-1, d])
        logits = self.gate(x2)
        n_per, flat_params = self._flat_params()
        e = self.num_expert
        template = self._template
        param_objs = [p for _, p in template.named_parameters()]

        second_policy = getattr(self.gate, "second_expert_policy", "all")
        rng_key = None
        if second_policy == "random" and self.training:
            from ..framework import random as _random
            rng_key = _random.next_key()

        def f(x_arr, logit_arr, *flat):
            # restack [e, ...] per param position from the flat operands
            stk = [jnp.stack([flat[i * n_per + j] for i in range(e)],
                             axis=0) for j in range(n_per)]

            def efn(expert_in):
                def one(args):
                    params_i, xi = args
                    saved = [p._data for p in param_objs]
                    try:
                        for p, arr in zip(param_objs, params_i):
                            p._data = arr
                        from ..framework.core import no_grad, \
                            functional_mode
                        with functional_mode(), no_grad():
                            out = template(Tensor(xi))
                        return as_jax(out)
                    finally:
                        for p, arr in zip(param_objs, saved):
                            p._data = arr
                return jax.lax.map(one, (tuple(stk), expert_in))
            y, aux, stats = moe_dispatch_combine(
                x_arr, logit_arr, self.num_expert, self.top_k,
                self.capacity_factor, efn, self.moe_axis,
                second_expert_policy=second_policy, rng_key=rng_key,
                return_stats=True)
            return y, aux, stats["drop_rate"]

        y, aux, drop = apply_jax("moe", f, x2, logits, *flat_params,
                                 n_outputs=3)
        self.gate.loss = aux
        self._aux_loss = aux
        self.drop_rate = drop
        return reshape(y, list(orig_shape))
