"""Mixture-of-Experts with expert parallelism
(``python/paddle/incubate/distributed/models/moe/moe_layer.py`` +
``gate/*.py`` parity).

TPU-first (SURVEY.md §7.4): GShard-style static-capacity dispatch. Expert
weights are stacked with a leading expert dim sharded over the expert
axis; dispatch/combine are einsums against one-hot capacity masks, so the
all-to-all the reference codes against ProcessGroup appears as GSPMD
collectives when the expert dim is mesh-sharded. Static shapes throughout
(capacity padding), as jit requires.
"""
from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ..nn import functional as F
from ..nn.layer.layers import Layer
from .shard_utils import annotate_param, constraint, mesh_axis_size

__all__ = ["MoELayer", "NaiveGate", "GShardGate", "SwitchGate",
           "moe_dispatch_combine"]


class BaseGate(Layer):
    def __init__(self, d_model, num_expert):
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert
        self.loss = None


class NaiveGate(BaseGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(d_model, num_expert)
        from ..nn.layer.common import Linear
        self.gate = Linear(d_model, num_expert)
        self.top_k = topk

    def forward(self, x):
        return self.gate(x)


class GShardGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), group=None, gate_bias=True):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity_factor = capacity[0]


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.capacity_factor = capacity[0]


def moe_dispatch_combine(x, gate_logits, num_expert, top_k=2,
                         capacity_factor=1.25, expert_fn=None,
                         expert_axis=None, normalize_gates=True):
    """Pure-array GShard dispatch → expert_fn → combine.

    x: [tokens, d]; gate_logits: [tokens, e]. expert_fn(inputs[e, c, d])
    -> [e, c, d]. Returns (y [tokens, d], aux_loss scalar).
    ``normalize_gates=False`` combines with the raw softmax probs of the
    selected experts (Qwen2-MoE/DeepSeek ``norm_topk_prob=False``).
    """
    s, d = x.shape
    e = num_expert
    c = max(int(math.ceil(capacity_factor * s * top_k / e)), 1)

    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    # top-k selection
    topk_prob, topk_idx = jax.lax.top_k(probs, top_k)  # [s, k]

    # position of each (token, k) within its expert's queue
    onehot = jax.nn.one_hot(topk_idx, e, dtype=jnp.int32)  # [s, k, e]
    flat = onehot.reshape(s * top_k, e)
    pos_in_expert = (jnp.cumsum(flat, axis=0) - flat).reshape(
        s, top_k, e)  # [s, k, e]
    pos = jnp.sum(pos_in_expert * onehot, axis=-1)  # [s, k]
    keep = pos < c

    # load-balancing aux loss (GShard eq.: e * sum(me * ce))
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(onehot[:, 0].astype(jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)

    if normalize_gates:
        gates = topk_prob / jnp.maximum(
            jnp.sum(topk_prob, axis=-1, keepdims=True), 1e-9)
    else:
        gates = topk_prob
    gates = jnp.where(keep, gates, 0.0).astype(x.dtype)

    # dispatch mask [s, k, e, c]
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, c), c + 1,
                            dtype=x.dtype)[..., :c]
    disp = onehot.astype(x.dtype)[..., None] * pos_oh[:, :, None, :]
    disp = jnp.sum(disp, axis=1)               # [s, e, c]
    comb = jnp.einsum("sk,ske,skc->sec", gates,
                      onehot.astype(x.dtype), pos_oh)

    expert_in = jnp.einsum("sec,sd->ecd", disp, x)
    if expert_axis is not None:
        expert_in = _ep_constraint(expert_in, expert_axis)
    expert_out = expert_fn(expert_in)          # [e, c, d_out]
    if expert_axis is not None:
        expert_out = _ep_constraint(expert_out, expert_axis)
    y = jnp.einsum("sec,ecd->sd", comb, expert_out)
    return y, aux


def _ep_constraint(arr, axis):
    from . import env as _env
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _env.get_mesh()
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return arr
    spec = P(*([axis] + [None] * (arr.ndim - 1)))
    try:
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, spec))
    except Exception:
        return arr


class MoELayer(Layer):
    """``MoELayer`` parity. experts: list of Layers (one per local
    expert) with identical structure; their params are stacked into
    [e, ...] arrays sharded over ``moe_axis``."""

    def __init__(self, d_model, experts: List[Layer] = None, gate=None,
                 moe_group=None, mp_group=None, recompute_interval=0,
                 top_k=2, capacity_factor=1.25, moe_axis="dp", **kwargs):
        super().__init__()
        self.d_model = d_model
        from ..nn.layer.container import LayerList
        self.experts = LayerList(experts or [])
        self.num_expert = len(self.experts)
        if gate is None or isinstance(gate, dict):
            cfg = gate or {}
            gtype = cfg.get("type", "gshard")
            topk = cfg.get("top_k", top_k)
            cls = {"naive": NaiveGate, "gshard": GShardGate,
                   "switch": SwitchGate}[gtype]
            gate = cls(d_model, self.num_expert, topk=topk)
        self.gate = gate
        self.top_k = getattr(gate, "top_k", top_k)
        self.capacity_factor = capacity_factor
        self.moe_axis = moe_axis
        # stacked expert params: [e, ...] (template = expert 0)
        self._template = self.experts[0] if self.num_expert else None

    def _flat_params(self):
        """All expert params expert-major, as the live Tensor objects (so
        the tape records grads against each expert's own parameters)."""
        items = [list(exp.named_parameters()) for exp in self.experts]
        n_per = len(items[0])
        flat = [p for exp_items in items for _, p in exp_items]
        return n_per, flat

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        from ..ops.manipulation import reshape
        x2 = reshape(x, [-1, d])
        logits = self.gate(x2)
        n_per, flat_params = self._flat_params()
        e = self.num_expert
        template = self._template
        param_objs = [p for _, p in template.named_parameters()]

        def f(x_arr, logit_arr, *flat):
            # restack [e, ...] per param position from the flat operands
            stk = [jnp.stack([flat[i * n_per + j] for i in range(e)],
                             axis=0) for j in range(n_per)]

            def efn(expert_in):
                def one(args):
                    params_i, xi = args
                    saved = [p._data for p in param_objs]
                    try:
                        for p, arr in zip(param_objs, params_i):
                            p._data = arr
                        from ..framework.core import no_grad, \
                            functional_mode
                        with functional_mode(), no_grad():
                            out = template(Tensor(xi))
                        return as_jax(out)
                    finally:
                        for p, arr in zip(param_objs, saved):
                            p._data = arr
                return jax.lax.map(one, (tuple(stk), expert_in))
            y, aux = moe_dispatch_combine(
                x_arr, logit_arr, self.num_expert, self.top_k,
                self.capacity_factor, efn, self.moe_axis)
            return y, aux

        y, aux = apply_jax("moe", f, x2, logits, *flat_params,
                           n_outputs=2)
        self.gate.loss = aux
        self._aux_loss = aux
        return reshape(y, list(orig_shape))
