"""Mixture-of-Experts with expert parallelism
(``python/paddle/incubate/distributed/models/moe/moe_layer.py`` +
``gate/*.py`` parity).

TPU-first (SURVEY.md §7.4): GShard-style static-capacity dispatch. Expert
weights are stacked with a leading expert dim sharded over the expert
axis; dispatch/combine are einsums against one-hot capacity masks, so the
all-to-all the reference codes against ProcessGroup appears as GSPMD
collectives when the expert dim is mesh-sharded. Static shapes throughout
(capacity padding), as jit requires.
"""
from __future__ import annotations

import math
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ..nn import functional as F
from ..nn.layer.layers import Layer
from .shard_utils import annotate_param, constraint, mesh_axis_size

__all__ = ["MoELayer", "NaiveGate", "GShardGate", "SwitchGate",
           "moe_dispatch_combine", "ClipGradForMOEByGlobalNorm"]


from ..nn.clip import ClipGradByGlobalNorm as _ClipGradByGlobalNorm


class ClipGradForMOEByGlobalNorm(_ClipGradByGlobalNorm):
    """MoE-aware global-norm clip (reference:
    ``incubate/distributed/models/moe/grad_clip.py``). The reference
    splits (param, grad) pairs into expert / non-expert sets and
    all-reduces the expert-set norm over the moe group, because with EP
    each rank holds only its local experts; expert_sq + normal_sq is
    then the true global norm. TPU-first: expert params are stacked
    GSPMD arrays that are *logically global*, so the plain global norm
    over all grads is already the same quantity — this subclass exists
    so reference scripts construct the same class name, and keeps the
    constructor surface (predicate/group args are metadata here)."""

    def __init__(self, clip_norm, is_expert_param_func=None,
                 moe_group=None, group_name="default_moe_group"):
        super().__init__(clip_norm, group_name=group_name)
        self.is_expert_param_func = is_expert_param_func
        self.moe_group = moe_group


class BaseGate(Layer):
    def __init__(self, d_model, num_expert):
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert
        self.loss = None


class NaiveGate(BaseGate):
    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(d_model, num_expert)
        from ..nn.layer.common import Linear
        self.gate = Linear(d_model, num_expert)
        self.top_k = topk

    def forward(self, x):
        return self.gate(x)


class GShardGate(NaiveGate):
    """GShard top-2 gate (``gate/gshard_gate.py`` parity): the 2nd-choice
    expert receives the token only with probability ``min(1, 2*g2)``
    (GShard's random routing), sampled per token during training."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), group=None, gate_bias=True):
        super().__init__(d_model, num_expert, world_size, topk)
        self.capacity_factor = capacity[0]
        self.second_expert_policy = "random"


class SwitchGate(NaiveGate):
    """Switch top-1 gate (``gate/switch_gate.py`` parity): multiplicative
    jitter noise ``U(1-eps, 1+eps)`` on the router input during
    training; capacity-drop statistics surface via ``drop_rate``."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.capacity_factor = capacity[0]
        self.switch_eps = float(switch_eps)

    def forward(self, x):
        if self.training and self.switch_eps > 0:
            from ..framework import random as _random
            key = _random.next_key()
            eps = self.switch_eps

            def jitter(a):
                noise = jax.random.uniform(
                    key, a.shape, jnp.float32, 1.0 - eps, 1.0 + eps)
                return a * noise.astype(a.dtype)
            x = apply_jax("switch_jitter", jitter, x)
        return self.gate(x)


def moe_dispatch_combine(x, gate_logits, num_expert, top_k=2,
                         capacity_factor=1.25, expert_fn=None,
                         expert_axis=None, normalize_gates=True,
                         second_expert_policy="all", rng_key=None,
                         return_stats=False):
    """Pure-array GShard dispatch → expert_fn → combine.

    x: [tokens, d]; gate_logits: [tokens, e]. expert_fn(inputs[e, c, d])
    -> [e, c, d]. Returns (y [tokens, d], aux_loss scalar), plus a stats
    dict (capacity ``drop_rate``) when ``return_stats``.
    ``normalize_gates=False`` combines with the raw softmax probs of the
    selected experts (Qwen2-MoE/DeepSeek ``norm_topk_prob=False``).
    ``second_expert_policy="random"`` + ``rng_key`` enables GShard's
    random routing: slot j>=1 dispatches with probability
    ``min(1, k * g_j)``.
    """
    s, d = x.shape
    e = num_expert
    c = max(int(math.ceil(capacity_factor * s * top_k / e)), 1)

    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    # top-k selection
    topk_prob, topk_idx = jax.lax.top_k(probs, top_k)  # [s, k]

    # position of each (token, k) within its expert's queue
    onehot = jax.nn.one_hot(topk_idx, e, dtype=jnp.int32)  # [s, k, e]

    sel = None
    if second_expert_policy == "random" and rng_key is not None \
            and top_k >= 2:
        u = jax.random.uniform(rng_key, (s, top_k))
        sel = u < jnp.minimum(top_k * topk_prob, 1.0)
        sel = sel.at[:, 0].set(True)  # 1st choice always dispatches
        onehot = onehot * sel[..., None].astype(onehot.dtype)

    flat = onehot.reshape(s * top_k, e)
    pos_in_expert = (jnp.cumsum(flat, axis=0) - flat).reshape(
        s, top_k, e)  # [s, k, e]
    pos = jnp.sum(pos_in_expert * onehot, axis=-1)  # [s, k]
    slot_used = jnp.sum(onehot, axis=-1) > 0  # [s, k]
    keep = (pos < c) & slot_used

    # load-balancing aux loss (GShard eq.: e * sum(me * ce))
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(onehot[:, 0].astype(jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)

    # random-skipped slots are zeroed BEFORE normalization (GShard/
    # fairseq top2gating order): a token whose 2nd expert was skipped
    # combines with weight ~1.0, not g1/(g1+g2)
    eff_prob = topk_prob if sel is None \
        else topk_prob * sel.astype(topk_prob.dtype)
    if normalize_gates:
        gates = eff_prob / jnp.maximum(
            jnp.sum(eff_prob, axis=-1, keepdims=True), 1e-9)
    else:
        gates = eff_prob
    gates = jnp.where(keep, gates, 0.0).astype(x.dtype)

    # scatter-pack tokens into expert buffers — NO [s, e, c] one-hot
    # mask (the einsum formulation materializes s*e*c elements, which
    # OOMs at real MoE scale); dropped slots scatter into a discard row
    flat_e = topk_idx.reshape(-1)                       # [s*k]
    flat_p = jnp.where(keep, pos, c).reshape(-1)        # [s*k]
    src = jnp.broadcast_to(x[:, None, :], (s, top_k, d)) \
        .reshape(s * top_k, d)
    src = src * keep.reshape(-1, 1).astype(x.dtype)
    buf = jnp.zeros((e, c + 1, d), x.dtype)
    buf = buf.at[flat_e, flat_p].add(src)
    expert_in = buf[:, :c]
    if expert_axis is not None:
        expert_in = _ep_constraint(expert_in, expert_axis)
    expert_out = expert_fn(expert_in)          # [e, c, d_out]
    if expert_axis is not None:
        expert_out = _ep_constraint(expert_out, expert_axis)
    # combine: gather each (token, slot)'s expert output
    kp_safe = jnp.minimum(flat_p, c - 1).reshape(s, top_k)
    picked = expert_out[topk_idx, kp_safe]     # [s, k, d_out]
    y = jnp.einsum("sk,skd->sd", gates, picked)
    if return_stats:
        # fraction of requested (token, slot) dispatches that were
        # dropped — capacity overflow plus random-routing skips
        stats = {"drop_rate": 1.0 - jnp.sum(keep.astype(jnp.float32))
                 / float(s * top_k)}
        return y, aux, stats
    return y, aux


def moe_dispatch_combine_dropless(x, gate_logits, num_expert, top_k,
                                  gate_up, down, normalize_gates=True,
                                  expert_axis=None, return_stats=False):
    """DROPLESS dispatch → SwiGLU experts → combine (reference:
    capacity-free routing the fused-MoE kernels in
    ``phi/kernels/fusion/`` approximate; design follows the MegaBlocks
    grouped-matmul formulation).

    No capacity factor and no dropped tokens: (token, slot) pairs are
    sorted by expert and the expert MLP runs as TWO grouped ragged
    matmuls (``jax.lax.ragged_dot`` — XLA's native grouped-GEMM on TPU,
    tiling each ragged expert segment onto the MXU), so each expert
    processes exactly its routed tokens. Under an expert-sharded mesh
    the cross-device exchange this implies is ``ragged_all_to_all``;
    inside one jitted program GSPMD inserts the equivalent collectives
    from the sharding annotations.

    x: [s, d]; gate_logits: [s, e]; gate_up: [e, d, 2f]; down: [e, f, d].
    Returns (y [s, d], aux) (+ stats dict with drop_rate=0.0).
    """
    s, d = x.shape
    e = num_expert
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    topk_prob, topk_idx = jax.lax.top_k(probs, top_k)       # [s, k]

    # sort (token, slot) pairs by destination expert; stable order keeps
    # in-expert arrival order deterministic
    flat_e = topk_idx.reshape(-1)                           # [s*k]
    order = jnp.argsort(flat_e, stable=True)
    xs = x[order // top_k]                                  # [s*k, d]
    group_sizes = jnp.bincount(flat_e, length=e).astype(jnp.int32)

    # expert weights shard over the EP axis (same constraint the
    # capacity path puts on its expert buffers); GSPMD turns the
    # token-side exchange into the ragged all-to-all equivalent
    if expert_axis is not None:
        gate_up = _ep_constraint(gate_up, expert_axis)
        down = _ep_constraint(down, expert_axis)
    gu = jax.lax.ragged_dot(xs, gate_up.astype(xs.dtype), group_sizes)
    g, u = jnp.split(gu, 2, axis=-1)
    h = (jax.nn.silu(g.astype(jnp.float32)).astype(xs.dtype) * u)
    ys = jax.lax.ragged_dot(h, down.astype(xs.dtype), group_sizes)

    # unsort back to (token, slot) order and combine — inverse-permute
    # by GATHER (argsort of the sort order), not scatter: TPU gathers
    # are cheaper than .at[].set scatters
    inv = jnp.argsort(order)
    picked = ys[inv].reshape(s, top_k, -1)                  # [s, k, d]

    if normalize_gates:
        gates = topk_prob / jnp.maximum(
            jnp.sum(topk_prob, axis=-1, keepdims=True), 1e-9)
    else:
        gates = topk_prob
    y = jnp.einsum("sk,skd->sd", gates.astype(x.dtype), picked)

    # same GShard load-balance aux as the capacity path
    me = jnp.mean(probs, axis=0)
    onehot0 = jax.nn.one_hot(topk_idx[:, 0], e, dtype=jnp.float32)
    aux = e * jnp.sum(me * jnp.mean(onehot0, axis=0))
    if return_stats:
        return y, aux, {"drop_rate": jnp.float32(0.0)}
    return y, aux


def _ep_constraint(arr, axis):
    from . import env as _env
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _env.get_mesh()
    if mesh is None or mesh.shape.get(axis, 1) <= 1:
        return arr
    spec = P(*([axis] + [None] * (arr.ndim - 1)))
    try:
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, spec))
    except Exception as exc:
        import warnings
        warnings.warn(
            f"moe: expert-parallel sharding constraint on axis {axis!r} "
            f"failed ({exc!r}); expert compute stays replicated")
        return arr


class MoELayer(Layer):
    """``MoELayer`` parity. experts: list of Layers (one per local
    expert) with identical structure; their params are stacked into
    [e, ...] arrays sharded over ``moe_axis``."""

    def __init__(self, d_model, experts: List[Layer] = None, gate=None,
                 moe_group=None, mp_group=None, recompute_interval=0,
                 top_k=2, capacity_factor=None, moe_axis="dp", **kwargs):
        super().__init__()
        self.d_model = d_model
        from ..nn.layer.container import LayerList
        self.experts = LayerList(experts or [])
        self.num_expert = len(self.experts)
        if gate is None or isinstance(gate, dict):
            cfg = gate or {}
            gtype = cfg.get("type", "gshard")
            topk = cfg.get("top_k", top_k)
            cls = {"naive": NaiveGate, "gshard": GShardGate,
                   "switch": SwitchGate}[gtype]
            gate = cls(d_model, self.num_expert, topk=topk)
        self.gate = gate
        self.top_k = getattr(gate, "top_k", top_k)
        # explicit layer arg wins; else the gate's capacity; else 1.25
        if capacity_factor is not None:
            self.capacity_factor = capacity_factor
        else:
            gate_cap = getattr(gate, "capacity_factor", None)
            self.capacity_factor = 1.25 if gate_cap is None else gate_cap
        self.moe_axis = moe_axis
        # stacked expert params: [e, ...] (template = expert 0)
        self._template = self.experts[0] if self.num_expert else None
        # mark for MoE-aware grad clip (ClipGradForMOEByGlobalNorm)
        for exp in self.experts:
            for p in exp.parameters():
                p.is_expert_param = True
        self.drop_rate = None

    def _flat_params(self):
        """All expert params expert-major, as the live Tensor objects (so
        the tape records grads against each expert's own parameters)."""
        items = [list(exp.named_parameters()) for exp in self.experts]
        n_per = len(items[0])
        flat = [p for exp_items in items for _, p in exp_items]
        return n_per, flat

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        from ..ops.manipulation import reshape
        x2 = reshape(x, [-1, d])
        logits = self.gate(x2)
        n_per, flat_params = self._flat_params()
        e = self.num_expert
        template = self._template
        param_objs = [p for _, p in template.named_parameters()]

        second_policy = getattr(self.gate, "second_expert_policy", "all")
        rng_key = None
        if second_policy == "random" and self.training:
            from ..framework import random as _random
            rng_key = _random.next_key()

        def f(x_arr, logit_arr, *flat):
            # restack [e, ...] per param position from the flat operands
            stk = [jnp.stack([flat[i * n_per + j] for i in range(e)],
                             axis=0) for j in range(n_per)]

            def efn(expert_in):
                def one(args):
                    params_i, xi = args
                    saved = [p._data for p in param_objs]
                    try:
                        for p, arr in zip(param_objs, params_i):
                            p._data = arr
                        from ..framework.core import no_grad, \
                            functional_mode
                        with functional_mode(), no_grad():
                            out = template(Tensor(xi))
                        return as_jax(out)
                    finally:
                        for p, arr in zip(param_objs, saved):
                            p._data = arr
                return jax.lax.map(one, (tuple(stk), expert_in))
            y, aux, stats = moe_dispatch_combine(
                x_arr, logit_arr, self.num_expert, self.top_k,
                self.capacity_factor, efn, self.moe_axis,
                second_expert_policy=second_policy, rng_key=rng_key,
                return_stats=True)
            return y, aux, stats["drop_rate"]

        y, aux, drop = apply_jax("moe", f, x2, logits, *flat_params,
                                 n_outputs=3)
        self.gate.loss = aux
        self._aux_loss = aux
        self.drop_rate = drop
        return reshape(y, list(orig_shape))
