"""Distributed environment: the Mesh is the ProcessGroup.

Reference parity: ``ProcessGroupNCCL`` + ``TCPStore`` bootstrap
(``paddle/fluid/distributed/collective/``, ``paddle/fluid/distributed/
store/tcp_store.cc``). TPU-first: ``jax.distributed.initialize`` is the
rendezvous, ``jax.sharding.Mesh`` axes are the process groups, collectives
are XLA ops over ICI/DCN (SURVEY.md §5.8 mapping).

Single-controller jax means "rank" here is the process index
(``jax.process_index``), and intra-process device parallelism is expressed
with shardings rather than ranks.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

import jax


class ParallelEnv:
    """``paddle.distributed.ParallelEnv`` parity."""

    def __init__(self):
        self._init_from_env()

    def _init_from_env(self):
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID",
                                       jax.process_index()))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        n_env = len(eps.split(",")) if eps else jax.process_count()
        self.world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", n_env))
        self.device_id = int(os.environ.get("FLAGS_selected_gpus",
                                            "0").split(",")[0])
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                               "127.0.0.1:6170")
        self.trainer_endpoints = eps.split(",") if eps else [
            self.current_endpoint]

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank

    @property
    def dev_id(self):
        return self.device_id


_parallel_env: Optional[ParallelEnv] = None
_initialized = False
_global_mesh: Optional[jax.sharding.Mesh] = None


def _env() -> ParallelEnv:
    global _parallel_env
    if _parallel_env is None:
        _parallel_env = ParallelEnv()
    return _parallel_env


def init_parallel_env(strategy=None):
    """``paddle.distributed.init_parallel_env`` — multi-host rendezvous via
    the jax coordination service when endpoints are configured."""
    global _initialized
    if _initialized:
        return _env()
    env = _env()
    coord = os.environ.get("PADDLE_MASTER") or \
        os.environ.get("MASTER_ADDR")
    if coord and env.world_size > 1 and jax.process_count() == 1:
        port = os.environ.get("MASTER_PORT", "8476")
        try:
            jax.distributed.initialize(
                coordinator_address=f"{coord}:{port}"
                if ":" not in coord else coord,
                num_processes=env.world_size, process_id=env.rank)
        except Exception:
            pass  # already initialized or single-host emulation
    log_dir = os.environ.get("PADDLE_LOG_DIR")
    if log_dir:
        from ..framework.log import init_per_rank_logging
        init_per_rank_logging(log_dir, rank=env.rank)
    from ..framework.log import vlog
    vlog(1, "init_parallel_env: rank %d / world %d", env.rank,
         env.world_size)
    if os.environ.get("PADDLE_ELASTIC_ENABLE") == "1" \
            and env.world_size > 1:
        try:
            _start_elastic_heartbeat(env, coord)
        except Exception as exc:
            import warnings
            warnings.warn(
                f"elastic heartbeat disabled: could not reach the "
                f"liveness store ({exc!r}); training continues without "
                "hang detection")
    _initialized = True
    return env


def _start_elastic_heartbeat(env, coord):
    """Opt-in (PADDLE_ELASTIC_ENABLE=1): register this rank with the
    native-TCPStore ElasticManager and beat in a daemon thread so the
    launch controller's watch loop sees liveness (SURVEY §5.3)."""
    import threading
    import time
    from .fleet.elastic import ElasticManager
    host = (coord or "127.0.0.1").split(":")[0]
    port = int(os.environ.get("PADDLE_ELASTIC_PORT", "6179"))
    interval = float(os.environ.get("PADDLE_ELASTIC_BEAT_S", "5"))
    # PADDLE_ELASTIC_EXTERNAL=1: the launch controller hosts the store
    # (it outlives pod restarts); otherwise rank 0 hosts it in-process
    external = os.environ.get("PADDLE_ELASTIC_EXTERNAL") == "1"
    mgr = ElasticManager(host=host, port=port, rank=env.rank,
                         world_size=env.world_size,
                         is_master=(not external) and env.rank == 0,
                         timeout=3 * interval)
    mgr.register()

    def beat():
        while not getattr(mgr, "_stop_beat", False):
            time.sleep(interval)
            try:
                mgr.heartbeat()
            except Exception:
                return  # store gone: job is tearing down

    t = threading.Thread(target=beat, daemon=True,
                         name="paddle-elastic-heartbeat")
    t.start()

    def _stop_at_exit():
        # a daemon thread killed mid-ctypes-RPC at interpreter shutdown
        # segfaults — stop it, join, then shut the socket down (close
        # unblocks any straggling RPC safely: tcp_store.cc close locks
        # the request mutex and only invalidates the fd)
        mgr._stop_beat = True
        t.join(timeout=interval + 1.0)
        try:
            mgr.deregister()  # clean exit != death: no spurious restart
            mgr.close()
        except Exception:
            pass

    import atexit
    atexit.register(_stop_at_exit)
    env.elastic_manager = mgr


def is_initialized() -> bool:
    return _initialized


def get_rank(group=None) -> int:
    if group is not None and hasattr(group, "rank"):
        return group.rank
    return _env().rank


def get_world_size(group=None) -> int:
    if group is not None and hasattr(group, "nranks"):
        return group.nranks
    return _env().world_size


def device_mesh(shape: Dict[str, int] = None) -> jax.sharding.Mesh:
    """The global device mesh. Default: all local devices on one 'dp' axis;
    fleet topology reshapes it into (pp, dp, sharding, sep, mp) axes."""
    global _global_mesh
    if shape is None:
        if _global_mesh is None:
            devs = np.array(jax.devices())
            _global_mesh = jax.sharding.Mesh(devs, ("dp",))
        return _global_mesh
    names = tuple(shape.keys())
    sizes = tuple(shape.values())
    devs = np.array(jax.devices())
    total = int(np.prod(sizes))
    if total > devs.size:
        raise ValueError(
            f"mesh {dict(shape)} needs {total} devices, "
            f"have {devs.size}")
    mesh = jax.sharding.Mesh(devs[:total].reshape(sizes), names)
    _global_mesh = mesh
    return mesh


def set_mesh(mesh: jax.sharding.Mesh):
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Optional[jax.sharding.Mesh]:
    return _global_mesh
