"""ZeRO-style sharded data parallel (``python/paddle/distributed/fleet/
meta_parallel/sharding/`` + ``group_sharded_parallel`` parity).

TPU mapping (SURVEY.md §7.2): the ``sharding`` mesh axis is an fsdp axis.
  - stage 1/2 (optimizer-state / +grad shard): parameters stay replicated,
    optimizer accumulators are sharded over the axis (XLA keeps the
    reduce-scatter + gathered update local to each shard).
  - stage 3 (parameter shard): parameters themselves are annotated
    ``P("sharding", ...)`` on dim 0; GSPMD all-gathers just-in-time for
    each layer's compute — the pre-fetch/release hook machinery of
    GroupShardedStage3 is the compiler's job here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.core import Tensor
from . import env as _env
from .shard_utils import annotate_param, mesh_axis_size

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "ShardingOptimizerStage2", "shard_optimizer_states"]


def _shardable_dim0(param, degree):
    return param.shape and param.shape[0] % degree == 0


def group_sharded_parallel(model, optimizer, level="p_g_os", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """``paddle.distributed.sharding.group_sharded_parallel`` parity.
    level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3)."""
    degree = mesh_axis_size("sharding")
    if degree <= 1:
        return model, optimizer, scaler
    if level == "p_g_os":
        for p in model.parameters():
            if _shardable_dim0(p, degree) and getattr(
                    p, "dist_spec", None) is None:
                spec = ["sharding"] + [None] * (len(p.shape) - 1)
                annotate_param(p, spec)
    shard_optimizer_states(optimizer, degree)
    return model, optimizer, scaler


def shard_optimizer_states(optimizer, degree=None):
    """Make optimizer accumulators shard over the ``sharding`` axis
    (stage-1 semantics). Works with both eager step() and TrainStep."""
    degree = degree or mesh_axis_size("sharding")
    mesh = _env.get_mesh()
    if mesh is None or degree <= 1:
        return optimizer
    orig_create = optimizer._create_accumulator

    def sharded_create(name, param, fill=0.0, dtype=None):
        acc = orig_create(name, param, fill, dtype)
        if hasattr(acc, "shape") and acc.shape and \
                acc.shape[0] % degree == 0:
            spec = P(*(["sharding"] + [None] * (acc.ndim - 1)))
            try:
                acc = jax.device_put(acc, NamedSharding(mesh, spec))
                optimizer._accumulators[name][id(param)] = acc
            except Exception:
                pass
        return acc

    optimizer._create_accumulator = sharded_create
    return optimizer


class ShardingOptimizerStage2:
    """GroupShardedOptimizerStage2 facade."""

    def __init__(self, params, optim, group=None, offload=False, **kw):
        self._optim = shard_optimizer_states(optim)

    def __getattr__(self, name):
        return getattr(self.__dict__["_optim"], name)


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ..framework.io import save
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
