"""ZeRO-style sharded data parallel (``python/paddle/distributed/fleet/
meta_parallel/sharding/`` + ``group_sharded_parallel`` parity).

TPU mapping (SURVEY.md §7.2): the ``sharding`` mesh axis is an fsdp axis.
  - stage 1/2 (optimizer-state / +grad shard): parameters stay replicated,
    optimizer accumulators are sharded over the axis (XLA keeps the
    reduce-scatter + gathered update local to each shard).
  - stage 3 (parameter shard): parameters themselves are annotated
    ``P("sharding", ...)`` on dim 0; GSPMD all-gathers just-in-time for
    each layer's compute — the pre-fetch/release hook machinery of
    GroupShardedStage3 is the compiler's job here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.core import Tensor
from . import env as _env
from .shard_utils import annotate_param, mesh_axis_size

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "ShardingOptimizerStage2", "shard_optimizer_states",
           "shard_gradients", "constrain_grad_shards",
           "GroupShardedScaler"]


def _shardable_dim0(param, degree):
    return param.shape and param.shape[0] % degree == 0


def group_sharded_parallel(model, optimizer, level="p_g_os", scaler=None,
                           group=None, offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """``paddle.distributed.sharding.group_sharded_parallel`` parity.
    level: 'os' (stage1) | 'os_g' (stage2) | 'p_g_os' (stage3)."""
    degree = mesh_axis_size("sharding")
    if degree <= 1:
        return model, optimizer, scaler
    if level == "p_g_os":
        skipped = []
        for p in model.parameters():
            if _shardable_dim0(p, degree) and getattr(
                    p, "dist_spec", None) is None:
                spec = ["sharding"] + [None] * (len(p.shape) - 1)
                annotate_param(p, spec)
            elif not _shardable_dim0(p, degree):
                skipped.append((p.name, tuple(p.shape)))
        if skipped:
            from ..framework.log import logger
            logger.warning(
                "sharding stage-3: %d parameter(s) have dim0 not "
                "divisible by degree %d and stay REPLICATED (first "
                "few: %s) — pad those dims for full memory savings",
                len(skipped), degree, skipped[:3])
    if level in ("os_g", "p_g_os"):
        shard_gradients(optimizer)
    shard_optimizer_states(optimizer, degree)
    if scaler is not None:
        scaler = GroupShardedScaler(scaler)
    return model, optimizer, scaler


def shard_optimizer_states(optimizer, degree=None):
    """Make optimizer accumulators shard over the ``sharding`` axis
    (stage-1 semantics). Works with both eager step() and TrainStep."""
    degree = degree or mesh_axis_size("sharding")
    mesh = _env.get_mesh()
    if mesh is None or degree <= 1:
        return optimizer
    orig_create = optimizer._create_accumulator

    def sharded_create(name, param, fill=0.0, dtype=None):
        acc = orig_create(name, param, fill, dtype)
        if hasattr(acc, "shape") and acc.shape and \
                acc.shape[0] % degree == 0:
            spec = P(*(["sharding"] + [None] * (acc.ndim - 1)))
            try:
                acc = jax.device_put(acc, NamedSharding(mesh, spec))
                optimizer._accumulators[name][id(param)] = acc
            except Exception as exc:
                import warnings
                warnings.warn(
                    f"sharding: could not shard optimizer state {name!r} "
                    f"for param shape {tuple(param.shape)}: {exc!r}; "
                    "state stays replicated")
        return acc

    optimizer._create_accumulator = sharded_create
    return optimizer


def shard_gradients(optimizer):
    """ZeRO stage-2 semantics (``GroupShardedStage2`` parity): mark the
    optimizer so the jitted TrainStep constrains every gradient to
    ``P("sharding")`` on dim 0. XLA then lowers the data-parallel grad
    all-reduce to reduce-scatter, the optimizer update consumes the
    local grad shard, and (with stage-1 sharded accumulators) the param
    write-back all-gathers — the reference's reduce-scatter-hook
    machinery, expressed as a sharding constraint."""
    optimizer._shard_grads = True
    return optimizer


def constrain_grad_shards(grads, params=None, axis="sharding"):
    """Apply the stage-2 grad sharding constraint to a list of (traced)
    grad arrays. ``params`` (matching Tensors, optional) let the
    constraint respect existing layouts: a grad whose param is already
    sharded on dim 0 (stage-3/mp) is skipped, and other dims keep the
    param's spec so mp-sharded grads are not resharded to replicated."""
    mesh = _env.get_mesh()
    degree = mesh_axis_size(axis)
    if mesh is None or degree <= 1:
        return grads
    params = params or [None] * len(grads)
    out = []
    n_constrained = n_skipped = 0
    for g, p in zip(grads, params):
        if g is None or getattr(g, "ndim", 0) < 1 \
                or g.shape[0] % degree != 0:
            out.append(g)
            n_skipped += 1
            continue
        pspec = getattr(p, "dist_spec", None) if p is not None else None
        rest = [None] * (g.ndim - 1)
        if pspec is not None:
            entries = list(pspec) + [None] * (g.ndim - len(pspec))
            if entries[0] is not None:
                out.append(g)  # dim 0 already owned by another axis
                n_skipped += 1
                continue
            rest = entries[1:g.ndim]
        spec = P(*([axis] + rest))
        out.append(jax.lax.with_sharding_constraint(
            g, NamedSharding(mesh, spec)))
        n_constrained += 1
    # stage-2 coverage telemetry (trace-time): how many grads actually
    # reduce-scatter vs stay replicated — a silent coverage drop is the
    # classic ZeRO-2 memory regression
    from .. import monitor as _monitor
    _monitor.gauge("zero2_grad_shards",
                   "grads constrained to the sharding axis vs skipped",
                   labels=("disposition",)) \
        .labels(disposition="constrained").set(n_constrained)
    _monitor.gauge("zero2_grad_shards", labels=("disposition",)) \
        .labels(disposition="skipped").set(n_skipped)
    return out


class GroupShardedScaler:
    """``GroupShardedScaler`` parity wrapper. The reference overrides
    ``unscale_`` to all-reduce the found-inf flag across shard ranks
    (each rank only checks its grad shard). Under GSPMD the finite
    check in ``amp.GradScaler`` reduces over full logical grad arrays,
    so the flag is already globally consistent — delegation IS the
    TPU-correct implementation; the class exists so reference scripts
    (`scaler = GroupShardedScaler(scaler)`) run unchanged."""

    def __init__(self, scaler):
        self._scaler = scaler

    def __getattr__(self, name):
        return getattr(self.__dict__["_scaler"], name)


class ShardingOptimizerStage2:
    """GroupShardedOptimizerStage2 facade."""

    def __init__(self, params, optim, group=None, offload=False, **kw):
        self._optim = shard_optimizer_states(optim)

    def __getattr__(self, name):
        return getattr(self.__dict__["_optim"], name)


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ..framework.io import save
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
