"""``paddle.distributed.fleet`` API (``python/paddle/distributed/fleet/``).

``fleet.init`` builds the hybrid mesh (pp, dp, sharding, sep, mp) from
DistributedStrategy degrees; ``distributed_model``/``distributed_optimizer``
place parameters/optimizer state onto it. Collectives are emitted by
GSPMD in the jitted step rather than by per-group NCCL communicators.
"""
from __future__ import annotations

from typing import Optional

from ...framework.core import Tensor
from .. import env as _env
from ..shard_utils import mesh_axis_size, place_param
from .distributed_strategy import DistributedStrategy
from .meta_parallel import (LayerDesc, PipelineLayer, PipelineParallel,
                            SharedLayerDesc, ShardingParallel,
                            TensorParallel, get_rng_state_tracker)
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, VocabParallelEmbedding)
from . import sequence_parallel_utils
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       get_hcg, set_hcg)

__all__ = ["init", "DistributedStrategy", "HybridCommunicateGroup",
           "CommunicateTopology", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_num", "worker_index", "is_first_worker", "barrier_worker",
           "LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "PipelineParallel", "ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy",
           "get_rng_state_tracker"]

_fleet_initialized = False
_strategy: Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective=False, strategy=None, log_level=2):
    global _fleet_initialized, _strategy
    _strategy = strategy or DistributedStrategy()
    _env.init_parallel_env()
    hcg = HybridCommunicateGroup(strategy=_strategy)
    set_hcg(hcg)
    _fleet_initialized = True
    return None


def is_initialized():
    return _fleet_initialized


def get_hybrid_communicate_group():
    return get_hcg()


def _place_model_params(model):
    for p in model.parameters():
        place_param(p)
    return model


def distributed_model(model):
    """Wrap per the active parallel mode (``fleet.distributed_model``)."""
    hcg = get_hcg()
    _place_model_params(model)
    if hcg is None:
        return model
    if isinstance(model, PipelineLayer) or \
            hcg.get_pipe_parallel_world_size() > 1:
        if isinstance(model, PipelineLayer):
            return PipelineParallel(model, hcg, _strategy)
    if hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, hcg, _strategy)
    if hcg.get_data_parallel_world_size() > 1 or \
            hcg.get_sharding_parallel_world_size() > 1:
        from ...parallel import DataParallel
        return DataParallel(model)
    return model


class HybridParallelOptimizer:
    """``fleet.distributed_optimizer`` result: delegates to the inner
    optimizer; hybrid grad sync happens in the jitted step via GSPMD."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner = optimizer
        self._hcg = hcg
        if strategy is not None and mesh_axis_size("sharding") > 1:
            stage = strategy.sharding_configs.get("stage", 1)
            if stage >= 1:
                from ..sharding import shard_optimizer_states
                shard_optimizer_states(optimizer)
            if stage >= 2:
                from ..sharding import shard_gradients
                shard_gradients(optimizer)

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner"], name)

    def step(self):
        self._inner.step()

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self._inner.step()
        return None, None


def distributed_optimizer(optimizer, strategy=None):
    return HybridParallelOptimizer(optimizer, get_hcg(),
                                   strategy or _strategy)


# worker info -----------------------------------------------------------

def worker_num():
    return _env.get_world_size()


def worker_index():
    return _env.get_rank()


def is_first_worker():
    return _env.get_rank() == 0


def barrier_worker():
    from ..collective import barrier
    barrier()


class UtilBase:
    def all_reduce(self, input, mode="sum"):
        return input

    def barrier(self):
        barrier_worker()


util = UtilBase()


# expose as fleet.fleet for `from paddle.distributed.fleet import fleet`
import sys as _sys
fleet = _sys.modules[__name__]
utils = sequence_parallel_utils
