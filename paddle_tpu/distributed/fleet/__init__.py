"""``paddle.distributed.fleet`` API (``python/paddle/distributed/fleet/``).

``fleet.init`` builds the hybrid mesh (pp, dp, sharding, sep, mp) from
DistributedStrategy degrees; ``distributed_model``/``distributed_optimizer``
place parameters/optimizer state onto it. Collectives are emitted by
GSPMD in the jitted step rather than by per-group NCCL communicators.
"""
from __future__ import annotations

from typing import Optional

from ...framework.core import Tensor
from .. import env as _env
from ..shard_utils import mesh_axis_size, place_param
from .distributed_strategy import DistributedStrategy
from .meta_parallel import (LayerDesc, PipelineLayer, PipelineParallel,
                            SharedLayerDesc, ShardingParallel,
                            TensorParallel, get_rng_state_tracker)
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, VocabParallelEmbedding)
from . import sequence_parallel_utils
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       get_hcg, set_hcg)

__all__ = ["init", "DistributedStrategy", "HybridCommunicateGroup",
           "CommunicateTopology", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_num", "worker_index", "is_first_worker", "barrier_worker",
           "LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "PipelineParallel", "ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy",
           "get_rng_state_tracker", "PaddleCloudRoleMaker", "is_server",
           "is_worker", "run_server", "init_worker", "stop_worker",
           "server_num", "server_index", "ps_client"]

_fleet_initialized = False
_strategy: Optional[DistributedStrategy] = None
_role_maker = None


class PaddleCloudRoleMaker:
    """Env-driven role maker (reference
    ``fleet/base/role_maker.py::PaddleCloudRoleMaker``): PS mode reads
    ``TRAINING_ROLE`` (TRAINER|PSERVER), ``PADDLE_PSERVERS_IP_PORT_LIST``,
    ``PADDLE_TRAINERS_NUM`` / ``PADDLE_TRAINER_ID`` /
    ``PADDLE_PSERVER_ID``."""

    def __init__(self, is_collective=False, **kwargs):
        import os
        self._is_collective = is_collective
        self.role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self.server_endpoints = [e for e in eps.split(",") if e]
        self.n_trainers = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        self.trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self.server_id = int(os.environ.get("PADDLE_PSERVER_ID", 0))

    def _is_server(self):
        return self.role == "PSERVER"

    def _server_num(self):
        return max(len(self.server_endpoints), 1)


def init(role_maker=None, is_collective=False, strategy=None, log_level=2):
    global _fleet_initialized, _strategy, _role_maker
    _strategy = strategy or DistributedStrategy()
    _role_maker = role_maker
    ps_mode = (role_maker is not None
               and getattr(role_maker, "server_endpoints", None)
               and not getattr(role_maker, "_is_collective", False))
    if not ps_mode:
        # collective mode: build the hybrid device mesh
        _env.init_parallel_env()
        hcg = HybridCommunicateGroup(strategy=_strategy)
        set_hcg(hcg)
    _fleet_initialized = True
    return None


# -- parameter-server role flow (reference fleet PS mode) ----------------

def is_server():
    return _role_maker is not None and _role_maker._is_server()


def is_worker():
    return _role_maker is None or not _role_maker._is_server()


def server_num():
    return _role_maker._server_num() if _role_maker else 0


def server_index():
    return _role_maker.server_id if _role_maker else 0


def _ps_world():
    n_s = _role_maker._server_num()
    return n_s, n_s + _role_maker.n_trainers


def run_server(drain_timeout=86400):
    """Host PS tables in this process and genuinely BLOCK until every
    trainer announces shutdown (reference ``fleet.run_server()``) —
    ``drain_timeout`` (default 24h) bounds the wait so a wedged job
    still terminates."""
    from ..ps import run_server as _run
    from .. import rpc
    n_s, world = _ps_world()
    _run(f"ps{_role_maker.server_id}", rank=_role_maker.server_id,
         world_size=world)
    rpc.shutdown(timeout=drain_timeout)


def init_worker():
    """Join the PS world as a trainer; returns the PSClient."""
    from .. import rpc
    from ..ps import PSClient
    n_s, world = _ps_world()
    rpc.init_rpc(f"trainer{_role_maker.trainer_id}",
                 rank=n_s + _role_maker.trainer_id, world_size=world)
    global _ps_client
    _ps_client = PSClient([f"ps{i}" for i in range(n_s)])
    return _ps_client


_ps_client = None


def ps_client():
    return _ps_client


def stop_worker():
    from .. import rpc
    rpc.shutdown()


def is_initialized():
    return _fleet_initialized


def get_hybrid_communicate_group():
    return get_hcg()


def _place_model_params(model):
    for p in model.parameters():
        place_param(p)
    return model


def distributed_model(model):
    """Wrap per the active parallel mode (``fleet.distributed_model``)."""
    hcg = get_hcg()
    _place_model_params(model)
    if hcg is None:
        return model
    if isinstance(model, PipelineLayer) or \
            hcg.get_pipe_parallel_world_size() > 1:
        if isinstance(model, PipelineLayer):
            return PipelineParallel(model, hcg, _strategy)
    if hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, hcg, _strategy)
    if hcg.get_data_parallel_world_size() > 1 or \
            hcg.get_sharding_parallel_world_size() > 1:
        from ...parallel import DataParallel
        return DataParallel(model)
    return model


class HybridParallelOptimizer:
    """``fleet.distributed_optimizer`` result: delegates to the inner
    optimizer; hybrid grad sync happens in the jitted step via GSPMD."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner = optimizer
        self._hcg = hcg
        if strategy is not None and mesh_axis_size("sharding") > 1:
            stage = strategy.sharding_configs.get("stage", 1)
            if stage >= 1:
                from ..sharding import shard_optimizer_states
                shard_optimizer_states(optimizer)
            if stage >= 2:
                from ..sharding import shard_gradients
                shard_gradients(optimizer)

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner"], name)

    def step(self):
        self._inner.step()

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self._inner.step()
        return None, None


def distributed_optimizer(optimizer, strategy=None):
    return HybridParallelOptimizer(optimizer, get_hcg(),
                                   strategy or _strategy)


# worker info -----------------------------------------------------------

def worker_num():
    return _env.get_world_size()


def worker_index():
    return _env.get_rank()


def is_first_worker():
    return _env.get_rank() == 0


def barrier_worker():
    from ..collective import barrier
    barrier()


class UtilBase:
    def all_reduce(self, input, mode="sum"):
        return input

    def barrier(self):
        barrier_worker()


util = UtilBase()


# expose as fleet.fleet for `from paddle.distributed.fleet import fleet`
import sys as _sys
fleet = _sys.modules[__name__]
utils = sequence_parallel_utils
