"""Hybrid-parallel topology (``python/paddle/distributed/fleet/base/
topology.py`` parity).

The reference builds cartesian NCCL process groups in axis order
(pp, dp, sharding, sep, mp). Here the same degrees define a
``jax.sharding.Mesh`` with those named axes — each "communication group"
is a mesh axis, and XLA emits the collectives over ICI (SURVEY.md §5.8).
Since r6 the mesh also carries an ``ep`` (expert-parallel) axis between
sep and mp: MoE layers shard their stacked expert dim over it and the
dropless dispatch runs its explicit all-to-alls inside a shard_map over
this axis (``distributed/moe.py::_dropless_ep``). Degree-1 axes are
inert, so non-MoE configs are unaffected.

On a single-controller jax runtime every process sees all devices, so the
"rank in group" notions are derived from the mesh coordinates of the
process's first local device — they exist for API parity and for
device-count bookkeeping in schedules.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax

from ..collective import Group
from .. import env as _env

_AXIS_ORDER = ("pp", "dp", "sharding", "sep", "ep", "mp")


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(hybrid_group_names
                                    or ["pipe", "data", "sharding", "sep",
                                        "expert", "model"])
        self._dims = list(dims or [1] * len(self._parallel_names))
        self._world_size = int(np.prod(self._dims))
        self._coords = np.arange(self._world_size).reshape(self._dims)

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        coord = [kwargs[name] for name in self._parallel_names]
        return int(self._coords[tuple(coord)])

    def get_coord(self, rank):
        idx = np.argwhere(self._coords == rank)[0]
        import collections
        Coord = collections.namedtuple("Coord", self._parallel_names)
        return Coord(*[int(i) for i in idx])

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        sl = [slice(None)] * len(self._dims)
        sl[axis] = index
        return sorted(int(r) for r in self._coords[tuple(sl)].reshape(-1))

    def get_comm_list(self, axis_name):
        axis = self._parallel_names.index(axis_name)
        moved = np.moveaxis(self._coords, axis, -1)
        return [list(map(int, row))
                for row in moved.reshape(-1, self._dims[axis])]


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology = None, strategy=None):
        if topology is None and strategy is not None:
            cfg = strategy.hybrid_configs
            dims = [cfg.get("pp_degree", 1), cfg.get("dp_degree", 1),
                    cfg.get("sharding_degree", 1),
                    cfg.get("sep_degree", 1), cfg.get("ep_degree", 1),
                    cfg.get("mp_degree", 1)]
            topology = CommunicateTopology(
                ["pipe", "data", "sharding", "sep", "expert", "model"],
                dims)
        self._topo = topology
        if "expert" not in self._topo._parallel_names:
            # accept a caller-built 5-axis topology (pre-r6 layout):
            # splice in a degree-1 expert axis so the mesh always
            # carries the full _AXIS_ORDER
            names = list(self._topo._parallel_names)
            dims = list(self._topo._dims)
            i = names.index("model") if "model" in names else len(names)
            names.insert(i, "expert")
            dims.insert(i, 1)
            self._topo = CommunicateTopology(names, dims)
        dims = self._topo._dims
        self._dp_degree = self._topo.get_dim("data")
        self._mp_degree = self._topo.get_dim("model")
        self._pp_degree = self._topo.get_dim("pipe")
        self._sharding_degree = self._topo.get_dim("sharding")
        self._sep_degree = self._topo.get_dim("sep")
        self._ep_degree = self._topo.get_dim("expert")

        n_needed = self._topo.world_size()
        devices = jax.devices()
        if n_needed > len(devices):
            raise ValueError(
                f"hybrid topology needs {n_needed} devices, "
                f"{len(devices)} available")
        mesh_devices = np.array(devices[:n_needed]).reshape(dims)
        self._mesh = jax.sharding.Mesh(mesh_devices, _AXIS_ORDER)
        _env.set_mesh(self._mesh)

        self.global_rank = _env.get_rank()
        coord = self._topo.get_coord(min(self.global_rank, n_needed - 1))
        self._dp_rank = coord.data
        self._mp_rank = coord.model
        self._pp_rank = coord.pipe
        self._sharding_rank = coord.sharding
        self._sep_rank = coord.sep
        self._ep_rank = coord.expert

        self._dp_group = Group(
            self._topo.get_axis_list("data", 0), axis_name="dp")
        self._mp_group = Group(
            self._topo.get_axis_list("model", 0), axis_name="mp")
        self._pp_group = Group(
            self._topo.get_axis_list("pipe", 0), axis_name="pp")
        self._sharding_group = Group(
            self._topo.get_axis_list("sharding", 0), axis_name="sharding")
        self._sep_group = Group(
            self._topo.get_axis_list("sep", 0), axis_name="sep")
        self._ep_group = Group(
            self._topo.get_axis_list("expert", 0), axis_name="ep")

    # mesh access (TPU-native extension point)
    @property
    def mesh(self) -> jax.sharding.Mesh:
        return self._mesh

    def topology(self):
        return self._topo

    # paddle API parity -------------------------------------------------
    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._sharding_degree > 1:
            return "sharding_parallel"
        if self._mp_degree > 1:
            return "tensor_parallel"
        return "data_parallel"

    def get_global_rank(self):
        return self.global_rank

    def get_data_parallel_rank(self):
        return self._dp_rank

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    def get_model_parallel_rank(self):
        return self._mp_rank

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    def get_stage_id(self):
        return self._pp_rank

    def get_pipe_parallel_rank(self):
        return self._pp_rank

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_rank(self):
        return self._sharding_rank

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sep_parallel_rank(self):
        return self._sep_rank

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_expert_parallel_rank(self):
        return self._ep_rank

    def get_expert_parallel_world_size(self):
        return self._ep_degree

    def get_expert_parallel_group(self):
        return self._ep_group

    def get_p2p_groups(self):
        return None

    def is_first_stage(self):
        return self._pp_rank == 0

    def is_last_stage(self):
        return self._pp_rank == self._pp_degree - 1


_hcg: Optional[HybridCommunicateGroup] = None


def set_hcg(hcg):
    global _hcg
    _hcg = hcg


def get_hcg() -> Optional[HybridCommunicateGroup]:
    return _hcg
