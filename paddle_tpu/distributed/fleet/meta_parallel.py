"""Meta-parallel wrappers (``python/paddle/distributed/fleet/
meta_parallel/`` parity): PipelineLayer/LayerDesc, PipelineParallel,
TensorParallel, ShardingParallel.

PipelineLayer partitions a LayerDesc list into stages. When the stages
are structurally homogeneous (the transformer case) the forward runs
through the shard_map pipeline engine (``distributed/pipeline.py``) over
the ``pp`` mesh axis; otherwise it falls back to sequential execution
whose params are still mesh-sharded by their annotations — numerically
identical, just without pp overlap.
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ...nn.layer.layers import Layer
from ..shard_utils import current_mesh, mesh_axis_size
from .topology import get_hcg

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "PipelineParallel", "TensorParallel", "ShardingParallel",
           "get_rng_state_tracker"]


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *inputs, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        hcg = get_hcg()
        self._num_stages = num_stages or (
            hcg.get_pipe_parallel_world_size() if hcg else 1)
        self._recompute_interval = recompute_interval

        self._descs = list(layers)
        built = []
        self._shared = {}
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(("shared", d.layer_name, d.forward_func))
                    continue
                layer = d.build_layer()
                self._shared[d.layer_name] = layer
                built.append(("layer", layer, None))
            elif isinstance(d, LayerDesc):
                built.append(("layer", d.build_layer(), None))
            elif callable(d) and not isinstance(d, Layer):
                built.append(("fn", d, None))
            else:
                built.append(("layer", d, None))
        self._items = built
        for i, (kind, obj, _) in enumerate(built):
            if kind == "layer":
                self.add_sublayer(str(i), obj)
        self._segments = self._segment(seg_method)

    def _segment(self, seg_method):
        n = len(self._items)
        k = self._num_stages
        if isinstance(seg_method, str) and seg_method.startswith("layer:"):
            pat = seg_method.split(":", 1)[1]
            marks = [i for i, (kind, obj, _) in enumerate(self._items)
                     if kind == "layer" and pat in type(obj).__name__]
            if len(marks) >= k:
                per = len(marks) // k
                bounds = [0] + [marks[per * i] for i in range(1, k)] + [n]
                return [list(range(bounds[i], bounds[i + 1]))
                        for i in range(k)]
        base, rem = divmod(n, k)
        out, idx = [], 0
        for i in range(k):
            size = base + (1 if i < rem else 0)
            out.append(list(range(idx, idx + size)))
            idx += size
        return out

    def get_stage_from_index(self, index):
        for stage, seg in enumerate(self._segments):
            if index in seg:
                return stage
        return self._num_stages - 1

    def _engine_route(self):
        """(pre, body, post) when a homogeneous run of layers can ride the
        shard_map pipeline engine; None → sequential fallback. The
        heterogeneous first/last-stage work (embedding, head, loss prep)
        stays outside the ring — the scan-pipeline equivalent of the
        reference's first/last-stage special-casing."""
        if getattr(self, "_route_cache", "unset") != "unset":
            return self._route_cache
        self._route_cache = None
        k = self._num_stages
        if k <= 1 or mesh_axis_size("pp") < k:
            return None
        from ...jit import _LayerBinder

        def sig(item):
            kind, obj, _ = item
            if kind != "layer":
                return None
            shapes = tuple((n, tuple(p.shape), str(p.dtype))
                           for n, p in _LayerBinder(obj).param_items)
            return (type(obj).__name__, shapes)

        sigs = [sig(it) for it in self._items]
        best = (0, 0)  # (length, start)
        i = 0
        n = len(sigs)
        while i < n:
            if sigs[i] is None:
                i += 1
                continue
            j = i
            while j < n and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[0]:
                best = (j - i, i)
            i = j
        length, start = best
        usable = (length // k) * k
        if usable < k or usable < 2:
            return None
        # align the run's tail with the segment boundary: keep the last
        # `usable` homogeneous layers in the body
        start = start + (length - usable)
        self._route_cache = (self._items[:start],
                             [obj for _, obj, _ in
                              self._items[start:start + usable]],
                             self._items[start + usable:])
        return self._route_cache

    def _run_items(self, items, x):
        for kind, obj, ffn in items:
            if kind == "layer":
                x = obj(x)
            elif kind == "shared":
                layer = self._shared[obj]
                x = ffn(layer, x) if ffn else layer(x)
            else:
                x = obj(x)
        return x

    def _pipe_body(self, body, x):
        from ...jit import _LayerBinder
        from ..pipeline import pipeline_apply
        from ..shard_utils import current_mesh
        mesh = current_mesh()
        pp = self._num_stages
        lps = len(body) // pp
        binder = _LayerBinder(body[0])
        n_p = len(binder.param_items)
        param_tensors = [p for lay in body
                         for _, p in _LayerBinder(lay).param_items]
        n_micro = getattr(self, "_num_micro", None) or pp
        recompute = self._recompute_interval and self.training

        def one_layer(params_local, h, i):
            arrs = [p[i] for p in params_local]
            out, _ = binder.call(arrs, [], (_wrap_out(h),), {})
            return as_jax(out)

        def stage_fn(params_local, h):
            f = one_layer
            if recompute:
                f = jax.checkpoint(one_layer, static_argnums=(2,))
            for i in range(lps):
                h = f(params_local, h, i)
            return h

        def run_pipe(h_a, *flat):
            per = [flat[kk * n_p:(kk + 1) * n_p]
                   for kk in range(len(body))]
            stacked = [
                jnp.stack([jnp.stack([per[s * lps + i][j]
                                      for i in range(lps)])
                           for s in range(pp)])
                for j in range(n_p)
            ]
            b = h_a.shape[0]
            nm = n_micro
            while b % nm != 0:
                nm -= 1
            mbs = h_a.reshape((nm, b // nm) + h_a.shape[1:])
            out = pipeline_apply(stage_fn, stacked, mbs, mesh=mesh)
            return out.reshape(h_a.shape)

        return apply_jax("pipeline_body", run_pipe, x, *param_tensors)

    def forward(self, x):
        route = self._engine_route()
        if route is None:
            return self._run_items(self._items, x)
        pre, body, post = route
        x = self._run_items(pre, x)
        x = self._pipe_body(body, x)
        return self._run_items(post, x)


class PipelineParallel(Layer):
    """``PipelineParallel.train_batch`` parity. Microbatching + grad
    accumulation; the per-microbatch step is the (optionally jitted)
    full model forward/backward — stage overlap comes from the shard_map
    engine when the wrapped model uses it, and from XLA's async scheduling
    otherwise."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg or get_hcg()
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None
               else {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", 1)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None,
                    scaler=None):
        inputs, labels = data
        if not isinstance(inputs, Tensor):
            inputs = Tensor(inputs)
        if not isinstance(labels, Tensor):
            labels = Tensor(labels)
        n_micro = self.accumulate_steps
        loss_fn = getattr(self._layers, "_loss_fn", None)
        if isinstance(self._layers, PipelineLayer) and \
                self._layers._engine_route() is not None:
            # engine path: all microbatches ride the scan pipeline in ONE
            # call — grad accumulation is the sum inside the scan, so the
            # python-loop schedule below would only add bubbles.
            self._layers._num_micro = n_micro
            out = self._layers(inputs)
            loss = loss_fn(out, labels) if loss_fn is not None else out
            if scaler is not None:
                scaler.scale(loss).backward()
                scaler.step(optimizer)
                scaler.update()
            else:
                loss.backward()
                optimizer.step()
            optimizer.clear_grad()
            if lr_scheduler is not None:
                lr_scheduler.step()
            return _wrap_out(as_jax(loss))
        bsz = inputs.shape[0]
        mb = max(bsz // n_micro, 1)
        total = 0.0
        for i in range(0, bsz, mb):
            x = inputs[i:i + mb]
            y = labels[i:i + mb]
            # weight by the actual slice size so a ragged tail microbatch
            # contributes proportionally, not double
            w = x.shape[0] / bsz
            out = self._layers(x)
            loss = loss_fn(out, y) if loss_fn is not None else out
            scaled = loss * w
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total += float(loss) * w
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return _wrap_out(jnp.asarray(total))

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(Tensor(inputs) if not isinstance(
            inputs, Tensor) else inputs)
        loss_fn = getattr(self._layers, "_loss_fn", None)
        if compute_loss and loss_fn is not None:
            return loss_fn(out, labels if isinstance(labels, Tensor)
                           else Tensor(labels))
        return out

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


class ShardingParallel(TensorParallel):
    pass


class _RNGStateTracker:
    """Model-parallel RNG tracker (``get_rng_state_tracker`` parity,
    reference ``fleet/meta_parallel/parallel_layers/random.py``).

    Two named streams matter: ``global_seed`` (identical on every mp
    rank — e.g. attention dropout over replicated activations) and
    ``local_seed`` (distinct per mp rank — dropout over TP-sharded
    activations must decorrelate). Entering ``rng_state(name)`` swaps the
    framework's functional PRNG key for one derived as
    ``fold_in(base_seed, stream)`` and, for local streams, additionally
    ``fold_in(mp_rank)`` — so TP dropout is decorrelated where it must be
    and reproducible everywhere."""

    LOCAL_STREAMS = ("local_seed", "model_parallel_rng")

    def __init__(self):
        self._seeds = {}

    def add(self, name, seed):
        if name in self._seeds and self._seeds[name] != seed:
            raise ValueError(f"seed for state {name!r} already set")
        self._seeds[name] = int(seed)

    def get_states_tracker(self):
        return dict(self._seeds)

    def rng_state(self, name="global_seed"):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            from ...framework import random as frandom
            hcg = get_hcg()
            seed = self._seeds.get(name, 0)
            key = jax.random.PRNGKey(seed) if seed else frandom.get_key()
            # crc32, not hash(): str hashes are salted per process, which
            # would desynchronize "identical on every rank" streams
            import zlib
            key = jax.random.fold_in(key, zlib.crc32(name.encode()))
            if name in self.LOCAL_STREAMS and hcg is not None:
                key = jax.random.fold_in(
                    key, hcg.get_model_parallel_rank())
            prev = frandom.swap_key(key)
            try:
                yield
            finally:
                frandom.swap_key(prev)
        return ctx()


_tracker = _RNGStateTracker()


def get_rng_state_tracker():
    return _tracker
