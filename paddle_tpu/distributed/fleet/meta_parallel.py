"""Meta-parallel wrappers (``python/paddle/distributed/fleet/
meta_parallel/`` parity): PipelineLayer/LayerDesc, PipelineParallel,
TensorParallel, ShardingParallel.

PipelineLayer partitions a LayerDesc list into stages. When the stages
are structurally homogeneous (the transformer case) the forward runs
through the shard_map pipeline engine (``distributed/pipeline.py``) over
the ``pp`` mesh axis; otherwise it falls back to sequential execution
whose params are still mesh-sharded by their annotations — numerically
identical, just without pp overlap.
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ...nn.layer.layers import Layer
from ..shard_utils import current_mesh, mesh_axis_size
from .topology import get_hcg

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "PipelineParallel", "TensorParallel", "ShardingParallel",
           "get_rng_state_tracker"]


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *inputs, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        hcg = get_hcg()
        self._num_stages = num_stages or (
            hcg.get_pipe_parallel_world_size() if hcg else 1)
        self._recompute_interval = recompute_interval

        self._descs = list(layers)
        built = []
        self._shared = {}
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(("shared", d.layer_name, d.forward_func))
                    continue
                layer = d.build_layer()
                self._shared[d.layer_name] = layer
                built.append(("layer", layer, None))
            elif isinstance(d, LayerDesc):
                built.append(("layer", d.build_layer(), None))
            elif callable(d) and not isinstance(d, Layer):
                built.append(("fn", d, None))
            else:
                built.append(("layer", d, None))
        self._items = built
        for i, (kind, obj, _) in enumerate(built):
            if kind == "layer":
                self.add_sublayer(str(i), obj)
        self._segments = self._segment(seg_method)

    def _segment(self, seg_method):
        n = len(self._items)
        k = self._num_stages
        if isinstance(seg_method, str) and seg_method.startswith("layer:"):
            pat = seg_method.split(":", 1)[1]
            marks = [i for i, (kind, obj, _) in enumerate(self._items)
                     if kind == "layer" and pat in type(obj).__name__]
            if len(marks) >= k:
                per = len(marks) // k
                bounds = [0] + [marks[per * i] for i in range(1, k)] + [n]
                return [list(range(bounds[i], bounds[i + 1]))
                        for i in range(k)]
        base, rem = divmod(n, k)
        out, idx = [], 0
        for i in range(k):
            size = base + (1 if i < rem else 0)
            out.append(list(range(idx, idx + size)))
            idx += size
        return out

    def get_stage_from_index(self, index):
        for stage, seg in enumerate(self._segments):
            if index in seg:
                return stage
        return self._num_stages - 1

    def forward(self, x):
        for kind, obj, ffn in self._items:
            if kind == "layer":
                x = obj(x)
            elif kind == "shared":
                layer = self._shared[obj]
                x = ffn(layer, x) if ffn else layer(x)
            else:
                x = obj(x)
        return x


class PipelineParallel(Layer):
    """``PipelineParallel.train_batch`` parity. Microbatching + grad
    accumulation; the per-microbatch step is the (optionally jitted)
    full model forward/backward — stage overlap comes from the shard_map
    engine when the wrapped model uses it, and from XLA's async scheduling
    otherwise."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg or get_hcg()
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None
               else {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", 1)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None,
                    scaler=None):
        inputs, labels = data
        if not isinstance(inputs, Tensor):
            inputs = Tensor(inputs)
        if not isinstance(labels, Tensor):
            labels = Tensor(labels)
        n_micro = self.accumulate_steps
        bsz = inputs.shape[0]
        mb = max(bsz // n_micro, 1)
        total = 0.0
        loss_fn = getattr(self._layers, "_loss_fn", None)
        for i in range(0, bsz, mb):
            x = inputs[i:i + mb]
            y = labels[i:i + mb]
            # weight by the actual slice size so a ragged tail microbatch
            # contributes proportionally, not double
            w = x.shape[0] / bsz
            out = self._layers(x)
            loss = loss_fn(out, y) if loss_fn is not None else out
            scaled = loss * w
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total += float(loss) * w
        if scaler is not None:
            scaler.step(optimizer)
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return _wrap_out(jnp.asarray(total))

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(Tensor(inputs) if not isinstance(
            inputs, Tensor) else inputs)
        loss_fn = getattr(self._layers, "_loss_fn", None)
        if compute_loss and loss_fn is not None:
            return loss_fn(out, labels if isinstance(labels, Tensor)
                           else Tensor(labels))
        return out

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


class ShardingParallel(TensorParallel):
    pass


class _RNGStateTracker:
    """model-parallel RNG tracker (``get_rng_state_tracker`` parity) —
    dropout seeds differ across mp ranks via fold_in."""

    def __init__(self):
        self._states = {}

    def add(self, name, seed):
        self._states[name] = seed

    def rng_state(self, name="global_seed"):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            yield
        return ctx()


_tracker = _RNGStateTracker()


def get_rng_state_tracker():
    return _tracker
