"""Meta-parallel wrappers (``python/paddle/distributed/fleet/
meta_parallel/`` parity): PipelineLayer/LayerDesc, PipelineParallel,
TensorParallel, ShardingParallel.

PipelineLayer partitions a LayerDesc list into stages. When the stages
are structurally homogeneous (the transformer case) the forward runs
through the shard_map pipeline engine (``distributed/pipeline.py``) over
the ``pp`` mesh axis; otherwise it falls back to sequential execution
whose params are still mesh-sharded by their annotations — numerically
identical, just without pp overlap.
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply_jax, as_jax, _wrap_out
from ...nn.layer.layers import Layer
from ..shard_utils import current_mesh, mesh_axis_size
from .topology import get_hcg

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "PipelineParallel", "TensorParallel", "ShardingParallel",
           "get_rng_state_tracker"]


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *inputs, forward_func=None,
                 shared_weight_attr="weight", **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._num_virtual_stages = int(num_virtual_pipeline_stages or 1)
        self._topo = topology
        hcg = get_hcg()
        self._num_stages = num_stages or (
            hcg.get_pipe_parallel_world_size() if hcg else 1)
        self._recompute_interval = recompute_interval

        self._descs = list(layers)
        built = []
        self._shared = {}
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(("shared", d.layer_name, d.forward_func))
                    continue
                layer = d.build_layer()
                self._shared[d.layer_name] = layer
                built.append(("layer", layer, None))
            elif isinstance(d, LayerDesc):
                built.append(("layer", d.build_layer(), None))
            elif callable(d) and not isinstance(d, Layer):
                built.append(("fn", d, None))
            else:
                built.append(("layer", d, None))
        self._items = built
        for i, (kind, obj, _) in enumerate(built):
            if kind == "layer":
                self.add_sublayer(str(i), obj)
        self._segments = self._segment(seg_method)

    def _segment(self, seg_method):
        n = len(self._items)
        k = self._num_stages
        if isinstance(seg_method, str) and seg_method.startswith("layer:"):
            pat = seg_method.split(":", 1)[1]
            marks = [i for i, (kind, obj, _) in enumerate(self._items)
                     if kind == "layer" and pat in type(obj).__name__]
            if len(marks) >= k:
                per = len(marks) // k
                bounds = [0] + [marks[per * i] for i in range(1, k)] + [n]
                return [list(range(bounds[i], bounds[i + 1]))
                        for i in range(k)]
        base, rem = divmod(n, k)
        out, idx = [], 0
        for i in range(k):
            size = base + (1 if i < rem else 0)
            out.append(list(range(idx, idx + size)))
            idx += size
        return out

    def get_stage_from_index(self, index):
        for stage, seg in enumerate(self._segments):
            if index in seg:
                return stage
        return self._num_stages - 1

    def _engine_route(self):
        """(pre, body, post) when a homogeneous run of layers can ride the
        shard_map pipeline engine; None → sequential fallback. The
        heterogeneous first/last-stage work (embedding, head, loss prep)
        rides INSIDE the ring as stage-0/last-stage extra compute when the
        pre/post items are plain layers (reference: first/last-stage
        special-casing in ``pipeline_parallel.py``); the route decision is
        logged — never silent."""
        if getattr(self, "_route_cache", "unset") != "unset":
            return self._route_cache
        from ...framework.log import vlog
        self._route_cache = None
        k = self._num_stages
        if k <= 1 or mesh_axis_size("pp") < k:
            vlog(1, "PipelineLayer: sequential route (pp mesh axis %d < "
                 "num_stages %d)", mesh_axis_size("pp"), k)
            return None
        from ...jit import _LayerBinder

        def sig(item):
            kind, obj, _ = item
            if kind != "layer":
                return None
            shapes = tuple((n, tuple(p.shape), str(p.dtype))
                           for n, p in _LayerBinder(obj).param_items)
            return (type(obj).__name__, shapes)

        sigs = [sig(it) for it in self._items]
        best = (0, 0)  # (length, start)
        i = 0
        n = len(sigs)
        while i < n:
            if sigs[i] is None:
                i += 1
                continue
            j = i
            while j < n and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[0]:
                best = (j - i, i)
            i = j
        length, start = best
        usable = (length // k) * k
        if usable < k or usable < 2:
            from ...framework.log import logger
            logger.warning(
                "PipelineLayer: no homogeneous run of >= %d layers "
                "(longest run %d) — pp=%d gets NO pipeline overlap; "
                "running stages sequentially (params stay mesh-sharded)",
                k, length, k)
            return None
        # align the run's tail with the segment boundary: keep the last
        # `usable` homogeneous layers in the body
        start = start + (length - usable)
        self._route_cache = (self._items[:start],
                             [obj for _, obj, _ in
                              self._items[start:start + usable]],
                             self._items[start + usable:])
        vlog(1, "PipelineLayer: engine route — %d pre item(s) -> stage-0 "
             "work, %d-layer homogeneous body over pp=%d, %d post "
             "item(s) -> last-stage work", start, usable, k,
             n - start - usable)
        return self._route_cache

    def _run_items(self, items, x):
        for kind, obj, ffn in items:
            if kind == "layer":
                x = obj(x)
            elif kind == "shared":
                layer = self._shared[obj]
                x = ffn(layer, x) if ffn else layer(x)
            else:
                x = obj(x)
        return x

    @staticmethod
    def _liftable(items):
        """pre/post items that can ride inside the ring as first/last
        stage work: plain buffer-less layers."""
        from ...jit import _LayerBinder
        return bool(items) and all(
            kind == "layer" and not _LayerBinder(obj).buffer_items
            for kind, obj, _ in items)

    def _stage_machinery(self, pre, body, post, recompute=False,
                         n_parts=None):
        """Shared stage plumbing for the pipeline engines (GPipe scan,
        1F1B, interleaved): binders, param tensors, the per-part chain
        closures, and part-major stacking. ``n_parts`` defaults to pp;
        interleaved engines pass pp * v."""
        from ...jit import _LayerBinder
        pp = self._num_stages
        n_parts = n_parts or pp
        lps = len(body) // n_parts
        binder = _LayerBinder(body[0])
        n_p = len(binder.param_items)

        m = {
            "pp": pp, "lps": lps, "n_p": n_p,
            "body_tensors": [p for lay in body
                             for _, p in _LayerBinder(lay).param_items],
            "pre_binders": [_LayerBinder(obj) for _, obj, _ in pre],
            "post_binders": [_LayerBinder(obj) for _, obj, _ in post],
        }
        m["pre_sizes"] = [len(b.param_items) for b in m["pre_binders"]]
        m["post_sizes"] = [len(b.param_items) for b in m["post_binders"]]
        m["pre_tensors"] = [p for b in m["pre_binders"]
                            for _, p in b.param_items]
        m["post_tensors"] = [p for b in m["post_binders"]
                             for _, p in b.param_items]

        def chain(binders, sizes, flat, h):
            i = 0
            for b, s in zip(binders, sizes):
                arrs = list(flat[i:i + s])
                i += s
                out, _ = b.call(arrs, [], (_wrap_out(h),), {})
                h = as_jax(out)
            return h

        def one_layer(params_local, h, i):
            arrs = [p[i] for p in params_local]
            out, _ = binder.call(arrs, [], (_wrap_out(h),), {})
            return as_jax(out)

        def stage_fn(params_local, h):
            f = one_layer
            if recompute:
                f = jax.checkpoint(one_layer, static_argnums=(2,))
            for i in range(lps):
                h = f(params_local, h, i)
            return h

        def stack_body(body_flat):
            # part-major: part index p covers layers [p*lps, (p+1)*lps)
            per = [body_flat[kk * n_p:(kk + 1) * n_p]
                   for kk in range(len(body))]
            return [
                jnp.stack([jnp.stack([per[pt * lps + i][j]
                                      for i in range(lps)])
                           for pt in range(n_parts)])
                for j in range(n_p)
            ]

        m["chain"] = chain
        m["stage_fn"] = stage_fn
        m["stack_body"] = stack_body
        m["first_fn"] = (lambda fp, feed, *e:
                         chain(m["pre_binders"], m["pre_sizes"], fp,
                               feed)) if pre else None
        m["post_chain"] = (lambda lp, y:
                           chain(m["post_binders"], m["post_sizes"],
                                 lp, y)) if post else None
        return m

    def _adjust_nm(self, b, n_micro):
        nm = min(n_micro, b)
        while b % nm != 0:
            nm -= 1
        if nm != n_micro and \
                getattr(self, "_nm_logged", None) != (n_micro, nm):
            from ...framework.log import logger
            logger.warning(
                "PipelineLayer: batch %d not divisible by %d "
                "microbatches — using %d microbatches instead",
                b, n_micro, nm)
            self._nm_logged = (n_micro, nm)
        return nm

    def _pipe_body(self, pre, body, post, x):
        """Pipelined run: homogeneous body over the pp ring; lifted pre
        items execute per-microbatch on stage 0 (first_fn) and post items
        on the last stage (last_fn), so embedding/head work overlaps the
        pipeline instead of running replicated outside it."""
        from ..pipeline import pipeline_apply
        from ..shard_utils import current_mesh
        mesh = current_mesh()
        mach = self._stage_machinery(
            pre, body, post,
            recompute=bool(self._recompute_interval and self.training))
        n_micro = getattr(self, "_num_micro", None) or mach["pp"]
        n_body = len(mach["body_tensors"])
        n_pre = len(mach["pre_tensors"])

        def run_pipe(x_a, *flat):
            pre_flat = list(flat[n_body:n_body + n_pre])
            post_flat = list(flat[n_body + n_pre:])
            stacked = mach["stack_body"](flat[:n_body])
            b = x_a.shape[0]
            nm = self._adjust_nm(b, n_micro)
            mbs = x_a.reshape((nm, b // nm) + x_a.shape[1:])
            last_fn = (lambda lp, y, lf, *e: mach["post_chain"](lp, y)) \
                if post else None
            out = pipeline_apply(
                mach["stage_fn"], stacked, mbs, mesh=mesh,
                first_fn=mach["first_fn"], first_params=pre_flat,
                last_fn=last_fn, last_params=post_flat)
            return out.reshape((b,) + out.shape[2:])

        return apply_jax("pipeline_body", run_pipe, x,
                         *mach["body_tensors"], *mach["pre_tensors"],
                         *mach["post_tensors"])

    def forward(self, x):
        route = self._engine_route()
        if route is None:
            return self._run_items(self._items, x)
        pre, body, post = route
        lift_pre = self._liftable(pre)
        lift_post = self._liftable(post)
        if pre and not lift_pre:
            x = self._run_items(pre, x)
        x = self._pipe_body(pre if lift_pre else [], body,
                            post if lift_post else [], x)
        if post and not lift_post:
            x = self._run_items(post, x)
        return x

    def train_batch_1f1b(self, x, labels, n_micro, loss_scale=None):
        """One full 1F1B train pass (O(pp) activation memory): computes
        the mean loss and ACCUMULATES parameter gradients directly
        (``p.grad``), bypassing the tape — the schedule interleaves
        forward and backward inside one scan, which autograd-through-
        forward cannot express. Requires an engine route whose pre/post
        items are liftable and a ``loss_fn``."""
        from ...jit import _LayerBinder
        from ..pipeline_1f1b import pipeline_1f1b_grads
        from ..shard_utils import current_mesh
        route = self._engine_route()
        if route is None:
            raise RuntimeError("1F1B needs the pipeline engine route "
                               "(homogeneous stage body over a pp mesh)")
        if self._loss_fn is None:
            raise RuntimeError("1F1B training needs loss_fn")
        pre, body, post = route
        if (pre and not self._liftable(pre)) or \
                (post and not self._liftable(post)):
            raise RuntimeError("1F1B needs liftable (plain-layer) "
                               "pre/post stage items")
        mesh = current_mesh()
        # 1F1B recomputes stage interiors on every B slot by design
        # (activation remat is built into the schedule), so the
        # recompute_interval knob is moot here
        pp = self._num_stages
        v = max(self._num_virtual_stages, 1)
        x_a = as_jax(x)
        b = x_a.shape[0]
        nm = self._adjust_nm(b, n_micro)
        if v > 1 and (len(body) % (pp * v) != 0 or nm % pp != 0):
            if getattr(self, "_v_logged", None) != (len(body), nm, v):
                from ...framework.log import logger
                logger.warning(
                    "PipelineLayer: interleave needs body %% (pp*v) == 0 "
                    "and n_micro %% pp == 0 (body=%d, pp*v=%d, "
                    "n_micro=%d) — ignoring "
                    "num_virtual_pipeline_stages=%d",
                    len(body), pp * v, nm, v)
                self._v_logged = (len(body), nm, v)
            v = 1
        mach = self._stage_machinery(pre, body, post, recompute=False,
                                     n_parts=pp * v)
        lps = mach["lps"]
        loss_fn = self._loss_fn

        def last_fn(lp, y, lf):
            out = mach["post_chain"](lp, y) if post else y
            return as_jax(loss_fn(_wrap_out(out), _wrap_out(lf)))

        pre_arrs = [as_jax(p) for p in mach["pre_tensors"]]
        post_arrs = [as_jax(p) for p in mach["post_tensors"]]
        body_arrs = [as_jax(p) for p in mach["body_tensors"]]

        y_a = as_jax(labels)
        feeds = x_a.reshape((nm, b // nm) + x_a.shape[1:])
        lfeeds = y_a.reshape((nm, b // nm) + y_a.shape[1:])

        # one jitted program per (shapes, nm): the whole 1F1B timetable
        # — stacking, scan, grads — compiles once and is re-dispatched
        # per step (re-tracing the scan per step would dominate)
        key = (feeds.shape, str(feeds.dtype), lfeeds.shape,
               str(lfeeds.dtype), nm, v, lps)
        cache = self.__dict__.setdefault("_1f1b_jit_cache", {})
        runner = cache.get(key)
        if runner is None:
            def runner_fn(body_a, pre_a, post_a, feeds_a, lfeeds_a,
                          scale_a):
                if v > 1:
                    from ..pipeline_1f1b import pipeline_interleaved_grads
                    # engine layout [pp, v, lps, ...]: model part
                    # c*pp + s lives at (stage s, chunk c)
                    parts = mach["stack_body"](body_a)  # [pp*v, lps,...]
                    stacked = [
                        jnp.stack([jnp.stack([pj[c * pp + s]
                                              for c in range(v)])
                                   for s in range(pp)])
                        for pj in parts
                    ]
                    return pipeline_interleaved_grads(
                        mach["stage_fn"], stacked, feeds_a, last_fn,
                        v, first_fn=mach["first_fn"], first_params=pre_a,
                        last_params=post_a, last_feeds=lfeeds_a,
                        mesh=mesh, loss_scale=scale_a)
                stacked = mach["stack_body"](body_a)
                return pipeline_1f1b_grads(
                    mach["stage_fn"], stacked, feeds_a, last_fn,
                    first_fn=mach["first_fn"], first_params=pre_a,
                    last_params=post_a, last_feeds=lfeeds_a, mesh=mesh,
                    loss_scale=scale_a)
            runner = jax.jit(runner_fn)
            cache[key] = runner
        # the scale rides as a traced argument: dynamic loss scaling
        # changes it per step without recompiling the timetable
        scale_a = jnp.float32(1.0 if loss_scale is None else loss_scale)
        loss, (g_stacked, g_first, g_last) = runner(
            body_arrs, pre_arrs, post_arrs, feeds, lfeeds, scale_a)

        def accum(p, g):
            g = jnp.asarray(g)
            p._grad = _wrap_out(g if p.grad is None
                                else as_jax(p.grad) + g)

        for li, lay in enumerate(body):
            part, i = divmod(li, lps)
            if v > 1:
                c, s = divmod(part, pp)
                for j, (_, p) in enumerate(
                        _LayerBinder(lay).param_items):
                    accum(p, g_stacked[j][s, c, i])
            else:
                for j, (_, p) in enumerate(
                        _LayerBinder(lay).param_items):
                    accum(p, g_stacked[j][part, i])
        for p, g in zip(mach["pre_tensors"], g_first):
            accum(p, g)
        for p, g in zip(mach["post_tensors"], g_last):
            accum(p, g)
        return _wrap_out(loss)


class PipelineParallel(Layer):
    """``PipelineParallel.train_batch`` parity. Microbatching + grad
    accumulation; the per-microbatch step is the (optionally jitted)
    full model forward/backward — stage overlap comes from the shard_map
    engine when the wrapped model uses it, and from XLA's async scheduling
    otherwise."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg or get_hcg()
        self._strategy = strategy
        cfg = (strategy.pipeline_configs if strategy is not None
               else {"accumulate_steps": 1, "micro_batch_size": 1})
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", 1)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None,
                    scaler=None):
        inputs, labels = data
        if not isinstance(inputs, Tensor):
            inputs = Tensor(inputs)
        if not isinstance(labels, Tensor):
            labels = Tensor(labels)
        n_micro = self.accumulate_steps
        loss_fn = getattr(self._layers, "_loss_fn", None)
        cfg = self._strategy.pipeline_configs if self._strategy else {}
        if str(cfg.get("schedule", "")).upper() == "1F1B" and \
                isinstance(self._layers, PipelineLayer) and \
                self._layers._engine_route() is not None:
            # true 1F1B: fwd/bwd interleaved in one scan, O(pp) live
            # activations; grads are produced directly by the engine.
            # GradScaler: the scale seeds the backward chain INSIDE the
            # engine (last-stage loss seed), so boundary grads ride the
            # ring scaled — fp16-underflow protection identical to the
            # reference's scaled-loss backward
            scale = getattr(scaler, "_scale", None) if scaler is not None \
                and scaler.is_enable() else None
            loss = self._layers.train_batch_1f1b(inputs, labels, n_micro,
                                                 loss_scale=scale)
            if scaler is not None:
                # unscale_ divides the accumulated grads by the scale
                # and finite-checks them: a NaN/Inf microbatch SKIPS the
                # step and update() adjusts the scale, same as the
                # non-1F1B path
                scaler.unscale_(optimizer)
                scaler.step(optimizer)
                scaler.update()
            else:
                optimizer.step()
            optimizer.clear_grad()
            if lr_scheduler is not None:
                lr_scheduler.step()
            return loss
        if isinstance(self._layers, PipelineLayer) and \
                self._layers._engine_route() is not None:
            # engine path: all microbatches ride the scan pipeline in ONE
            # call — grad accumulation is the sum inside the scan, so the
            # python-loop schedule below would only add bubbles.
            self._layers._num_micro = n_micro
            out = self._layers(inputs)
            loss = loss_fn(out, labels) if loss_fn is not None else out
            if scaler is not None:
                scaler.scale(loss).backward()
                scaler.step(optimizer)
                scaler.update()
            else:
                loss.backward()
                optimizer.step()
            optimizer.clear_grad()
            if lr_scheduler is not None:
                lr_scheduler.step()
            return _wrap_out(as_jax(loss))
        bsz = inputs.shape[0]
        mb = max(bsz // n_micro, 1)
        total = 0.0
        for i in range(0, bsz, mb):
            x = inputs[i:i + mb]
            y = labels[i:i + mb]
            # weight by the actual slice size so a ragged tail microbatch
            # contributes proportionally, not double
            w = x.shape[0] / bsz
            out = self._layers(x)
            loss = loss_fn(out, y) if loss_fn is not None else out
            scaled = loss * w
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total += float(loss) * w
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return _wrap_out(jnp.asarray(total))

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(Tensor(inputs) if not isinstance(
            inputs, Tensor) else inputs)
        loss_fn = getattr(self._layers, "_loss_fn", None)
        if compute_loss and loss_fn is not None:
            return loss_fn(out, labels if isinstance(labels, Tensor)
                           else Tensor(labels))
        return out

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


class TensorParallel(Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)


class ShardingParallel(TensorParallel):
    pass


class _RNGStateTracker:
    """Model-parallel RNG tracker (``get_rng_state_tracker`` parity,
    reference ``fleet/meta_parallel/parallel_layers/random.py``).

    Two named streams matter: ``global_seed`` (identical on every mp
    rank — e.g. attention dropout over replicated activations) and
    ``local_seed`` (distinct per mp rank — dropout over TP-sharded
    activations must decorrelate). Entering ``rng_state(name)`` swaps the
    framework's functional PRNG key for one derived as
    ``fold_in(base_seed, stream)`` and, for local streams, additionally
    ``fold_in(mp_rank)`` — so TP dropout is decorrelated where it must be
    and reproducible everywhere."""

    LOCAL_STREAMS = ("local_seed", "model_parallel_rng")

    def __init__(self):
        self._seeds = {}

    def add(self, name, seed):
        if name in self._seeds and self._seeds[name] != seed:
            raise ValueError(f"seed for state {name!r} already set")
        self._seeds[name] = int(seed)

    def get_states_tracker(self):
        return dict(self._seeds)

    def rng_state(self, name="global_seed"):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            from ...framework import random as frandom
            hcg = get_hcg()
            seed = self._seeds.get(name, 0)
            key = jax.random.PRNGKey(seed) if seed else frandom.get_key()
            # crc32, not hash(): str hashes are salted per process, which
            # would desynchronize "identical on every rank" streams
            import zlib
            key = jax.random.fold_in(key, zlib.crc32(name.encode()))
            if name in self.LOCAL_STREAMS and hcg is not None:
                key = jax.random.fold_in(
                    key, hcg.get_model_parallel_rank())
            prev = frandom.swap_key(key)
            try:
                yield
            finally:
                frandom.swap_key(prev)
        return ctx()


_tracker = _RNGStateTracker()


def get_rng_state_tracker():
    return _tracker
