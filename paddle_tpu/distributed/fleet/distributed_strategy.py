"""``DistributedStrategy`` (``python/paddle/distributed/fleet/base/
distributed_strategy.py`` parity — protobuf replaced by dataclass state)."""
from __future__ import annotations

import copy
from typing import Any, Dict


class DistributedStrategy:
    def __init__(self):
        self.amp = False
        self.amp_configs: Dict[str, Any] = {
            "init_loss_scaling": 32768.0, "use_pure_fp16": False,
            "use_fp16_guard": True, "custom_white_list": [],
            "custom_black_list": [], "dtype": "bfloat16",
        }
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": []}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {
            "sharding_degree": 1, "stage": 1, "offload": False,
        }
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {
            "accumulate_steps": 1, "micro_batch_size": 1,
            "schedule_mode": "1F1B",
        }
        self.hybrid_configs: Dict[str, Any] = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
            # expert-parallel degree: MoE expert dim shards over the
            # "ep" mesh axis; dropless dispatch runs grouped matmuls
            # inside a shard_map over it (distributed/moe.py)
            "ep_degree": 1,
            # mechanism consuming the sep axis: "ulysses" (all-to-all
            # head<->seq, the reference's sep semantics) or "ring"
            # (ppermute KV ring / context parallel)
            "sep_mechanism": "ulysses",
        }
        self.tensor_parallel = False
        self.tensor_parallel_configs: Dict[str, Any] = {
            "tensor_parallel_degree": 1,
        }
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.a_sync = False

    def __deepcopy__(self, memo):
        new = DistributedStrategy()
        for k, v in self.__dict__.items():
            setattr(new, k, copy.deepcopy(v, memo))
        return new

    def __repr__(self):
        lines = ["DistributedStrategy("]
        for k, v in sorted(self.__dict__.items()):
            lines.append(f"  {k}={v!r},")
        return "\n".join(lines) + "\n)"
