"""Megatron-style sequence parallelism
(``python/paddle/distributed/fleet/utils/sequence_parallel_utils.py``).

Activations between TP blocks are sharded on the sequence dim over the
``mp`` axis. The reference's ScatterOp/GatherOp autograd pairs become
sharding constraints: GSPMD emits the reduce-scatter / all-gather pair
(which is the bandwidth-optimal form of the identity/allreduce pair).
Layout convention matches Paddle: [s, b, h] with seq first.
"""
from __future__ import annotations

from ...framework.core import Tensor
from ...nn import functional as F
from ...nn.initializer import XavierNormal
from ...nn.layer.layers import Layer
from ..shard_utils import annotate_param, constraint, mesh_axis_size

__all__ = ["ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks"]


class ScatterOp:
    """Split activations along seq dim across mp (static: a constraint)."""

    @staticmethod
    def apply(x):
        return constraint(x, "mp", *([None] * (x.ndim - 1)))


class GatherOp:
    @staticmethod
    def apply(x):
        return constraint(x, *([None] * x.ndim))


AllGatherOp = GatherOp
ReduceScatterOp = ScatterOp


def scatter(x):
    return ScatterOp.apply(x)


def all_gather(x):
    return GatherOp.apply(x)


class ColumnSequenceParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal())
        annotate_param(self.weight, (None, "mp"))
        if has_bias is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([out_features], attr=None,
                                              is_bias=True)
            annotate_param(self.bias, ("mp",))

    def forward(self, x):
        # input seq-sharded [s/mp, b, h] -> gather seq, shard hidden
        x = GatherOp.apply(x)
        y = F.linear(x, self.weight, self.bias)
        return constraint(y, *([None] * (y.ndim - 1) + ["mp"]))


class RowSequenceParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal())
        annotate_param(self.weight, ("mp", None))
        self.bias = self.create_parameter([out_features], attr=None,
                                          is_bias=True) if has_bias \
            else None

    def forward(self, x):
        y = F.linear(x, self.weight, None)
        # output reduce-scattered onto seq dim
        y = ScatterOp.apply(y)
        if self.bias is not None:
            y = y + self.bias
        return y


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """SP-parameter grad all-reduce is emitted by GSPMD in the jitted
    step; the hook registration is kept for source compatibility."""
    return model
