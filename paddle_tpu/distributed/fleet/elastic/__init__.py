"""Elastic training / failure detection (reference:
``python/paddle/distributed/fleet/elastic/manager.py`` — etcd-backed
``ElasticManager`` watching peer liveness and triggering relaunch;
SURVEY.md §5.3).

TPU-first design: multi-controller JAX has no in-job elasticity — a
lost host invalidates the mesh — so the recovery unit is the *job*:
detect the failure fast, relaunch the processes (launch controller's
``--max_restarts``), and resume from the latest checkpoint with
reshard-on-load (orbax handles a different mesh/degree at restore).
The rendezvous/liveness store is the native C++ TCPStore
(``native/tcp_store.cc``) instead of etcd — same keyed watch pattern,
no external service.

Pieces:
- ``ElasticManager``: heartbeat registration + liveness watch over the
  TCPStore; ``watch()`` reports dead ranks, ``ready()`` gates job start
  on np in [min, max].
- ``save_checkpoint`` / ``resume_or_start``: the checkpoint-restart-
  reshard recipe (step-numbered orbax dirs, latest-wins, pruning).
"""
from __future__ import annotations

import json
import os
import re
import shutil
import time
from typing import Dict, List, Optional

__all__ = ["ElasticManager", "ElasticStatus", "save_checkpoint",
           "resume_or_start", "latest_checkpoint"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Liveness bookkeeping over the native TCPStore.

    rank 0 passes ``is_master=True`` (hosts the store in-process); every
    rank calls ``register()`` then ``heartbeat()`` periodically (the
    reference's etcd lease refresh). The watcher (usually the launch
    controller) polls ``watch()``; a rank whose heartbeat is older than
    ``timeout`` is dead -> ElasticStatus.RESTART.
    """

    def __init__(self, host="127.0.0.1", port=0, rank=0, world_size=1,
                 is_master=None, np_range=None, timeout=30.0,
                 join_timeout=60.0, snapshot_path=None):
        from ....native import TCPStore
        self.rank = int(rank)
        self.world_size = int(world_size)
        if is_master is None:
            is_master = self.rank == 0
        # join_timeout covers the initial rendezvous (rank 0 may bring
        # the store up seconds later); liveness polls use the
        # non-blocking try_get, so no RPC timeout applies there.
        # snapshot_path (master only) persists the store map across
        # master restarts — the etcd-durability the reference gets from
        # its external etcd master: a relaunched rank-0 preloads
        # registrations/heartbeats and job metadata instead of starting
        # from an empty store.
        self._store = TCPStore(host=host, port=port, is_master=is_master,
                               world_size=world_size,
                               timeout=join_timeout,
                               snapshot_path=(snapshot_path
                                              if is_master else None))
        self.port = self._store.port
        self.timeout = float(timeout)
        if np_range is None:
            self.np_min = self.np_max = self.world_size
        else:
            self.np_min, self.np_max = np_range

    # -- worker side ----------------------------------------------------
    def register(self):
        self._store.set(f"elastic/rank/{self.rank}/registered", "1")
        self.heartbeat()

    def heartbeat(self):
        self._store.set(f"elastic/rank/{self.rank}/beat",
                        repr(time.time()))

    def deregister(self):
        self._store.set(f"elastic/rank/{self.rank}/registered", "0")

    # -- watcher side ---------------------------------------------------
    def _beat_age(self, rank) -> Optional[float]:
        raw = self._store.try_get(f"elastic/rank/{rank}/beat")
        if raw is None:
            return None
        try:
            return time.time() - float(raw.decode())
        except ValueError:
            return None

    def poll(self) -> Dict[str, List[int]]:
        """ONE sweep of the store classifying every rank:
        ``alive`` (registered, fresh beat), ``finished`` (deregistered —
        clean exit, NOT a failure), ``dead`` (registered, beat stale),
        ``pending`` (never registered)."""
        out = {"alive": [], "finished": [], "dead": [], "pending": []}
        for r in range(self.world_size):
            reg = self._store.try_get(f"elastic/rank/{r}/registered")
            if reg is None:
                out["pending"].append(r)
            elif reg == b"0":
                out["finished"].append(r)
            else:
                age = self._beat_age(r)
                if age is not None and age <= self.timeout:
                    out["alive"].append(r)
                else:
                    out["dead"].append(r)
        return out

    def alive_ranks(self) -> List[int]:
        return self.poll()["alive"]

    def dead_ranks(self) -> List[int]:
        return self.poll()["dead"]

    def ready(self) -> bool:
        """Enough registered+alive ranks to (re)start the job."""
        return len(self.alive_ranks()) >= self.np_min

    def status_of(self, polled: Dict[str, List[int]]) -> str:
        """Classify one poll() result (reference watch-loop decision):
        RESTART only on actual deaths that drop the job below np_min;
        pending ranks (still starting) and deaths above np_min HOLD;
        clean exits (finished) never count against the job."""
        n_ok = len(polled["alive"]) + len(polled["finished"])
        if polled["dead"] and n_ok < self.np_min:
            return ElasticStatus.RESTART
        if polled["dead"] or polled["pending"]:
            return ElasticStatus.HOLD
        return ElasticStatus.COMPLETED

    def watch(self) -> str:
        """One poll of the reference's watch loop."""
        return self.status_of(self.poll())

    def reset(self):
        """Clear all rank liveness keys (controller calls this between
        pod restart attempts so stale beats don't mask a dead rank)."""
        for r in range(self.world_size):
            self._store.delete_key(f"elastic/rank/{r}/beat")
            self._store.delete_key(f"elastic/rank/{r}/registered")

    def close(self):
        self._store.close()


# -----------------------------------------------------------------------
# checkpoint-restart-reshard recipe
# -----------------------------------------------------------------------

_STEP_RE = re.compile(r"^checkpoint-(\d+)$")


def latest_checkpoint(ckpt_dir) -> Optional[str]:
    """Path of the newest ``checkpoint-<step>`` subdir, or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    best = None
    best_step = -1
    for name in os.listdir(ckpt_dir):
        m = _STEP_RE.match(name)
        if m and os.path.exists(
                os.path.join(ckpt_dir, name, "_COMPLETE")):
            step = int(m.group(1))
            if step > best_step:
                best_step, best = step, os.path.join(ckpt_dir, name)
    return best


def save_checkpoint(ckpt_dir, step, state_dict, keep_last=3):
    """Write ``checkpoint-<step>`` (orbax sharded) + commit marker;
    prune older checkpoints beyond ``keep_last``. The commit marker
    makes a preemption mid-write invisible to resume."""
    from ...checkpoint import save_state_dict
    path = os.path.join(ckpt_dir, f"checkpoint-{int(step)}")
    os.makedirs(ckpt_dir, exist_ok=True)
    if os.path.exists(path):
        shutil.rmtree(path)
    save_state_dict(state_dict, os.path.join(path, "state"))
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump({"step": int(step), "time": time.time()}, f)
    with open(os.path.join(path, "_COMPLETE"), "w") as f:
        f.write("ok")
    steps = sorted(
        (int(_STEP_RE.match(n).group(1)) for n in os.listdir(ckpt_dir)
         if _STEP_RE.match(n)), reverse=True)
    for old in steps[keep_last:]:
        shutil.rmtree(os.path.join(ckpt_dir, f"checkpoint-{old}"),
                      ignore_errors=True)
    return path


def resume_or_start(ckpt_dir, state_dict) -> int:
    """Restore the newest complete checkpoint into ``state_dict`` IN
    PLACE (resharded to each tensor's CURRENT sharding — the restart may
    run on a different mesh). Returns the step to resume from (0 if no
    checkpoint exists)."""
    from ...checkpoint import load_state_dict
    path = latest_checkpoint(ckpt_dir)
    if path is None:
        return 0
    load_state_dict(state_dict, os.path.join(path, "state"))
    with open(os.path.join(path, "meta.json")) as f:
        return int(json.load(f)["step"])
