"""Megatron-style tensor-parallel layers
(``python/paddle/distributed/fleet/layers/mpu/mp_layers.py`` parity).

TPU-first: instead of per-rank weight shards + explicit
identity/allreduce autograd ops, each layer holds the FULL logical weight
annotated with a PartitionSpec over the ``mp`` mesh axis; GSPMD partitions
the matmul onto the MXU of each chip and inserts the all-reduce /
all-gather over ICI that the reference performs via ProcessGroupNCCL
(``mp_ops.py`` _c_identity/_c_allreduce pairs).
"""
from __future__ import annotations

import math

from ...framework.core import Tensor
from ...nn import functional as F
from ...nn.initializer import Constant, Normal, XavierNormal
from ...nn.layer.layers import Layer
from ...ops import lora as _lora
from ..shard_utils import annotate_param, constraint, mesh_axis_size

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy"]


class ColumnParallelLinear(Layer):
    """Y = XW, W sharded on the output (column) dim over ``mp``."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.world_size = mesh_axis_size("mp")
        if out_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"out_features={out_features} not divisible by mp degree "
                f"{self.world_size}")
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal())
        annotate_param(self.weight, (None, "mp"))
        if has_bias is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [out_features], attr=None, is_bias=True)
            annotate_param(self.bias, ("mp",))

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            y = constraint(y, *([None] * (y.ndim)))  # replicated
        else:
            y = constraint(y, *([None] * (y.ndim - 1) + ["mp"]))
        return _lora.apply(self, x, y)


class RowParallelLinear(Layer):
    """Y = XW, W sharded on the input (row) dim over ``mp``; GSPMD emits
    the partial-sum all-reduce the reference codes explicitly."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.world_size = mesh_axis_size("mp")
        if in_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"in_features={in_features} not divisible by mp degree "
                f"{self.world_size}")
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=XavierNormal())
        annotate_param(self.weight, ("mp", None))
        if has_bias:
            self.bias = self.create_parameter(
                [out_features], attr=None, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = constraint(x, *([None] * (x.ndim - 1) + ["mp"]))
        y = F.linear(x, self.weight, None)
        y = constraint(y, *([None] * y.ndim))  # forces the mp reduce
        if self.bias is not None:
            y = y + self.bias
        return _lora.apply(self, x, y)


class VocabParallelEmbedding(Layer):
    """Embedding table sharded on the vocab dim over ``mp``."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.world_size = mesh_axis_size("mp")
        if num_embeddings % max(self.world_size, 1) != 0:
            raise ValueError(
                f"num_embeddings={num_embeddings} not divisible by mp "
                f"degree {self.world_size}")
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        annotate_param(self.weight, ("mp", None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return constraint(out, *([None] * out.ndim))


class ParallelCrossEntropy(Layer):
    """Cross entropy over class-dim-sharded logits
    (``mp_ops._c_softmax_with_cross_entropy`` parity): GSPMD partitions
    the log-softmax reduction over ``mp``."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        from ...ops.manipulation import unsqueeze
        return unsqueeze(loss, -1)
