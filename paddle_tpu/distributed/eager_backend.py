"""Cross-process EAGER collectives over the native TCPStore (the Gloo
role: reference ``ProcessGroupGloo`` — ``paddle/fluid/distributed/
collective/process_group_gloo.cc``).

The TPU data path never uses this: jitted/shard_map code lowers
collectives to XLA ops over ICI (SURVEY §5.8). This backend exists for
the reference's EAGER ProcessGroup semantics — utility collectives and
multi-process tests (TestDistBase pattern: driver spawns ranks, each
executes real cross-process ops). Implementation: every rank posts its
payload to the rendezvous store under a (group, op, sequence) key and
reads the peers' — O(world²) store traffic, which is fine for the
control-plane/test role this backend serves. Keys are delete()d by the
last reader so long runs don't grow the store.
"""
from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["StoreBackend", "get_eager_backend", "init_eager_backend"]


class StoreBackend:
    def __init__(self, host: str, port: int, rank: int, world_size: int,
                 timeout: float = 120.0):
        from ..native import TCPStore
        self.rank = int(rank)
        self.world = int(world_size)
        self.store = TCPStore(host=host, port=int(port),
                              is_master=self.rank == 0,
                              world_size=self.world, timeout=timeout)
        self._seq: Dict[Tuple, int] = {}

    # -- plumbing -------------------------------------------------------
    def _next(self, gkey, op) -> int:
        k = (gkey, op)
        s = self._seq.get(k, 0)
        self._seq[k] = s + 1
        return s

    @staticmethod
    def _gkey(ranks: Sequence[int]) -> str:
        return "-".join(str(r) for r in ranks)

    def _post(self, key: str, obj) -> None:
        self.store.set(key, pickle.dumps(obj, protocol=4))

    def _fetch(self, key: str):
        self.store.wait([key])
        return pickle.loads(self.store.get(key))

    def _done(self, base: str, ranks) -> None:
        """Mark this rank done reading; the last reader deletes the
        op's keys (best effort — delete may not exist on old stores)."""
        n = self.store.add(base + "/done", 1)
        if n == len(ranks):
            for r in ranks:
                try:
                    self.store.delete_key(f"{base}/{r}")
                except Exception:
                    pass
            try:
                self.store.delete_key(base + "/done")
            except Exception:
                pass

    def _exchange(self, op: str, ranks: Sequence[int], payload):
        """All participating ranks post; each returns {rank: payload}."""
        gkey = self._gkey(ranks)
        seq = self._next(gkey, op)
        base = f"eager/{gkey}/{op}/{seq}"
        self._post(f"{base}/{self.rank}", payload)
        out = {r: self._fetch(f"{base}/{r}") for r in ranks}
        self._done(base, ranks)
        return out

    # -- collectives ----------------------------------------------------
    def all_reduce(self, arr: np.ndarray, op: str,
                   ranks: Sequence[int]) -> np.ndarray:
        vals = self._exchange("allreduce", ranks, np.asarray(arr))
        ordered = [vals[r] for r in sorted(vals)]
        if op in ("sum", "avg"):
            out = np.sum(ordered, axis=0)
            if op == "avg":
                out = out / len(ordered)
        elif op == "max":
            out = np.max(ordered, axis=0)
        elif op == "min":
            out = np.min(ordered, axis=0)
        elif op == "prod":
            out = np.prod(ordered, axis=0)
        else:
            raise ValueError(f"unknown reduce op {op!r}")
        return out.astype(np.asarray(arr).dtype)

    def all_gather(self, arr: np.ndarray,
                   ranks: Sequence[int]) -> List[np.ndarray]:
        vals = self._exchange("allgather", ranks, np.asarray(arr))
        return [vals[r] for r in sorted(vals)]

    def all_gather_object(self, obj, ranks: Sequence[int]) -> list:
        vals = self._exchange("allgather_obj", ranks, obj)
        return [vals[r] for r in sorted(vals)]

    def broadcast(self, arr, src: int, ranks: Sequence[int]):
        gkey = self._gkey(ranks)
        seq = self._next(gkey, "bcast")
        base = f"eager/{gkey}/bcast/{seq}"
        if self.rank == src:
            self._post(f"{base}/{src}", arr)
            out = arr
        else:
            out = self._fetch(f"{base}/{src}")
        # all participants count in; the LAST one deletes the value
        n = self.store.add(base + "/done", 1)
        if n == len(ranks):
            for k in (f"{base}/{src}", base + "/done"):
                try:
                    self.store.delete_key(k)
                except Exception:
                    pass
        return out

    def reduce_scatter(self, arr: np.ndarray, op: str,
                       ranks: Sequence[int]) -> np.ndarray:
        n = len(ranks)
        if np.shape(arr)[0] % n != 0:
            raise ValueError(
                f"reduce_scatter: dim0 {np.shape(arr)[0]} not divisible "
                f"by the group size {n}")
        if self.rank not in ranks:
            return np.asarray(arr)
        full = self.all_reduce(arr, op, ranks)
        chunk = full.shape[0] // n
        idx = sorted(ranks).index(self.rank)
        return full[idx * chunk:(idx + 1) * chunk]

    def all_to_all(self, chunks: List[np.ndarray],
                   ranks: Sequence[int]) -> List[np.ndarray]:
        srt = sorted(ranks)
        payload = {r: c for r, c in zip(srt, chunks)}
        vals = self._exchange("alltoall", ranks, payload)
        return [vals[r][self.rank] for r in srt]

    # -- p2p ------------------------------------------------------------
    def send(self, arr, dst: int) -> None:
        seq = self._next(("p2p", self.rank, dst), "send")
        self._post(f"eager/p2p/{self.rank}-{dst}/{seq}", np.asarray(arr))

    def recv(self, src: int):
        seq = self._next(("p2p", src, self.rank), "recv")
        key = f"eager/p2p/{src}-{self.rank}/{seq}"
        out = self._fetch(key)
        try:
            self.store.delete_key(key)   # single reader: delete now
        except Exception:
            pass
        return out

    def barrier(self, ranks: Sequence[int]) -> None:
        self._exchange("barrier", ranks, 0)


_backend: Optional[StoreBackend] = None
_backend_failed = False


def init_eager_backend(host=None, port=None, rank=None, world_size=None):
    """Explicitly initialize the eager cross-process backend (also done
    lazily by the collective facades when the launch env is present)."""
    global _backend
    if _backend is None:
        from . import env as _env
        e = _env._env()
        rank = e.rank if rank is None else rank
        world_size = e.world_size if world_size is None else world_size
        if host is None or port is None:
            eager_store = os.environ.get("PADDLE_EAGER_STORE")
            master = eager_store or os.environ.get("PADDLE_MASTER")
            if not master:
                raise RuntimeError(
                    "eager backend needs PADDLE_MASTER or "
                    "PADDLE_EAGER_STORE (host:port)")
            host, p = master.rsplit(":", 1)
            if port is None:
                if eager_store:
                    # an explicit PADDLE_EAGER_STORE names the exact
                    # store address — honor its port verbatim
                    port = int(p)
                else:
                    # derived from PADDLE_MASTER: offset past the
                    # launch controller's rendezvous store (which owns
                    # that port), unless overridden explicitly
                    port = int(os.environ.get("PADDLE_EAGER_STORE_PORT")
                               or int(p) + 2)
    if _backend is None:
        _backend = StoreBackend(host, int(port), rank, world_size)
    return _backend


def get_eager_backend() -> Optional[StoreBackend]:
    """The process backend, auto-initialized from the launch env when
    world_size > 1; None in a single-process world (facades then keep
    their identity semantics)."""
    global _backend, _backend_failed
    if _backend is not None or _backend_failed:
        return _backend
    from . import env as _env
    if _env.get_world_size() <= 1:
        return None
    if not (os.environ.get("PADDLE_MASTER")
            or os.environ.get("PADDLE_EAGER_STORE")):
        return None
    try:
        return init_eager_backend()
    except Exception as exc:
        _backend_failed = True   # don't retry per op
        if os.environ.get("PADDLE_EAGER_ALLOW_DEGRADE", "").lower() in (
                "1", "true", "yes", "on"):
            import warnings
            warnings.warn(
                f"eager collective backend FAILED to initialize ({exc!r});"
                " cross-process collectives on this rank degrade to "
                "single-process identity — ranks may silently diverge "
                "(PADDLE_EAGER_ALLOW_DEGRADE is set).")
            return None
        # a launch env with world_size > 1 promised a real backend; a
        # silent per-rank identity fallback would let ranks diverge —
        # fail loudly instead (PADDLE_EAGER_ALLOW_DEGRADE=1 opts out)
        raise RuntimeError(
            "eager collective backend failed to initialize for a "
            f"world_size={_env.get_world_size()} launch: {exc!r}. Set "
            "PADDLE_EAGER_STORE / PADDLE_EAGER_STORE_PORT to a reachable "
            "store address, or PADDLE_EAGER_ALLOW_DEGRADE=1 to accept "
            "single-process identity semantics.") from exc
