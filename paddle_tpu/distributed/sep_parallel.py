"""Ulysses-style segment parallelism over the ``sep`` mesh axis
(reference: the ``sep`` degree in
``python/paddle/distributed/fleet/base/topology.py`` plus PaddleNLP
``paddlenlp/transformers/segment_parallel_utils.py`` — SURVEY.md §5.7
mechanism 2; DeepSpeed-Ulysses is the originating design).

Mechanics, TPU-first: activations arrive sequence-sharded
``[B, L/sp, H, D]``. Inside a shard_map over the ``sep`` axis an
``all_to_all`` swaps the shard dimension — each device trades its
sequence slice of every head for the full sequence of ``H/sp`` heads —
attention runs un-sharded per head subset (so any kernel works,
including the Pallas flash kernel), and a second ``all_to_all``
restores sequence sharding. Total comm is 2 all-to-alls of the qkv/out
activations riding ICI, vs. the ring's ``sp`` ppermute hops of KV —
Ulysses wins when heads are plentiful and KV is large (GQA favors the
ring; dense MHA favors Ulysses), which is why the mechanism is a
config knob rather than hard-wired.

Distinct from ``ring_attention.py`` (context parallel): the config key
``hybrid_configs["sep_mechanism"]`` selects which mechanism consumes
the ``sep`` axis ("ulysses", the reference's sep semantics, is the
default; "ring" keeps the CP behavior).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..framework.core import Tensor, apply_jax, as_jax
from . import env as _env

__all__ = ["ulysses_attention", "sep_attention", "get_sep_mechanism",
           "ReshardLayer"]


def get_sep_mechanism() -> str:
    """Mechanism consuming the sep axis: "ulysses" (default) or "ring"."""
    try:
        from .fleet import _strategy
        if _strategy is not None:
            return _strategy.hybrid_configs.get("sep_mechanism", "ulysses")
    except Exception:
        pass
    return "ulysses"


def _full_seq_attention(q, k, v, causal, scale):
    """Attention on unsharded [B, L, H', D] blocks (head subset)."""
    from ..ops.pallas.flash_attention import flash_attention_core
    return flash_attention_core(q, k, v, is_causal=causal, scale=scale)


def ulysses_attention(q, k, v, mesh: Mesh = None, axis: str = "sep",
                      causal: bool = False, scale=None):
    """q/k/v: [B, L, H, D] with L globally sharded over ``axis`` and the
    same head count H (GQA callers repeat KV heads first). Requires
    H % sep_degree == 0. Returns [B, L, H, D], seq-sharded like q."""
    mesh = mesh or _env.get_mesh()
    q_arr, k_arr, v_arr = as_jax(q), as_jax(k), as_jax(v)
    if scale is None:
        scale = 1.0 / np.sqrt(q_arr.shape[-1])
    scale = float(scale)
    from .shard_utils import in_manual_region
    sp = mesh.shape[axis] if mesh is not None else 1
    if mesh is None or sp <= 1 or in_manual_region():
        # in_manual_region: already inside a shard_map (e.g. a pipeline
        # stage) — a nested shard_map over the same mesh is invalid, and
        # the data there is not seq-sharded, so plain attention is right
        out = jax.nn.dot_product_attention(q_arr, k_arr, v_arr,
                                           is_causal=causal, scale=scale)
        return Tensor(out) if isinstance(q, Tensor) else out

    n_heads = q_arr.shape[2]
    if n_heads % sp != 0:
        from ..framework.errors import InvalidArgumentError
        raise InvalidArgumentError(
            f"ulysses_attention: num_heads={n_heads} not divisible by "
            f"sep degree {sp}",
            hint="use sep_mechanism='ring' for this shape")

    def per_device(ql, kl, vl):
        # [B, L/sp, H, D] -> all_to_all -> [B, L, H/sp, D]
        def s2h(x):
            return jax.lax.all_to_all(x, axis, split_axis=2,
                                      concat_axis=1, tiled=True)

        def h2s(x):
            return jax.lax.all_to_all(x, axis, split_axis=1,
                                      concat_axis=2, tiled=True)

        qh, kh, vh = s2h(ql), s2h(kl), s2h(vl)
        out = _full_seq_attention(qh, kh, vh, causal, scale)
        return h2s(out)

    from .shard_utils import shard_map_compat
    spec = P(None, axis, None, None)
    mapped = shard_map_compat(per_device, mesh, (spec, spec, spec), spec)

    if isinstance(q, Tensor):
        return apply_jax("ulysses_attention", mapped, q, k, v)
    return mapped(q_arr, k_arr, v_arr)


_indivisible_warned = False


def sep_attention(q, k, v, causal: bool = True, scale=None):
    """Dispatch attention over the sep axis per the configured mechanism
    (the single entry point model code uses). Falls back to the ring
    when Ulysses can't split the heads evenly."""
    mechanism = get_sep_mechanism()
    if mechanism != "ring":
        mesh = _env.get_mesh()
        sp = mesh.shape.get("sep", 1) if mesh is not None else 1
        if sp > 1 and as_jax(q).shape[2] % sp != 0:
            global _indivisible_warned
            if not _indivisible_warned:
                _indivisible_warned = True
                import warnings
                warnings.warn(
                    "sep_attention: num_heads %d not divisible by sep "
                    "degree %d; falling back to the ring mechanism"
                    % (as_jax(q).shape[2], sp))
            mechanism = "ring"
    if mechanism == "ring":
        from .ring_attention import ring_flash_attention
        return ring_flash_attention(q, k, v, causal=causal, scale=scale)
    return ulysses_attention(q, k, v, causal=causal, scale=scale)


class ReshardLayer:
    """PaddleNLP ``segment_parallel_utils.ReshardLayer`` parity: reshard
    [b, s/sep, h, d] <-> [b, s, h/sep, d] via all_to_all on the sep
    axis (as a standalone op, outside attention)."""

    @staticmethod
    def apply(x, split_axis: int = 2, concat_axis: int = 1,
              axis: str = "sep"):
        mesh = _env.get_mesh()
        sp = mesh.shape[axis] if mesh is not None else 1
        if mesh is None or sp <= 1:
            return x

        def per_device(xl):
            return jax.lax.all_to_all(xl, axis, split_axis=split_axis,
                                      concat_axis=concat_axis, tiled=True)

        from .shard_utils import shard_map_compat
        ndim = as_jax(x).ndim
        in_spec = [None] * ndim
        in_spec[concat_axis] = axis
        out_spec = [None] * ndim
        out_spec[split_axis] = axis
        mapped = shard_map_compat(per_device, mesh, (P(*in_spec),),
                                  P(*out_spec))
        if isinstance(x, Tensor):
            return apply_jax("sep_reshard", mapped, x)
        return mapped(as_jax(x))
