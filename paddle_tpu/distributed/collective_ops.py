"""Static-graph collective operators (reference:
``paddle/fluid/operators/collective/c_*_op.cc`` — the comm nodes a
static ``Program`` holds explicitly: ``c_allreduce_sum``,
``c_broadcast``, ``c_allgather``, ``c_reducescatter``, ...).

TPU-first: each ``c_*`` op is the SAME collective verb the eager API
uses (``distributed/collective.py``'s three-regime design); recorded on
a ``SymbolicTensor`` it becomes a node of the static DAG and the
Executor jits it with the rest of the program — inside a mesh the verb
lowers to the ``lax`` collective, single-process it is the documented
identity regime. The reference needs distinct C++ operator classes
because its static IR is a separate universe from eager; here one
implementation serves both, and these names exist so reference static
scripts translate one-to-one.
"""
from __future__ import annotations

from . import collective as _c
from .collective import ReduceOp

__all__ = ["c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
           "c_allreduce_prod", "c_broadcast", "c_allgather",
           "c_reducescatter", "c_reduce_sum", "c_identity", "c_concat",
           "c_split", "c_sync_calc_stream", "c_sync_comm_stream"]


def c_allreduce_sum(x, ring_id=0, use_calc_stream=True, group=None):
    return _c.all_reduce(x, op=ReduceOp.SUM, group=group)


def c_allreduce_max(x, ring_id=0, use_calc_stream=True, group=None):
    return _c.all_reduce(x, op=ReduceOp.MAX, group=group)


def c_allreduce_min(x, ring_id=0, use_calc_stream=True, group=None):
    return _c.all_reduce(x, op=ReduceOp.MIN, group=group)


def c_allreduce_prod(x, ring_id=0, use_calc_stream=True, group=None):
    return _c.all_reduce(x, op=ReduceOp.PROD, group=group)


def c_reduce_sum(x, root=0, ring_id=0, use_calc_stream=True,
                 group=None):
    return _c.reduce(x, dst=root, op=ReduceOp.SUM, group=group)


def c_broadcast(x, root=0, ring_id=0, use_calc_stream=True,
                group=None):
    return _c.broadcast(x, src=root, group=group)


def c_allgather(x, nranks=None, ring_id=0, use_calc_stream=True,
                group=None):
    out = []
    _c.all_gather(out, x, group=group)
    from ..ops.manipulation import concat
    return concat(out, axis=0)


def c_reducescatter(x, nranks=None, ring_id=0, use_calc_stream=True,
                    group=None):
    return _c.reduce_scatter(x, None, op=ReduceOp.SUM, group=group)


def c_identity(x, ring_id=0, use_calc_stream=True, use_model_parallel=True):
    """Identity forward whose BACKWARD is an all-reduce (the mp-layers
    input marker). GSPMD inserts the gradient collective from the
    sharding, so forward identity is the whole op here."""
    return x


def c_concat(x, nranks=None, ring_id=0, group=None):
    """Gather model-parallel shards along the LAST dim (the reference's
    mp gather for gather_output=True)."""
    out = []
    _c.all_gather(out, x, group=group)
    from ..ops.manipulation import concat
    return concat(out, axis=-1)


def c_split(x, rank=None, nranks=None, ring_id=0, group=None):
    """Take this rank's slice along the last dim."""
    from .env import get_rank, get_world_size
    from ..ops.manipulation import split
    nr = nranks or max(get_world_size(), 1)
    r = rank if rank is not None else get_rank()
    return split(x, nr, axis=-1)[r]


def c_sync_calc_stream(x=None):
    """Stream sync is a no-op under XLA's single ordered program."""
    return x


def c_sync_comm_stream(x=None, ring_id=0):
    return x
