"""``paddle.distributed`` namespace (L4 in SURVEY.md §1).

Mesh-based: process groups are mesh axes, collectives are XLA ops, the
launcher shims onto single-controller jax or multi-process emulation.
"""
from .env import (ParallelEnv, get_rank, get_world_size, init_parallel_env,
                  is_initialized, device_mesh, get_mesh, set_mesh)
from .collective import (Group, P2POp, ReduceOp, all_gather,
                         all_gather_object, all_reduce, alltoall,
                         alltoall_single, barrier, batch_isend_irecv,
                         broadcast, broadcast_object_list, get_group,
                         isend, irecv, new_group, recv, reduce, reduce_scatter,
                         scatter, send, wait, _all_reduce_eager_mean)
from . import collective_ops
from .collective_ops import *  # noqa: F401,F403
from . import fleet
from . import auto_parallel
from . import checkpoint
from . import rpc
from . import ps
from . import sharding as sharding_mod
from .auto_parallel import (DistAttr, Partial, Placement, ProcessMesh,
                            Replicate, Shard, Strategy, dtensor_from_fn,
                            reshard, shard_layer, shard_optimizer,
                            shard_tensor, to_static, unshard_dtensor)
from .checkpoint import load_state_dict, save_state_dict
from .moe import MoELayer


def __getattr__(name):
    # TCPStore is native (ctypes over native/tcp_store.cc); import lazily
    # so `import paddle_tpu` works before the lib is first built.
    if name == "TCPStore":
        from ..native import TCPStore
        return TCPStore
    raise AttributeError(name)
from .pipeline import pipeline_apply, stack_stage_params
from .recompute import recompute, recompute_sequential
from .ring_attention import RingFlashAttention, ring_flash_attention
from .sep_parallel import (ReshardLayer, sep_attention,
                           ulysses_attention)
from .shard_utils import constraint as shard_op_constraint
from .sharding import group_sharded_parallel, save_group_sharded_model

# paddle.distributed.sharding submodule path parity
import sys as _sys
_sys.modules[__name__ + ".sharding"] = sharding_mod
sharding = sharding_mod


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """``paddle.distributed.spawn`` — multiprocess launch over local
    devices (used by collective tests; each proc sees the emulated mesh)."""
    import multiprocessing as mp
    import os
    if nprocs == -1:
        nprocs = 1
    procs = []
    for rank in range(nprocs):
        env = dict(os.environ)
        env["PADDLE_TRAINER_ID"] = str(rank)
        env["PADDLE_TRAINERS_NUM"] = str(nprocs)

        def target(r=rank, e=env):
            os.environ.update(e)
            func(*args)

        p = mp.Process(target=target, daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(
                    f"spawned process exited with {p.exitcode}")
    return procs


def get_backend():
    return "xla"
