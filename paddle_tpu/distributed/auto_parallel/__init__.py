"""Semi-auto parallel API (``python/paddle/distributed/auto_parallel/``
+ C++ DistTensor machinery parity).

TPU-first mapping (SURVEY.md §7.2): ``DistTensor + SPMD rules + reshard``
ARE ``jax.sharding.NamedSharding`` + GSPMD propagation + resharding
``device_put``. This module supplies the API parity layer: ProcessMesh,
Shard/Replicate/Partial placements, shard_tensor, reshard, shard_layer,
shard_optimizer. SPMD rule inference per-op is the compiler's job here —
XLA's sharding propagation replaces ``phi/infermeta/spmd_rules/``.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...framework.core import Tensor, as_jax, _wrap_out
from .. import env as _env

__all__ = ["ProcessMesh", "Placement", "Shard", "Replicate", "Partial",
           "shard_tensor", "dtensor_from_fn", "reshard", "shard_layer",
           "shard_optimizer", "unshard_dtensor", "get_mesh", "set_mesh",
           "Strategy", "to_static", "DistAttr", "DistModel"]


class ProcessMesh:
    """N-d logical mesh over device ids (``paddle.distributed.ProcessMesh``
    parity, backed by a jax Mesh)."""

    def __init__(self, mesh, dim_names=None, shape=None, process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._dim_names = list(dim_names)
        self._process_ids = arr.reshape(-1).tolist()
        self._shape = list(arr.shape)
        devices = jax.devices()
        dev_arr = np.array([devices[i % len(devices)]
                            for i in self._process_ids]).reshape(arr.shape)
        self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))

    @property
    def shape(self):
        return self._shape

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def process_ids(self):
        return self._process_ids

    @property
    def mesh(self):
        return np.asarray(self._process_ids).reshape(self._shape)

    def jax_mesh(self) -> Mesh:
        return self._jax_mesh

    def get_dim_size(self, name):
        return self._shape[self._dim_names.index(name)]

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self._shape == other._shape
                and self._process_ids == other._process_ids)

    def __repr__(self):
        return f"ProcessMesh(shape={self._shape}, dims={self._dim_names})"


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def get_dim(self):
        return self.dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)


class Partial(Placement):
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"


def _placements_to_spec(mesh: ProcessMesh, placements, ndim: int):
    """placements[i] describes mesh dim i → build a PartitionSpec over
    tensor dims."""
    tensor_axes: List = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Partial):
            # TPU-first semantics: pending-reduction state only exists
            # transiently INSIDE compiled GSPMD programs (XLA inserts
            # the psum where needed). A materialized dist tensor with a
            # Partial placement therefore carries the REDUCED value and
            # keeps Partial as metadata — reshard(..., Replicate()) is
            # then the identity p_to_r, matching the reference's
            # observable contract without inventing per-rank state the
            # single-controller model doesn't have.
            continue
        if isinstance(pl, Shard):
            name = mesh.dim_names[mesh_dim]
            cur = tensor_axes[pl.dim]
            if cur is None:
                tensor_axes[pl.dim] = name
            elif isinstance(cur, tuple):
                tensor_axes[pl.dim] = cur + (name,)
            else:
                tensor_axes[pl.dim] = (cur, name)
    return P(*tensor_axes)


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None,
                 place=None, stop_gradient=None):
    """``dist.shard_tensor`` — place onto the mesh with NamedSharding."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    spec = _placements_to_spec(mesh, placements, t.ndim)
    sharding = NamedSharding(mesh.jax_mesh(), spec)
    if not isinstance(t._data, jax.core.Tracer):
        t._data = jax.device_put(t._data, sharding)
    else:
        t._data = jax.lax.with_sharding_constraint(t._data, sharding)
    t.dist_spec = spec
    t.process_mesh = mesh
    t.placements = list(placements)
    if stop_gradient is not None:
        t.stop_gradient = stop_gradient
    return t


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(dist_tensor, mesh: ProcessMesh, placements):
    """``dist.reshard`` — XLA moves the data (the reshard/ function zoo
    s_to_r/r_to_s/p_to_r collapses into one device_put)."""
    return shard_tensor(dist_tensor, mesh, placements)


def unshard_dtensor(dist_tensor):
    arr = as_jax(dist_tensor)
    full = jax.device_put(
        arr, NamedSharding(_single_mesh(), P()))
    out = _wrap_out(full)
    out.stop_gradient = dist_tensor.stop_gradient \
        if isinstance(dist_tensor, Tensor) else True
    return out


def _single_mesh():
    return Mesh(np.array(jax.devices()[:1]), ("x",))


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """``dist.shard_layer``: apply shard_fn(name, layer, mesh) to place
    each sublayer's params; default replicates onto the mesh."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for pname, p in sublayer._parameters.items():
                if p is not None:
                    ndim = p.ndim
                    shard_tensor(p, mesh,
                                 [Replicate()] * len(mesh.shape))
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inp, out: output_fn(out, process_mesh))
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """``dist.shard_optimizer``: optimizer states inherit each param's
    sharding automatically (accumulators are created like the param);
    shard_fn can override per-state placement."""
    if shard_fn is not None:
        optimizer._dist_shard_fn = shard_fn
    return optimizer


class DistAttr:
    def __init__(self, mesh=None, sharding_specs=None):
        self.process_mesh = mesh
        self.sharding_specs = sharding_specs or []


class Strategy:
    """``dist.Strategy`` (auto-parallel strategy mirror)."""

    class _Sub:
        def __init__(self, **kw):
            self.__dict__.update(kw)
            self.enable = False

    def __init__(self, config=None):
        self.sharding = Strategy._Sub(degree=1, stage=1)
        self.fused_passes = Strategy._Sub(fused_passes_list=[])
        self.gradient_merge = Strategy._Sub(k_steps=1, avg=True)
        self.pipeline = Strategy._Sub(schedule_mode="1F1B",
                                      micro_batch_size=1,
                                      accumulate_steps=1)
        self.amp = Strategy._Sub(dtype="bfloat16", level="O1")
        self.recompute = Strategy._Sub(checkpoints=[])


class DistModel:
    """Result of ``dist.to_static``: jitted dist train/eval step."""

    def __init__(self, layer, loader, loss=None, optimizer=None,
                 strategy=None):
        self.network = layer
        self._loss = loss
        self._optimizer = optimizer
        self._mode = "train"
        self._train_step = None

    def train(self):
        self._mode = "train"

    def eval(self):
        self._mode = "eval"

    def __call__(self, *batch):
        inputs = [b if isinstance(b, Tensor) else Tensor(b)
                  for b in batch]
        if self._mode == "train" and self._optimizer is not None \
                and self._loss is not None:
            if self._train_step is None:
                from ...jit import TrainStep
                self._train_step = TrainStep(
                    self.network,
                    lambda out, a, k: self._loss(
                        out, *[Tensor(x) for x in k["_labels"]]),
                    self._optimizer)
            *feats, label = inputs
            return self._train_step(*feats, _labels=(label,))
        out = self.network(*inputs[:-1] if self._loss else inputs)
        if self._loss is not None:
            return self._loss(out, inputs[-1])
        return out

    def state_dict(self, mode="all"):
        return self.network.state_dict()


def to_static(layer, loader=None, loss=None, optimizer=None,
              strategy=None):
    """``dist.to_static`` parity — returns (DistModel, loader)."""
    dm = DistModel(layer, loader, loss, optimizer, strategy)
    return dm, loader


def get_mesh():
    m = _env.get_mesh()
    return m


def set_mesh(mesh):
    if isinstance(mesh, ProcessMesh):
        _env.set_mesh(mesh.jax_mesh())
    else:
        _env.set_mesh(mesh)
