"""Parameter-server mode (reference: ``paddle/fluid/distributed/ps/``
brpc PS — sparse/dense tables with server-side optimizers, async
push/pull — and ``python/paddle/distributed/fleet`` PS role flow).

TPU-first scope: the PS serves the SPARSE side of recommendation
models (huge embedding tables that cannot live in HBM) from host
memory, while the dense math runs through the normal jax path. The
transport is the in-tree RPC stack (``distributed/rpc`` over sockets +
TCPStore discovery) instead of brpc; tables are numpy on the server
(the reference's are C++ host tables — same locality, simpler code).

Pieces:
- ``SparseTable`` / ``DenseTable``: server-side state with server-side
  optimizers (async-SGD semantics: ``push`` applies the update at
  arrival order, no global barrier — the reference's async mode).
- ``run_server()``: hosts the tables in this process and serves
  create/pull/push/stop via RPC.
- ``PSClient``: worker-side facade; sparse ids shard across servers by
  ``id % n_servers`` (the reference's hash sharding).
- ``DistributedEmbedding``: an ``nn.Layer`` whose rows are pulled per
  batch from the PS and whose row gradients are pushed back on
  ``backward()`` via a grad hook.
"""
from __future__ import annotations

import builtins
import threading
from typing import Dict, List, Optional

import numpy as np

__all__ = ["SparseTable", "SSDSparseTable", "DenseTable", "run_server",
           "stop_server", "PSClient", "DistributedEmbedding"]


class SparseTable:
    """id -> row table with lazy row init and a server-side optimizer."""

    def __init__(self, dim, dtype="float32", optimizer="sgd", lr=0.01,
                 init_std=0.01, seed=0):
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.init_std = float(init_std)
        self.rows: Dict[int, np.ndarray] = {}
        self.acc: Dict[int, np.ndarray] = {}   # adagrad accumulators
        self._rng = np.random.RandomState(seed)
        self._mu = threading.Lock()

    def _row(self, i: int) -> np.ndarray:
        r = self.rows.get(i)
        if r is None:
            r = (self._rng.randn(self.dim) * self.init_std).astype(
                self.dtype)
            self.rows[i] = r
        return r

    def pull(self, ids) -> np.ndarray:
        with self._mu:
            return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads) -> None:
        grads = np.asarray(grads, self.dtype)
        with self._mu:
            for i, g in zip(ids, grads):
                i = int(i)
                r = self._row(i)
                if self.optimizer == "adagrad":
                    a = self.acc.setdefault(
                        i, np.zeros(self.dim, self.dtype))
                    a += g * g
                    r -= self.lr * g / (np.sqrt(a) + 1e-6)
                else:                       # async SGD
                    r -= self.lr * g

    def n_rows(self) -> int:
        return len(self.rows)


class SSDSparseTable(SparseTable):
    """Two-tier sparse table (reference: ``paddle/fluid/distributed/ps/
    table/ssd_sparse_table.cc`` + the CtrAccessor show/shrink flow): a
    bounded in-memory HOT tier with LRU eviction to a fixed-slot disk
    file, plus per-row show counters driving ``shrink()``. This is the
    industrial shape of the reference's largest subsystem scaled to the
    in-tree PS: embedding tables larger than host RAM keep serving,
    cold ids age out.

    Disk layout: one record per slot = [value row | accumulator row]
    (both ``dim`` wide, table dtype); ``_slots`` maps id -> slot. Slots
    are allocated on first eviction and reused for the row's lifetime,
    so the file never needs compaction until ``shrink``.
    """

    def __init__(self, dim, dtype="float32", optimizer="sgd", lr=0.01,
                 init_std=0.01, seed=0, cache_rows=100_000, path=None):
        super().__init__(dim, dtype, optimizer, lr, init_std, seed)
        import collections
        import os
        import tempfile
        # cache_rows=0 would evict the row being returned; the hot
        # tier needs at least one slot
        self.cache_rows = max(int(cache_rows), 1)
        self.rows = collections.OrderedDict()     # hot tier (LRU)
        if path is None:
            fd, path = tempfile.mkstemp(suffix=".pstable")
            os.close(fd)
            self._own_path = True
        else:
            self._own_path = False
        self.path = path
        # O_CREAT semantics without append-mode write repositioning
        # ("a+b" ignores seek() for writes on POSIX)
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        self._file = os.fdopen(fd, "r+b")
        self._slots: Dict[int, int] = {}          # id -> disk slot
        self._free: List[int] = []                # reusable slots
        self._n_slots = 0
        self._rec_bytes = 2 * self.dim * self.dtype.itemsize
        self.show: Dict[int, int] = {}            # CtrAccessor-lite

    # ---- disk records -------------------------------------------------

    def _write_slot(self, slot: int, value, acc) -> None:
        rec = np.concatenate([value, acc]).astype(self.dtype)
        self._file.seek(slot * self._rec_bytes)
        self._file.write(rec.tobytes())

    def _read_slot(self, slot: int):
        self._file.seek(slot * self._rec_bytes)
        buf = self._file.read(self._rec_bytes)
        rec = np.frombuffer(buf, self.dtype).copy()
        return rec[:self.dim], rec[self.dim:]

    def _evict_lru(self) -> None:
        while len(self.rows) > self.cache_rows:
            old_id, value = self.rows.popitem(last=False)
            acc = self.acc.pop(old_id, None)
            if acc is None:
                acc = np.zeros(self.dim, self.dtype)
            slot = self._slots.get(old_id)
            if slot is None:
                slot = self._free.pop() if self._free else self._n_slots
                if slot == self._n_slots:
                    self._n_slots += 1
                self._slots[old_id] = slot
            self._write_slot(slot, value, acc)

    # ---- row access (hot tier first, then disk, then init) -----------

    def _row(self, i: int) -> np.ndarray:
        r = self.rows.get(i)
        if r is not None:
            self.rows.move_to_end(i)
            self.show[i] = self.show.get(i, 0) + 1
            return r
        slot = self._slots.get(i)
        if slot is not None:
            value, acc = self._read_slot(slot)
            self.rows[i] = value
            if np.any(acc):
                self.acc[i] = acc
        else:
            self.rows[i] = (self._rng.randn(self.dim)
                            * self.init_std).astype(self.dtype)
        self.show[i] = self.show.get(i, 0) + 1
        self._evict_lru()
        return self.rows[i]

    def n_rows(self) -> int:
        return len(self.rows) + len(self._slots) - sum(
            1 for i in self._slots if i in self.rows)

    def n_hot(self) -> int:
        return len(self.rows)

    def n_disk(self) -> int:
        return len(self._slots)

    def shrink(self, threshold: int = 1) -> int:
        """Drop rows whose show count is below ``threshold`` (the
        CtrAccessor shrink pass). Returns the number dropped."""
        with self._mu:
            victims = [i for i in set(list(self.rows) +
                                      list(self._slots))
                       if self.show.get(i, 0) < threshold]
            for i in victims:
                self.rows.pop(i, None)
                self.acc.pop(i, None)
                slot = self._slots.pop(i, None)
                if slot is not None:
                    self._free.append(slot)
                self.show.pop(i, None)
            return len(victims)

    def close(self):
        import os
        try:
            self._file.close()
        finally:
            if self._own_path:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass


class DenseTable:
    def __init__(self, shape, dtype="float32", optimizer="sgd", lr=0.01,
                 seed=0):
        rng = np.random.RandomState(seed)
        self.value = (rng.randn(*shape) * 0.01).astype(dtype)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.acc = np.zeros_like(self.value)
        self._mu = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._mu:
            return self.value.copy()

    def push(self, grad) -> None:
        g = np.asarray(grad, self.value.dtype)
        with self._mu:
            if self.optimizer == "adagrad":
                self.acc += g * g
                self.value -= self.lr * g / (np.sqrt(self.acc) + 1e-6)
            else:
                self.value -= self.lr * g


# ---------------------------------------------------------------------------
# server process side: module-level state + RPC-invokable functions
# ---------------------------------------------------------------------------

_TABLES: Dict[str, object] = {}


def _ps_create_sparse(name, dim, optimizer, lr, init_std, seed,
                      table_class="memory", cache_rows=100_000,
                      path=None):
    if name not in _TABLES:
        if table_class == "ssd":
            _TABLES[name] = SSDSparseTable(
                dim, optimizer=optimizer, lr=lr, init_std=init_std,
                seed=seed, cache_rows=cache_rows, path=path)
        else:
            _TABLES[name] = SparseTable(dim, optimizer=optimizer,
                                        lr=lr, init_std=init_std,
                                        seed=seed)
    return True


def _ps_create_dense(name, shape, optimizer, lr, seed):
    _TABLES.setdefault(name, DenseTable(shape, optimizer=optimizer,
                                        lr=lr, seed=seed))
    return True


def _ps_pull_sparse(name, ids):
    return _TABLES[name].pull(ids)


def _ps_push_sparse(name, ids, grads):
    _TABLES[name].push(ids, grads)
    return True


def _ps_pull_dense(name):
    return _TABLES[name].pull()


def _ps_push_dense(name, grad):
    _TABLES[name].push(grad)
    return True


def _ps_stat(name):
    t = _TABLES[name]
    return {"n_rows": t.n_rows()} if isinstance(t, SparseTable) \
        else {"shape": list(t.value.shape)}


def run_server(name, rank=None, world_size=None, master_endpoint=None):
    """Host PS tables in this process: join the RPC world and serve
    until ``stop_server`` (the reference's ``fleet.run_server()``)."""
    from .. import rpc
    rpc.init_rpc(name, rank=rank, world_size=world_size,
                 master_endpoint=master_endpoint)
    return name


def stop_server():
    from .. import rpc
    rpc.shutdown()


def _ps_account(op, table, rows, nbytes):
    """Push/pull volume counters (rows + payload bytes per table) in
    the metrics registry — the PS analogue of the collective census."""
    try:
        from ... import monitor as _monitor
        _monitor.counter("ps_ops", "PS push/pull calls",
                         labels=("op", "table")) \
            .labels(op=op, table=table).inc()
        _monitor.counter("ps_rows", "PS rows moved",
                         labels=("op", "table")) \
            .labels(op=op, table=table).inc(int(rows))
        _monitor.counter("ps_bytes", "PS payload bytes moved",
                         labels=("op", "table")) \
            .labels(op=op, table=table).inc(int(nbytes))
    except Exception:
        pass


class PSClient:
    """Worker-side facade: shards sparse ids across the server list by
    ``id % n_servers``; dense tables live on server 0."""

    def __init__(self, servers: List[str]):
        if not servers:
            raise ValueError("PSClient needs at least one server name")
        self.servers = list(servers)

    def _rpc(self, server, fn, *args):
        from .. import rpc
        return rpc.rpc_sync(server, fn, args=args)

    # -- table management -----------------------------------------------
    def create_sparse_table(self, name, dim, optimizer="sgd", lr=0.01,
                            init_std=0.01, table_class="memory",
                            cache_rows=100_000, path=None):
        """``table_class="ssd"`` selects the two-tier disk-spilling
        table (``SSDSparseTable``) on each server shard."""
        for k, s in enumerate(self.servers):
            # per-shard seed so shards don't repeat the same rows
            self._rpc(s, _ps_create_sparse, name, dim, optimizer, lr,
                      init_std, k, table_class, cache_rows,
                      None if path is None else f"{path}.shard{k}")
        self._dims = getattr(self, "_dims", {})
        self._dims[name] = int(dim)
        return name

    def create_dense_table(self, name, shape, optimizer="sgd", lr=0.01):
        self._rpc(self.servers[0], _ps_create_dense, name, list(shape),
                  optimizer, lr, 0)
        return name

    # -- sparse ---------------------------------------------------------
    def _shard(self, ids):
        ids = np.asarray(ids, np.int64).reshape(-1)
        n = len(self.servers)
        which = ids % n
        return ids, which

    def pull_sparse(self, name, ids) -> np.ndarray:
        from ...profiler import RecordEvent
        with RecordEvent("ps:pull_sparse"):
            ids, which = self._shard(ids)
            dim = getattr(self, "_dims", {}).get(name, 0)
            out = np.zeros((len(ids), dim), np.float32)
            for k, s in enumerate(self.servers):
                sel = np.nonzero(which == k)[0]
                if sel.size == 0:
                    continue
                rows = self._rpc(s, _ps_pull_sparse, name,
                                 ids[sel].tolist())
                if out.shape[1] != rows.shape[1] \
                        or out.dtype != rows.dtype:
                    out = np.zeros((len(ids), rows.shape[1]),
                                   rows.dtype)
                out[sel] = rows
        _ps_account("pull_sparse", name, len(ids), out.nbytes)
        return out

    def push_sparse(self, name, ids, grads) -> None:
        from ...profiler import RecordEvent
        with RecordEvent("ps:push_sparse"):
            ids, which = self._shard(ids)
            grads = np.asarray(grads)
            for k, s in enumerate(self.servers):
                sel = np.nonzero(which == k)[0]
                if sel.size:
                    self._rpc(s, _ps_push_sparse, name,
                              ids[sel].tolist(), grads[sel])
        _ps_account("push_sparse", name, len(ids), grads.nbytes)

    # -- dense ----------------------------------------------------------
    def pull_dense(self, name) -> np.ndarray:
        from ...profiler import RecordEvent
        with RecordEvent("ps:pull_dense"):
            out = self._rpc(self.servers[0], _ps_pull_dense, name)
        _ps_account("pull_dense", name, len(out), out.nbytes)
        return out

    def push_dense(self, name, grad) -> None:
        from ...profiler import RecordEvent
        grad = np.asarray(grad)
        with RecordEvent("ps:push_dense"):
            self._rpc(self.servers[0], _ps_push_dense, name, grad)
        _ps_account("push_dense", name, len(grad), grad.nbytes)

    def stat(self, name) -> dict:
        stats = [self._rpc(s, _ps_stat, name) for s in self.servers]
        if "n_rows" in stats[0]:
            return {"n_rows": builtins.sum(s["n_rows"] for s in stats)}
        return stats[0]


class DistributedEmbedding:
    """Embedding whose table lives on the PS (reference:
    ``paddle.static.nn.sparse_embedding`` / distributed lookup table).

    ``forward(ids)`` pulls the batch's rows into a local Tensor wired
    into the autograd tape; after ``loss.backward()``, call
    ``push_grads()`` to send the accumulated row gradients to the PS
    (async-SGD: the server applies its optimizer on arrival)."""

    def __init__(self, client: PSClient, name, dim, optimizer="sgd",
                 lr=0.01):
        self.client = client
        self.name = client.create_sparse_table(name, dim,
                                               optimizer=optimizer,
                                               lr=lr)
        self.dim = int(dim)
        self._pending = []   # [(unique_ids, local Tensor)]

    def forward(self, ids):
        from ...framework.core import Tensor
        import jax.numpy as jnp
        ids_np = np.asarray(
            ids.numpy() if hasattr(ids, "numpy") else ids, np.int64)
        flat = ids_np.reshape(-1)
        uniq, inverse = np.unique(flat, return_inverse=True)
        rows = self.client.pull_sparse(self.name, uniq)
        local = Tensor(jnp.asarray(rows))
        from ...framework.core import is_grad_enabled
        if is_grad_enabled():
            # training: remember the pulled rows until push_grads();
            # eval/no_grad pulls are not recorded (unbounded growth)
            local.stop_gradient = False
            self._pending.append((uniq, local))
            if len(self._pending) > 64:
                import warnings
                warnings.warn(
                    "DistributedEmbedding: %d pulled batches pending — "
                    "call push_grads() each step (flushing the oldest "
                    "to bound memory)" % len(self._pending))
                # push the oldest batch's gradient (if backward already
                # produced one) BEFORE dropping it, so bounding memory
                # never silently discards embedding updates
                uniq0, local0 = self._pending.pop(0)
                if local0.grad is not None:
                    self.client.push_sparse(
                        self.name, uniq0,
                        np.asarray(local0.grad.numpy()))
        from ...ops.manipulation import gather, reshape
        out = gather(local, Tensor(jnp.asarray(inverse)))
        return reshape(out, list(ids_np.shape) + [self.dim])

    __call__ = forward

    def push_grads(self):
        """Send grads of every pulled batch since the last push."""
        for uniq, local in self._pending:
            if local.grad is not None:
                self.client.push_sparse(self.name, uniq,
                                        np.asarray(local.grad.numpy()))
        self._pending.clear()
