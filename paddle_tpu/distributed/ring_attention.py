"""Ring (context-parallel) attention
(PaddleNLP ``paddlenlp/transformers/ring_flash_attention.py`` parity —
the reference lives out-of-tree; SURVEY.md §5.7 mechanism 3).

TPU-first: sequence is sharded over the ``sep`` mesh axis; KV blocks ride
a ``ppermute`` ring inside shard_map while each step folds a partial
attention into online-softmax accumulators (m, l, o). Causality is
handled per source-block: blocks strictly in the future are skipped via
masking, the diagonal block gets the triangular mask. Backward is
``jax.grad`` through the scan (ppermute transposes to the reverse ring).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..framework.core import Tensor, apply_jax, as_jax
from . import env as _env

__all__ = ["ring_flash_attention", "RingFlashAttention"]


def _block_attn(q, k, v, scale, mask=None):
    """One partial attention: returns (o_partial, m, l) for online
    softmax. q: [B, Lq, H, D]; k/v: [B, Lk, H, D]."""
    s = jnp.einsum("blhd,bmhd->bhlm", q, k) * scale
    if mask is not None:
        s = jnp.where(mask, s, -1e9)
    m = jnp.max(s, axis=-1)                       # [B, H, Lq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                       # [B, H, Lq]
    o = jnp.einsum("bhlm,bmhd->blhd", p, v)
    return o, m, l


def ring_flash_attention(q, k, v, mesh: Mesh = None, axis: str = "sep",
                         causal: bool = False, scale=None):
    """q/k/v: [B, L, H, D] with L globally sharded over ``axis``.
    Returns [B, L, H, D] with the same sharding."""
    mesh = mesh or _env.get_mesh()
    q_arr, k_arr, v_arr = as_jax(q), as_jax(k), as_jax(v)
    if scale is None:
        scale = 1.0 / np.sqrt(q_arr.shape[-1])
    scale = float(scale)  # keep weak-typed under x64
    from .shard_utils import in_manual_region
    sp = mesh.shape[axis] if mesh is not None else 1
    if mesh is None or sp <= 1 or in_manual_region():
        out = jax.nn.dot_product_attention(q_arr, k_arr, v_arr,
                                           is_causal=causal, scale=scale)
        return Tensor(out) if isinstance(q, Tensor) else out

    def per_device(ql, kl, vl):
        my = jax.lax.axis_index(axis)
        L = ql.shape[1]
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        rows = jnp.arange(L)[:, None]
        cols = jnp.arange(L)[None, :]

        def step(carry, t):
            kt, vt, o_acc, m_acc, l_acc = carry
            src = (my - t) % sp  # which global block this kv is
            if causal:
                tri = rows >= cols
                mask = jnp.where(src == my, tri,
                                 jnp.broadcast_to(src < my, tri.shape))
                mask = mask[None, None]
            else:
                mask = None
            o_p, m_p, l_p = _block_attn(ql, kt, vt, scale, mask)
            m_new = jnp.maximum(m_acc, m_p)
            alpha = jnp.exp(m_acc - m_new)
            beta = jnp.exp(m_p - m_new)
            l_new = l_acc * alpha + l_p * beta
            o_new = (o_acc * alpha.transpose(0, 2, 1)[..., None]
                     + o_p * beta.transpose(0, 2, 1)[..., None])
            kn = jax.lax.ppermute(kt, axis, perm)
            vn = jax.lax.ppermute(vt, axis, perm)
            return (kn, vn, o_new, m_new, l_new), None

        B, L_, H, D = ql.shape
        o0 = jnp.zeros_like(ql)
        m0 = jnp.full((B, H, L_), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((B, H, L_), jnp.float32)
        (k_f, v_f, o, m, l), _ = jax.lax.scan(
            step, (kl, vl, o0, m0.astype(ql.dtype),
                   l0.astype(ql.dtype)), jnp.arange(sp))
        return o / jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]

    from .shard_utils import shard_map_compat
    spec = P(None, axis, None, None)
    mapped = shard_map_compat(per_device, mesh, (spec, spec, spec), spec)

    def f(qa, ka, va):
        return mapped(qa, ka, va)

    if isinstance(q, Tensor):
        return apply_jax("ring_flash_attention", f, q, k, v)
    return mapped(q_arr, k_arr, v_arr)


class RingFlashAttention:
    """Class facade matching PaddleNLP's RingFlashAttention.apply."""

    @staticmethod
    def apply(q, k, v, group=None, causal=False, **kw):
        axis = getattr(group, "axis_name", None) or "sep"
        return ring_flash_attention(q, k, v, axis=axis, causal=causal)
