"""Ring (context-parallel) attention
(PaddleNLP ``paddlenlp/transformers/ring_flash_attention.py`` parity —
the reference lives out-of-tree; SURVEY.md §5.7 mechanism 3).

TPU-first: sequence is sharded over the ``sep`` mesh axis; KV blocks ride
a ``ppermute`` ring inside shard_map while each step folds a partial
attention into online-softmax accumulators (m, l, o), kept in fp32 until
the final normalization. Backward is ``jax.grad`` through the scan
(ppermute transposes to the reverse ring).

Causal efficiency:
- future KV blocks are skipped with ``lax.cond`` (no FLOPs — not
  computed-then-masked);
- ``balance=True`` (default for causal) uses the ZIGZAG layout: the
  global sequence is split into 2*sp chunks and device d holds chunks
  (d, 2sp-1-d), so every device does the same amount of causal work
  instead of device 0 idling while device sp-1 computes sp blocks. The
  contiguous->zigzag resharding is two ppermutes on entry and exit —
  callers keep the ordinary contiguous seq sharding.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..framework.core import Tensor, apply_jax, as_jax
from . import env as _env

__all__ = ["ring_flash_attention", "RingFlashAttention"]


def _block_attn_f32(q, k, v, scale, mask=None):
    """One partial attention in fp32: returns (o_partial, m, l).
    q: [B, Lq, H, D]; k/v: [B, Lk, H, D]."""
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    s = jnp.einsum("blhd,bmhd->bhlm", qf, kf) * scale
    if mask is not None:
        s = jnp.where(mask, s, jnp.float32(-1e30))
    m = jnp.max(s, axis=-1)                       # [B, H, Lq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                       # [B, H, Lq]
    o = jnp.einsum("bhlm,bmhd->blhd", p, v.astype(jnp.float32))
    return o, m, l


def _merge(o_acc, m_acc, l_acc, o_p, m_p, l_p):
    """Fold a partial (o_p, m_p, l_p) into fp32 online-softmax state."""
    m_new = jnp.maximum(m_acc, m_p)
    alpha = jnp.exp(m_acc - m_new)
    beta = jnp.exp(m_p - m_new)
    l_new = l_acc * alpha + l_p * beta
    o_new = (o_acc * alpha.transpose(0, 2, 1)[..., None]
             + o_p * beta.transpose(0, 2, 1)[..., None])
    return o_new, m_new, l_new


NEG_INF = np.float32(-1e30)  # finite: exp(m_p - m_acc) of two empty
# online-softmax states must be exp(0)=1, not exp(-inf + inf)=NaN


def _zeros_state(B, L, H, D):
    return (jnp.zeros((B, L, H, D), jnp.float32),
            jnp.full((B, H, L), NEG_INF, jnp.float32),
            jnp.zeros((B, H, L), jnp.float32))


def _finalize(o, m, l, dtype):
    out = o / jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]
    return out.astype(dtype)


def ring_flash_attention(q, k, v, mesh: Mesh = None, axis: str = "sep",
                         causal: bool = False, scale=None,
                         balance: bool = True):
    """q/k/v: [B, L, H, D] with L globally sharded over ``axis``.
    Returns [B, L, H, D] with the same sharding."""
    mesh = mesh or _env.get_mesh()
    q_arr, k_arr, v_arr = as_jax(q), as_jax(k), as_jax(v)
    if scale is None:
        scale = 1.0 / np.sqrt(q_arr.shape[-1])
    scale = float(scale)  # keep weak-typed under x64
    from .shard_utils import in_manual_region
    sp = mesh.shape[axis] if mesh is not None else 1
    if mesh is None or sp <= 1 or in_manual_region():
        out = jax.nn.dot_product_attention(q_arr, k_arr, v_arr,
                                           is_causal=causal, scale=scale)
        return Tensor(out) if isinstance(q, Tensor) else out

    if causal and balance and q_arr.shape[1] % (2 * sp) == 0:
        per_device = functools.partial(_ring_zigzag, axis=axis, sp=sp,
                                       scale=scale)
    else:
        per_device = functools.partial(_ring_contiguous, axis=axis,
                                       sp=sp, scale=scale, causal=causal)

    from .shard_utils import shard_map_compat
    spec = P(None, axis, None, None)
    mapped = shard_map_compat(per_device, mesh, (spec, spec, spec), spec)

    if isinstance(q, Tensor):
        return apply_jax("ring_flash_attention", mapped, q, k, v)
    return mapped(q_arr, k_arr, v_arr)


def _ring_contiguous(ql, kl, vl, *, axis, sp, scale, causal):
    """Plain ring over the contiguous seq layout. Future blocks are
    skipped with lax.cond (zero FLOPs), the diagonal applies the
    triangular mask; non-causal computes every block."""
    my = jax.lax.axis_index(axis)
    B, L, H, D = ql.shape
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    rows = jnp.arange(L)[:, None]
    cols = jnp.arange(L)[None, :]
    tri = (rows >= cols)[None, None]

    def step(carry, t):
        kt, vt, o_acc, m_acc, l_acc = carry
        src = (my - t) % sp  # which global block this kv is

        def diag(_):
            return _block_attn_f32(ql, kt, vt, scale, tri)

        def full(_):
            return _block_attn_f32(ql, kt, vt, scale, None)

        def skip(_):
            return (jnp.zeros((B, L, H, D), jnp.float32),
                    jnp.full((B, H, L), NEG_INF, jnp.float32),
                    jnp.zeros((B, H, L), jnp.float32))

        if causal:
            # 0: past (full), 1: diagonal, 2: future (skip)
            sel = jnp.int32(0) + (src == my) + 2 * (src > my)
            o_p, m_p, l_p = jax.lax.switch(sel, [full, diag, skip], None)
        else:
            o_p, m_p, l_p = full(None)
        o_new, m_new, l_new = _merge(o_acc, m_acc, l_acc, o_p, m_p, l_p)
        kn = jax.lax.ppermute(kt, axis, perm)
        vn = jax.lax.ppermute(vt, axis, perm)
        return (kn, vn, o_new, m_new, l_new), None

    o0, m0, l0 = _zeros_state(B, L, H, D)
    (_, _, o, m, l), _ = jax.lax.scan(
        step, (kl, vl, o0, m0, l0), jnp.arange(sp))
    return _finalize(o, m, l, ql.dtype)


def _zigzag_perms(sp):
    """ppermute tables: contiguous half h of device d is global chunk
    c=2d+h; zigzag owner of chunk c is c if c<sp else 2sp-1-c."""
    fwd0, fwd1 = [], []
    for d in range(sp):
        for h, table in ((0, fwd0), (1, fwd1)):
            c = 2 * d + h
            t = c if c < sp else 2 * sp - 1 - c
            table.append((d, t))
    # inverse: zigzag device d holds chunks (d, 2sp-1-d); owner of
    # chunk c in contiguous layout is c//2, half c%2
    inv0 = [(t, d) for d, t in fwd0]
    inv1 = [(t, d) for d, t in fwd1]
    return fwd0, fwd1, inv0, inv1


def _ring_zigzag(ql, kl, vl, *, axis, sp, scale):
    """Causal ring on the zigzag layout: device d computes against KV
    chunk pairs from each source with per-chunk full/diag/skip selection
    — every device does equal work. Entry/exit reshards contiguous <->
    zigzag with two ppermutes each way."""
    my = jax.lax.axis_index(axis)
    B, L, H, D = ql.shape
    Lh = L // 2
    fwd0, fwd1, inv0, inv1 = _zigzag_perms(sp)

    def to_zigzag(x):
        lo, hi = x[:, :Lh], x[:, Lh:]
        a = jax.lax.ppermute(lo, axis, fwd0)   # -> chunk (my) owner
        b = jax.lax.ppermute(hi, axis, fwd1)   # -> chunk (2sp-1-my)
        return a, b

    def from_zigzag(a, b):
        lo = jax.lax.ppermute(a, axis, inv0)
        hi = jax.lax.ppermute(b, axis, inv1)
        return jnp.concatenate([lo, hi], axis=1)

    qa, qb = to_zigzag(ql)     # my global chunks: a=my, b=2sp-1-my
    ka, kb = to_zigzag(kl)
    va, vb = to_zigzag(vl)

    rows = jnp.arange(Lh)[:, None]
    cols = jnp.arange(Lh)[None, :]
    tri = (rows >= cols)[None, None]

    def pair(qc, q_chunk, kt, vt, k_chunk):
        """Attend one q chunk against one kv chunk by causal relation
        (global chunk ids are traced scalars)."""

        def full(_):
            return _block_attn_f32(qc, kt, vt, scale, None)

        def diag(_):
            return _block_attn_f32(qc, kt, vt, scale, tri)

        def skip(_):
            return (jnp.zeros((B, Lh, H, D), jnp.float32),
                    jnp.full((B, H, Lh), NEG_INF, jnp.float32),
                    jnp.zeros((B, H, Lh), jnp.float32))

        sel = jnp.int32(0) + (k_chunk == q_chunk) + \
            2 * (k_chunk > q_chunk)
        return jax.lax.switch(sel, [full, diag, skip], None)

    # device d owns chunks {d, 2sp-1-d}; fwd0 carries EVEN global
    # chunks and fwd1 ODD ones, and d / 2sp-1-d have opposite parity —
    # so which of the pair landed in slot a/b depends on d's parity
    def owned_chunks(d):
        even = jnp.where(d % 2 == 0, d, 2 * sp - 1 - d)
        odd = jnp.where(d % 2 == 1, d, 2 * sp - 1 - d)
        return even, odd

    chunk_a, chunk_b = owned_chunks(my)

    def step(carry, t):
        (kta, vta, ktb, vtb, oa, ma, la, ob, mb, lb) = carry
        src = (my - t) % sp
        src_a, src_b = owned_chunks(src)  # kv chunk ids on the ring
        for (kt, vt, kc) in ((kta, vta, src_a), (ktb, vtb, src_b)):
            o_p, m_p, l_p = pair(qa, chunk_a, kt, vt, kc)
            oa, ma, la = _merge(oa, ma, la, o_p, m_p, l_p)
            o_p, m_p, l_p = pair(qb, chunk_b, kt, vt, kc)
            ob, mb, lb = _merge(ob, mb, lb, o_p, m_p, l_p)
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        kta = jax.lax.ppermute(kta, axis, perm)
        vta = jax.lax.ppermute(vta, axis, perm)
        ktb = jax.lax.ppermute(ktb, axis, perm)
        vtb = jax.lax.ppermute(vtb, axis, perm)
        return (kta, vta, ktb, vtb, oa, ma, la, ob, mb, lb), None

    oa0, ma0, la0 = _zeros_state(B, Lh, H, D)
    ob0, mb0, lb0 = _zeros_state(B, Lh, H, D)
    carry = (ka, va, kb, vb, oa0, ma0, la0, ob0, mb0, lb0)
    (_, _, _, _, oa, ma, la, ob, mb, lb), _ = jax.lax.scan(
        step, carry, jnp.arange(sp))
    out_a = _finalize(oa, ma, la, ql.dtype)
    out_b = _finalize(ob, mb, lb, ql.dtype)
    return from_zigzag(out_a, out_b)


class RingFlashAttention:
    """Class facade matching PaddleNLP's RingFlashAttention.apply."""

    @staticmethod
    def apply(q, k, v, group=None, causal=False, **kw):
        axis = getattr(group, "axis_name", None) or "sep"
        return ring_flash_attention(q, k, v, axis=axis, causal=causal)
