"""``python -m paddle_tpu.distributed.launch`` (``python/paddle/
distributed/launch/`` parity).

The reference spawns one process per GPU with PADDLE_TRAINER_* env and an
HTTP/etcd master. Single-controller jax on TPU usually wants ONE process
per host seeing all local chips, so the default is nprocs=1 with the env
set for rank bookkeeping; ``--nproc_per_node`` > 1 spawns the reference's
multi-process layout for emulation/tests (each proc gets the same device
view; collectives still run via the mesh).
"""
from __future__ import annotations

import os
import subprocess
import sys


def parse_args(argv):
    import argparse
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--devices", "--gpus", "--xpus", default=None,
                   dest="devices")
    p.add_argument("--nnodes", default="1",
                   help="node count, or 'min:max' for elastic range")
    p.add_argument("--ips", default=None,
                   help="comma-separated host list for multi-node; "
                        "this node's position = --rank (or inferred "
                        "from the local hostname/IP)")
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--master", default=None)
    p.add_argument("--rank", type=int, default=-1,
                   help="node rank among --ips (-1: infer)")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--job_id", default="default")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic: relaunch the pod this many times "
                        "after a worker failure (checkpoint-resume is "
                        "the training script's job)")
    p.add_argument("--elastic_level", type=int, default=0,
                   help=">0 enables heartbeat hang-detection: workers "
                        "register with the controller's TCPStore and a "
                        "rank whose heartbeat stops (hung, not just "
                        "exited) triggers pod restart")
    p.add_argument("--elastic_timeout", type=float, default=30.0,
                   help="seconds without a heartbeat before a rank is "
                        "declared dead (with --elastic_level > 0)")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs="...")
    return p.parse_args(argv)


def _node_layout(args, nprocs):
    """(hosts, node_rank, master): the multi-node topology. Single-node
    default is localhost; with --ips the reference semantics apply —
    node 0's address hosts the master, global trainer ids are
    node_rank*nprocs + local_rank."""
    import socket
    if not args.ips:
        return ["127.0.0.1"], 0, args.master or "127.0.0.1:6170"
    hosts = [h.strip() for h in args.ips.split(",") if h.strip()]
    node_rank = args.rank
    if node_rank < 0:
        me = {socket.gethostname(), "127.0.0.1", "localhost"}
        try:
            me.add(socket.gethostbyname(socket.gethostname()))
        except OSError:
            pass
        matches = [i for i, h in enumerate(hosts) if h in me]
        if len(matches) != 1:
            raise SystemExit(
                f"launch: cannot infer this node's rank among "
                f"--ips {hosts}; pass --rank explicitly")
        node_rank = matches[0]
    master = args.master or f"{hosts[0]}:6170"
    return hosts, node_rank, master


def _spawn_pod(args, nprocs, attempt, elastic_port=None):
    """Start one process per LOCAL rank; returns [(Popen, log_file)].
    Multi-node: global ids/endpoints span every host in --ips."""
    hosts, node_rank, master = _node_layout(args, nprocs)
    endpoints = ",".join(f"{h}:{6170 + i}" for h in hosts
                         for i in range(nprocs))
    world = len(hosts) * nprocs
    procs = []
    for rank in range(nprocs):
        global_rank = node_rank * nprocs + rank
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(global_rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT":
                f"{hosts[node_rank]}:{6170 + rank}",
            "PADDLE_MASTER": master,
            "PADDLE_NODE_RANK": str(node_rank),
            "PADDLE_RESTART_ATTEMPT": str(attempt),
            "PADDLE_LOG_DIR": args.log_dir,
            "FLAGS_selected_gpus": str(rank),
        })
        if elastic_port is not None:
            env.update({
                "PADDLE_ELASTIC_ENABLE": "1",
                "PADDLE_ELASTIC_PORT": str(elastic_port),
                "PADDLE_ELASTIC_EXTERNAL": "1",  # controller owns store
            })
        suffix = f".{attempt}" if attempt else ""
        log = open(os.path.join(args.log_dir,
                                f"workerlog.{rank}{suffix}"), "w")
        cmd = [sys.executable, args.training_script] + \
            list(args.training_script_args)
        procs.append((subprocess.Popen(
            cmd, env=env,
            stdout=log if rank != 0 else None,
            stderr=subprocess.STDOUT if rank != 0 else None), log))
    return procs


def _watch_pod(procs, poll_s=0.2, watcher=None, register_deadline=120.0):
    """Reference controller watch loop: poll children; on the FIRST
    non-zero exit kill the whole pod (a half-dead mesh cannot make
    progress) and report failure. With an ElasticManager ``watcher``,
    a hung rank also fails the pod — whether it hung after starting
    (beat went stale) or during startup (never registered within
    ``register_deadline`` seconds). Returns 0 when all exit clean."""
    import time
    live = list(procs)
    failed = 0
    t0 = time.monotonic()
    while live and not failed:
        time.sleep(poll_s)
        for p, _log in list(live):
            rc = p.poll()
            if rc is None:
                continue
            live.remove((p, _log))
            if rc != 0:
                failed = rc
                break
        if not failed and watcher is not None and live:
            polled = watcher.poll()  # ONE store sweep per tick
            if polled["dead"]:
                print("[launch] heartbeat lost for ranks "
                      f"{polled['dead']}; failing the pod",
                      file=sys.stderr)
                failed = 1
            elif polled["pending"] and \
                    time.monotonic() - t0 > register_deadline:
                print("[launch] ranks never registered within "
                      f"{register_deadline}s: {polled['pending']}; "
                      "failing the pod", file=sys.stderr)
                failed = 1
    if failed:
        for p, _log in live:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + 10
        for p, _log in live:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
    for _p, log in procs:
        log.close()
    return failed


def launch(argv=None):
    args = parse_args(argv if argv is not None else sys.argv[1:])
    nprocs = args.nproc_per_node or 1
    os.makedirs(args.log_dir, exist_ok=True)
    watcher = None
    elastic_port = None
    if args.elastic_level:
        if args.ips:
            # per-node watchers would poll GLOBAL ranks that register
            # on other nodes and kill healthy jobs; multi-node hang
            # detection needs the (future) cross-node master —
            # exit-code watching and --max_restarts still apply
            print("[launch] --elastic_level heartbeat watch is "
                  "single-node only; multi-node runs keep exit-code "
                  "watching", file=sys.stderr)
        else:
            from ..fleet.elastic import ElasticManager
            # controller hosts the liveness store; workers only connect
            watcher = ElasticManager(port=0, world_size=nprocs,
                                     is_master=True,
                                     timeout=args.elastic_timeout)
            elastic_port = watcher.port
    attempt = 0
    while True:
        procs = _spawn_pod(args, nprocs, attempt,
                           elastic_port=elastic_port)
        code = _watch_pod(procs, watcher=watcher,
                          register_deadline=max(
                              60.0, 10 * args.elastic_timeout))
        if code == 0:
            return
        if attempt >= args.max_restarts:
            raise SystemExit(code)
        attempt += 1
        if watcher is not None:
            watcher.reset()  # stale beats must not mask the next pod
        print(f"[launch] pod failed (rc={code}); elastic restart "
              f"{attempt}/{args.max_restarts}", file=sys.stderr)


def main():
    launch()
