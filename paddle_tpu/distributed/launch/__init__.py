"""``python -m paddle_tpu.distributed.launch`` (``python/paddle/
distributed/launch/`` parity).

The reference spawns one process per GPU with PADDLE_TRAINER_* env and an
HTTP/etcd master. Single-controller jax on TPU usually wants ONE process
per host seeing all local chips, so the default is nprocs=1 with the env
set for rank bookkeeping; ``--nproc_per_node`` > 1 spawns the reference's
multi-process layout for emulation/tests (each proc gets the same device
view; collectives still run via the mesh).
"""
from __future__ import annotations

import os
import subprocess
import sys


def parse_args(argv):
    import argparse
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--devices", "--gpus", "--xpus", default=None,
                   dest="devices")
    p.add_argument("--nnodes", default="1")
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--master", default=None)
    p.add_argument("--rank", type=int, default=0)
    p.add_argument("--log_dir", default="log")
    p.add_argument("--job_id", default="default")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs="...")
    return p.parse_args(argv)


def launch(argv=None):
    args = parse_args(argv if argv is not None else sys.argv[1:])
    nprocs = args.nproc_per_node or 1
    os.makedirs(args.log_dir, exist_ok=True)
    endpoints = ",".join(f"127.0.0.1:{6170 + i}" for i in range(nprocs))
    procs = []
    for rank in range(nprocs):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT": f"127.0.0.1:{6170 + rank}",
            "PADDLE_MASTER": args.master or "127.0.0.1:6170",
            "FLAGS_selected_gpus": str(rank),
        })
        log = open(os.path.join(args.log_dir,
                                f"workerlog.{rank}"), "w")
        cmd = [sys.executable, args.training_script] + \
            list(args.training_script_args)
        procs.append((subprocess.Popen(
            cmd, env=env,
            stdout=log if rank != 0 else None,
            stderr=subprocess.STDOUT if rank != 0 else None), log))
    code = 0
    for p, log in procs:
        rc = p.wait()
        log.close()
        code = code or rc
    if code:
        raise SystemExit(code)


def main():
    launch()
