from . import main

main()
