"""hapi callbacks (``python/paddle/hapi/callbacks.py`` parity)."""
from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping", "VisualDL",
           "LRScheduler", "CallbackList"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for cb in self.callbacks:
                    getattr(cb, name)(*args, **kwargs)
            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._epoch_t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            logs = logs or {}
            items = ", ".join(
                f"{k}: {self._fmt(v)}" for k, v in logs.items())
            total = f"/{self.steps}" if self.steps else ""
            print(f"Epoch {self.epoch + 1}/{self.epochs} "
                  f"step {step}{total} - {items}", flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._epoch_t0
            logs = logs or {}
            items = ", ".join(
                f"{k}: {self._fmt(v)}" for k, v in logs.items())
            print(f"Epoch {epoch + 1} done ({dt:.1f}s) - {items}",
                  flush=True)

    def on_eval_end(self, logs=None):
        if self.verbose:
            logs = logs or {}
            items = ", ".join(
                f"{k}: {self._fmt(v)}" for k, v in logs.items())
            print(f"Eval - {items}", flush=True)

    @staticmethod
    def _fmt(v):
        if isinstance(v, (list, tuple, np.ndarray)):
            return "[" + ", ".join(f"{float(x):.4f}" for x in
                                   np.ravel(v)) + "]"
        try:
            return f"{float(v):.4f}"
        except (TypeError, ValueError):
            return str(v)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.monitor_op = np.greater
            self.min_delta *= 1
        else:
            self.monitor_op = np.less
            self.min_delta *= -1
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            return
        if isinstance(current, (list, tuple, np.ndarray)):
            current = float(np.ravel(current)[0])
        if self.best is None or self.monitor_op(
                current - self.min_delta, self.best):
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None) if opt else None
        from ..optimizer.lr import LRScheduler as Sched
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class VisualDL(Callback):
    """``paddle.callbacks.VisualDL`` parity. The VisualDL service is a
    CUDA-ecosystem web app not present here; the callback keeps the
    same constructor/metric contract and writes scalar logs as JSONL
    (one record per logged step) plus, when torch's TensorBoard writer
    (``torch.utils.tensorboard``) is importable — torch is part of this
    image — TensorBoard event files; both consumable by standard
    dashboards."""

    def __init__(self, log_dir):
        self.log_dir = log_dir
        self._writer = None
        self._jsonl = None
        self._step = {"train": 0, "eval": 0}

    def _ensure(self):
        if self._jsonl is None:
            os.makedirs(self.log_dir, exist_ok=True)
            self._jsonl = open(os.path.join(self.log_dir,
                                            "scalars.jsonl"), "a")
            try:
                from torch.utils.tensorboard import SummaryWriter
                self._writer = SummaryWriter(self.log_dir)
            except Exception:
                self._writer = None

    def _log(self, mode, logs):
        import json as _json
        self._ensure()
        step = self._step[mode]
        record = {"mode": mode, "step": step}
        for k, v in (logs or {}).items():
            try:
                record[k] = float(np.asarray(v).reshape(-1)[0])
            except (TypeError, ValueError):
                continue
            if self._writer is not None:
                self._writer.add_scalar(f"{mode}/{k}", record[k], step)
        self._jsonl.write(_json.dumps(record) + "\n")
        self._jsonl.flush()
        self._step[mode] += 1

    def on_train_batch_end(self, step, logs=None):
        self._log("train", logs)

    def on_eval_end(self, logs=None):
        self._log("eval", logs)

    def on_train_end(self, logs=None):
        if self._writer is not None:
            self._writer.close()
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
